"""Amorphous-plasticity set-transformer workload — the north-star run.

Scriptable equivalent of the reference's amorphous notebook
(``complex_systems/InfoDecomp_Amorphous_plasticity_per_particle_measurements_
and_set_transformer.ipynb``), cell 8:

  - per-particle DIB (shared Gaussian encoder, KL summed over latent dims and
    particles) + set-transformer aggregator
    (:class:`~dib_tpu.models.per_particle.PerParticleDIBModel`);
  - 25k steps, batch 32 neighborhoods x 50 particles, per-step beta log-ramp
    2e-6 -> 2e-1, linear LR warmup;
  - per-particle MI sandwich bounds every ``eval_every`` steps (cell 5's
    ``compute_infos_mus_logvars`` — here the standard ``InfoPerFeatureHook``);
  - probe-grid information maps every ``probe_every`` steps: a grid of
    phantom particles of each type scored against a bank of real data
    particles with the asymmetric M x N sandwich bounds
    (:func:`~dib_tpu.ops.info_bounds.mi_sandwich_probe`), masked where the
    pair-correlation density g(r) vanishes (the excluded-volume core);
  - the distributed info plane: task loss vs transmitted information, with
    per-particle curves (rendered by ``dib_tpu.viz``).

The sweep driver (:func:`run_amorphous_sweep`) is the BASELINE.json north
star: the whole configuration swept over a grid of beta endpoints (and/or
seed repeats) as ONE jitted program on a ``(beta, data)`` mesh.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dib_tpu.data.amorphous import per_particle_features
from dib_tpu.data.registry import get_dataset
from dib_tpu.models.per_particle import PerParticleDIBModel
from dib_tpu.ops.entropy import LN2, sequence_entropy_bits
from dib_tpu.ops.info_bounds import mi_sandwich_probe
from dib_tpu.parallel.mesh import make_sweep_mesh
from dib_tpu.utils.profiling import PhaseTimer
from dib_tpu.parallel.sweep import BetaSweepTrainer, PerReplicaHook
from dib_tpu.train.hooks import Every, InfoPerFeatureHook
from dib_tpu.train.loop import DIBTrainer, TrainConfig
from dib_tpu.viz.info_plane import save_distributed_info_plane
from dib_tpu.viz.probe_maps import density_mask, save_info_maps

Array = jax.Array


@dataclass(frozen=True)
class AmorphousWorkloadConfig:
    """Amorphous notebook cell 8 defaults."""

    learning_rate: float = 1e-4
    batch_size: int = 32
    num_steps: int = 25_000
    beta_start: float = 2e-6
    beta_end: float = 2e-1
    warmup_steps: int = 500
    eval_every: int = 250             # MI bounds cadence
    probe_every: int = 1000           # info-map cadence (0 -> off)
    number_particles: int = 50
    grid_side: int = 100              # probe grid resolution
    grid_extent: float = 8.0          # probe positions span [-extent, extent]^2
    probe_data_batch: int = 512       # real-particle bank per bound evaluation
    mi_eval_batch_size: int = 1024
    mi_eval_batches: int = 4

    def train_config(self, steps_per_epoch: int = 1) -> TrainConfig:
        """As a TrainConfig with epoch == ``steps_per_epoch`` train steps.

        With the default 1 the beta ramp advances per STEP, exactly the
        notebook's schedule; the sweep/bench drivers use coarser epochs to
        amortize host re-entry."""
        return TrainConfig(
            learning_rate=self.learning_rate,
            batch_size=self.batch_size,
            beta_start=self.beta_start,
            beta_end=self.beta_end,
            num_pretraining_epochs=0,
            num_annealing_epochs=self.num_steps // steps_per_epoch,
            steps_per_epoch=steps_per_epoch,
            warmup_steps=self.warmup_steps,
            max_val_points=1024,
        )


def build_model(config: AmorphousWorkloadConfig, **overrides) -> PerParticleDIBModel:
    """The full paper architecture (amorphous notebook cell 8); ``overrides``
    shrink it for tests/smoke runs."""
    return PerParticleDIBModel(num_particles=config.number_particles, **overrides)


# ---------------------------------------------------------------- probe grids

def probe_grid_positions(grid_side: int, extent: float) -> np.ndarray:
    """[G*G, 2] xy positions of the phantom-particle grid."""
    axis = np.linspace(-extent, extent, grid_side, dtype=np.float32)
    xx, yy = np.meshgrid(axis, axis)
    return np.stack([xx.ravel(), yy.ravel()], axis=-1)


def probe_features_for_type(positions: np.ndarray, type_id: int) -> np.ndarray:
    """[M, 12] engineered features of phantom particles of one type."""
    types = np.full(positions.shape[0], type_id, dtype=np.int32)
    return per_particle_features(positions, types, number_particles_to_use=-1)


def pair_correlation(
    sets: np.ndarray, num_bins: int = 64, max_radius: float | None = None
) -> tuple[np.ndarray, np.ndarray]:
    """Radial pair-correlation histogram g(r) of real particles around the
    central site, from [N, P, 12] feature sets (radius is feature column 4).

    Normalized by the annulus area so empty excluded-volume bins read 0 — the
    quantity the reference masks probe maps with (amorphous notebook cell 8).
    Returns (g_r [num_bins], bin_edges [num_bins + 1]).
    """
    radii = np.asarray(sets)[..., 4].ravel()
    radii = radii[radii > 0]          # zero-padded slots sit at the origin
    if max_radius is None:
        max_radius = float(radii.max())
    hist, edges = np.histogram(radii, bins=num_bins, range=(0.0, max_radius))
    areas = np.pi * (edges[1:] ** 2 - edges[:-1] ** 2)
    g_r = hist / (areas * max(len(radii), 1))
    return g_r, edges


def probe_info_maps(
    model: PerParticleDIBModel,
    params,
    data_particles: np.ndarray,
    key: Array,
    config: AmorphousWorkloadConfig,
    mesh=None,
) -> list[np.ndarray]:
    """[G, G, 2] (lower, upper) info grids in nats, one per particle type.

    Parity: amorphous notebook cell 8 — asymmetric M-probe x N-data bounds
    with the shared particle encoder. With ``mesh``, the probe grid (the
    heaviest beta-checkpoint instrumentation: grid_side^2 phantom particles)
    shards over the mesh's trailing axis via
    :func:`dib_tpu.parallel.context.sharded_probe_bounds`.
    """
    positions = probe_grid_positions(config.grid_side, config.grid_extent)
    k_bank, k_type1, k_type2 = jax.random.split(key, 3)
    idx = jax.random.randint(
        k_bank, (config.probe_data_batch,), 0, data_particles.shape[0]
    )
    bank = jnp.asarray(data_particles)[idx]
    data_mus, data_logvars = model.encode_feature(params, 0, bank)

    grids = []
    for type_id, k in ((1, k_type1), (2, k_type2)):
        feats = jnp.asarray(probe_features_for_type(positions, type_id))
        probe_mus, probe_logvars = model.encode_feature(params, 0, feats)
        if mesh is not None:
            from dib_tpu.parallel.context import sharded_probe_bounds

            lower, upper = sharded_probe_bounds(
                k, probe_mus, probe_logvars, data_mus, data_logvars,
                mesh, axis=mesh.axis_names[-1],
            )
        else:
            lower, upper = mi_sandwich_probe(
                k, probe_mus, probe_logvars, data_mus, data_logvars
            )
        grid = np.stack([np.asarray(lower), np.asarray(upper)], axis=-1)
        grids.append(grid.reshape(config.grid_side, config.grid_side, 2))
    return grids


class ProbeGridHook:
    """Saves per-type probe-grid information maps at each invocation.

    The g(r) density mask is computed once from the training sets; maps are
    written as ``info_map_step{N}.png`` (amorphous notebook cell 8's
    every-1000-steps rendering).
    """

    def __init__(
        self,
        outdir: str,
        model: PerParticleDIBModel,
        sets_train: np.ndarray,
        config: AmorphousWorkloadConfig,
        seed: int = 0,
        mesh=None,   # shard the probe grid over this mesh's trailing axis
    ):
        self.outdir = outdir
        self.model = model
        self.config = config
        self.mesh = mesh
        os.makedirs(outdir, exist_ok=True)
        self.key = jax.random.key(seed)
        # flat bank of real per-particle features for the data side
        self.data_particles = np.asarray(sets_train).reshape(-1, sets_train.shape[-1])
        g_r, edges = pair_correlation(sets_train)
        mask = density_mask(
            probe_grid_positions(config.grid_side, config.grid_extent),
            g_r, edges[1:], config.grid_side,
        )
        self.masks = [mask, mask]
        self.grids_by_step: dict[int, list[np.ndarray]] = {}

    def __call__(self, trainer, state, epoch: int):
        self.key, k = jax.random.split(self.key)
        params = state.params["model"] if "model" in state.params else state.params
        grids = probe_info_maps(
            self.model, params, self.data_particles, k, self.config,
            mesh=self.mesh,
        )
        self.grids_by_step[epoch] = grids
        save_info_maps(
            grids,
            os.path.join(self.outdir, f"info_map_step{epoch}.png"),
            masks=self.masks,
            titles=["type A", "type B"],
        )


# ------------------------------------------------------------------- drivers

def run_amorphous_workload(
    key: Array | int = 0,
    config: AmorphousWorkloadConfig | None = None,
    outdir: str = "./amorphous_out",
    steps_per_epoch: int = 1,
    probe_maps: bool = True,
    model_overrides: dict | None = None,
    probe_mesh=None,
    **fetch_kwargs,
) -> dict:
    """Single-schedule end-to-end run (one protocol, one beta ramp).

    Returns the trained state, history (bits), MI-bound trajectory, probe-map
    grids, and artifact paths. ``probe_mesh`` shards the probe-grid
    evaluation (the heaviest checkpoint instrumentation) over a device mesh.
    """
    config = config or AmorphousWorkloadConfig()
    if isinstance(key, int):
        key = jax.random.key(key)
    bundle = get_dataset("amorphous_particles",
                         number_particles_to_use=config.number_particles,
                         **fetch_kwargs)
    model = build_model(config, **(model_overrides or {}))
    trainer = DIBTrainer(model, bundle, config.train_config(steps_per_epoch))

    info_hook = InfoPerFeatureHook(
        config.mi_eval_batch_size, config.mi_eval_batches
    )
    cadences = [max(config.eval_every // steps_per_epoch, 1)]
    hooks = [Every(cadences[0], info_hook)]
    probe_hook = None
    if probe_maps and config.probe_every:
        probe_hook = ProbeGridHook(
            outdir, model, bundle.extras["sets_train"], config, mesh=probe_mesh
        )
        cadences.append(max(config.probe_every // steps_per_epoch, 1))
        hooks.append(Every(cadences[-1], probe_hook))
    hook_every = int(np.gcd.reduce(cadences))

    state, history = trainer.fit(key, hooks=hooks, hook_every=hook_every)
    bits = history.to_bits()
    entropy_y = sequence_entropy_bits(bundle.y_train.reshape(-1))
    plane_path = save_distributed_info_plane(
        bits.kl_per_feature, bits.loss, outdir,
        entropy_y=entropy_y, info_plot_lims=(0.0, float(bits.total_kl.max()) + 1.0),
    )
    return {
        "state": state,
        "history": bits,
        "bundle": bundle,
        "entropy_y_bits": entropy_y,
        "mi_bounds_bits": info_hook.bounds_bits,     # [T, P, 2]
        "mi_epochs": info_hook.epochs,
        "probe_grids": probe_hook.grids_by_step if probe_hook else {},
        "info_plane_path": plane_path,
    }


def run_amorphous_sweep(
    key: Array | int = 0,
    config: AmorphousWorkloadConfig | None = None,
    beta_ends: Sequence[float] | None = None,
    num_repeats: int = 1,
    outdir: str = "./amorphous_sweep_out",
    steps_per_epoch: int = 50,
    mesh=None,
    use_mesh: bool = True,
    model_overrides: dict | None = None,
    hooks=(),
    chunk_epochs: int = 25,
    checkpoint_dir: str | None = None,
    **fetch_kwargs,
) -> dict:
    """The north-star run: the full set-transformer configuration swept over a
    grid of beta endpoints (x seed repeats) as ONE jitted program on a
    ``(beta, data)`` mesh.

    ``beta_ends`` defaults to a log grid around the paper's 2e-1; each endpoint
    is repeated ``num_repeats`` times with independent seeds (the papers run
    "20 repeats per" config, chaos notebook cell 10 header). Returns per-replica
    history records, the endpoint grid, wall-clock, and per-replica info-plane
    artifact paths.

    ``checkpoint_dir`` arms crash/stall recovery (train/watchdog.py): an
    Orbax checkpoint is saved at every chunk boundary, and when the
    directory already holds one the run RESUMES from it on the exact key
    chain (``DIBCheckpointer`` chunk-size contract) instead of starting
    over — a killed-and-relaunched invocation is bit-identical to an
    uninterrupted one. The result dict gains ``resumed_from_epoch``.
    """
    config = config or AmorphousWorkloadConfig()
    if isinstance(key, int):
        key = jax.random.key(key)
    if beta_ends is None:
        beta_ends = np.logspace(-2, 0, 8)
    ends = np.repeat(np.asarray(beta_ends, np.float64), num_repeats)
    num_replicas = len(ends)

    bundle = get_dataset("amorphous_particles",
                         number_particles_to_use=config.number_particles,
                         **fetch_kwargs)
    model = build_model(config, **(model_overrides or {}))
    if mesh is None and use_mesh and len(jax.devices()) > 1:
        num_beta = int(np.gcd(num_replicas, len(jax.devices())))
        mesh = make_sweep_mesh(num_beta=num_beta)

    sweep = BetaSweepTrainer(
        model, bundle, config.train_config(steps_per_epoch),
        config.beta_start, ends, mesh=mesh,
    )
    keys = jax.random.split(key, num_replicas)
    hooks = list(hooks)
    states = histories = None
    remaining = None
    resumed_from = None
    if checkpoint_dir:
        from dib_tpu.train.checkpoint import CheckpointHook, DIBCheckpointer

        ckpt = DIBCheckpointer(os.path.abspath(checkpoint_dir))
        # last, so a checkpoint is only written once the other hooks'
        # persisted instrumentation for that epoch is already on disk
        hooks.append(CheckpointHook(ckpt))
        if ckpt.latest_step is not None:
            states, histories, keys = ckpt.restore(
                sweep, chunk_size=chunk_epochs
            )
            resumed_from = int(np.max(jax.device_get(states.epoch)))
            total = config.train_config(steps_per_epoch).num_epochs
            remaining = max(total - resumed_from, 0)
    # Async-dispatch-honest wall-clock: the phase blocks on the final params
    # before closing (scripts/check_timing_hygiene.py rejects bare
    # wall-clock deltas around jitted work).
    timer = PhaseTimer()
    with timer.phase("sweep_fit") as ph:
        # chunk_epochs bounds single-dispatch size (very long device
        # programs can exceed runtime execution limits) and gives hooks
        # their cadence
        states, records = sweep.fit(
            keys, num_epochs=remaining, hooks=hooks, hook_every=chunk_epochs,
            states=states, histories=histories,
        )
        ph.block_on(states.params)
        if checkpoint_dir:
            ckpt.close()    # drain the async final save before returning
    wall_s = timer.totals["sweep_fit"]

    entropy_y = sequence_entropy_bits(bundle.y_train.reshape(-1))
    paths = []
    os.makedirs(outdir, exist_ok=True)
    for r, record in enumerate(records):
        bits = record.to_bits()
        paths.append(save_distributed_info_plane(
            bits.kl_per_feature, bits.loss, outdir,
            entropy_y=entropy_y,
            info_plot_lims=(0.0, float(bits.total_kl.max()) + 1.0),
            filename=f"info_plane_replica{r}_betaend{ends[r]:.2e}.png",
        ))
    return {
        "states": states,
        "records": records,
        "beta_ends": ends,
        "wall_clock_s": wall_s,
        "entropy_y_bits": entropy_y,
        "info_plane_paths": paths,
        "mesh": mesh,
        "resumed_from_epoch": resumed_from,
    }


def run_amorphous_protocols(
    key: Array | int = 0,
    protocols: Sequence[str] = ("GradualQuench", "RapidQuench"),
    config: AmorphousWorkloadConfig | None = None,
    outdir: str = "./amorphous_out",
    **workload_kwargs,
) -> dict:
    """The reference's outer loop (amorphous notebook cell 8: ``for protocol
    in ['GradualQuench', 'RapidQuench']``): one full per-particle run per
    quench protocol, each with its own artifact subdirectory and PRNG stream.

    Real ``{protocol}.npz`` exports are used when present under
    ``data_path``; otherwise each protocol gets an independent synthetic
    surrogate (distinct fetch seed). Returns ``{protocol: result}`` with the
    same per-run contract as :func:`run_amorphous_workload`.
    """
    if isinstance(key, int):
        key = jax.random.key(key)
    if isinstance(protocols, str):
        # a bare protocol name would iterate character-by-character,
        # launching one junk run per letter
        protocols = (protocols,)
    results = {}
    for i, protocol in enumerate(protocols):
        fetch = dict(workload_kwargs)
        fetch.setdefault("seed", 0)
        fetch["seed"] = fetch["seed"] + 7919 * i   # independent surrogates
        results[protocol] = run_amorphous_workload(
            jax.random.fold_in(key, i),
            config=config,
            outdir=os.path.join(outdir, protocol),
            protocol=protocol,
            **fetch,
        )
    return results
