"""Characterization of the MI sandwich bounds on synthetic channels.

Scriptable equivalent of the reference's characterization notebook
(``complex_systems/InfoDecomp_Characterization_of_mutual_information_bounds_
with_synthetic_data.ipynb``, cells 3-4): Gaussian channels with *known*
mutual information — uniform binary X in 1/2/4/6 dims and continuous uniform
X — swept over the separation scale and the evaluation batch size
{64, 256, 1024}, with the InfoNCE/LOO sandwich bounds compared against
brute-force Monte Carlo ground truth, mean +- std over repeats, and residual
plots.

Ground truth:
  - discrete X (uniform over {-1,+1}^k): the marginal p(u) is an EXACT
    2^k-component Gaussian mixture, so I(U;X) = E[log p(u|x) - log p(u)] is
    Monte Carlo only over u draws (float64, log-space on host).
  - continuous X: the marginal is approximated by a large reference mixture
    (MC marginal), the standard brute-force estimate the notebook uses.

The estimator under test is the production TPU path
(:func:`dib_tpu.ops.info_bounds.mi_sandwich_from_params` — f32 log-space);
the oracle is host-side NumPy f64. Residuals at the ~0.01-bit level validate
the precision design decision from SURVEY.md section 7.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dib_tpu.ops.entropy import LN2
from dib_tpu.ops.info_bounds import mi_sandwich_from_params

Array = jax.Array


@dataclass(frozen=True)
class SyntheticChannel:
    """U = scale * X (padded to ``embedding_dim``) + N(0, exp(logvar)).

    ``input_bits`` > 0: X uniform over the 2^k corners of {-1,+1}^k.
    ``input_bits`` == 0: continuous X ~ Uniform[-1, 1] (1-D).
    """

    input_bits: int = 1
    scale: float = 2.0
    logvar: float = 0.0
    embedding_dim: int = 8

    @property
    def is_discrete(self) -> bool:
        return self.input_bits > 0

    @property
    def input_dim(self) -> int:
        return self.input_bits if self.is_discrete else 1

    def sample_x(self, rng: np.random.Generator, n: int) -> np.ndarray:
        if self.is_discrete:
            return (rng.integers(0, 2, size=(n, self.input_bits)) * 2 - 1).astype(
                np.float64
            )
        return rng.uniform(-1.0, 1.0, size=(n, 1))

    def mus(self, x: np.ndarray) -> np.ndarray:
        """[N, embedding_dim] channel means: scale * x, zero-padded."""
        pad = self.embedding_dim - x.shape[-1]
        return np.concatenate(
            [self.scale * x, np.zeros((x.shape[0], pad))], axis=-1
        )


def _log_gaussian_mixture(u: np.ndarray, centers: np.ndarray, logvar: float) -> np.ndarray:
    """log[(1/M) sum_m N(u; c_m, e^logvar I)] for [N, d] u and [M, d] centers,
    float64 log-space (logsumexp) on host."""
    d = u.shape[-1]
    # ||u - c||^2 via the norm expansion (never materializes [N, M, d]; the
    # [N, M] matrix itself is the peak allocation)
    sq = (
        (u**2).sum(-1)[:, None]
        + (centers**2).sum(-1)[None, :]
        - 2.0 * u @ centers.T
    )
    z2 = np.maximum(sq, 0.0) / np.exp(logvar)
    log_p = -0.5 * (z2 + d * logvar + d * np.log(2.0 * np.pi))     # [N, M]
    m = log_p.max(axis=1, keepdims=True)
    return (m[:, 0] + np.log(np.mean(np.exp(log_p - m), axis=1)))


def monte_carlo_mi_bits(
    channel: SyntheticChannel,
    num_samples: int = 20_000,
    num_marginal_centers: int = 4096,
    seed: int = 0,
) -> float:
    """Brute-force I(U; X) in bits: E_{x, u|x}[log p(u|x) - log p(u)].

    For discrete X the marginal mixture is exact (all 2^k centers); for
    continuous X it uses ``num_marginal_centers`` reference draws.
    """
    rng = np.random.default_rng(seed)
    d = channel.embedding_dim
    x = channel.sample_x(rng, num_samples)
    mus = channel.mus(x)
    u = mus + rng.normal(size=(num_samples, d)) * np.exp(channel.logvar / 2.0)

    # conditional log-density at the sampled (x, u) pairs
    z2 = ((u - mus) ** 2).sum(-1) / np.exp(channel.logvar)
    log_cond = -0.5 * (z2 + d * channel.logvar + d * np.log(2.0 * np.pi))

    if channel.is_discrete:
        corners = np.array(
            np.meshgrid(*[[-1.0, 1.0]] * channel.input_bits)
        ).reshape(channel.input_bits, -1).T                         # [2^k, k]
        centers = channel.mus(corners)
    else:
        centers = channel.mus(channel.sample_x(rng, num_marginal_centers))
    log_marg = _log_gaussian_mixture(u, centers, channel.logvar)
    return float(np.mean(log_cond - log_marg) / LN2)


@dataclass
class CharacterizationResult:
    """One (channel, batch_size) cell of the sweep, all values in bits."""

    channel: SyntheticChannel
    batch_size: int
    mc_truth: float
    lower_mean: float
    lower_std: float
    upper_mean: float
    upper_std: float

    @property
    def lower_residual(self) -> float:
        return self.lower_mean - self.mc_truth

    @property
    def upper_residual(self) -> float:
        return self.upper_mean - self.mc_truth


def estimate_bounds_bits(
    channel: SyntheticChannel,
    batch_size: int,
    num_repeats: int = 8,
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """([R] lower, [R] upper) sandwich bounds in bits over independent batches,
    through the production f32 log-space estimator."""
    rng = np.random.default_rng(seed)
    lowers, uppers = [], []
    for r in range(num_repeats):
        x = channel.sample_x(rng, batch_size)
        mus = jnp.asarray(channel.mus(x), jnp.float32)
        logvars = jnp.full(mus.shape, channel.logvar, jnp.float32)
        lo, up = mi_sandwich_from_params(jax.random.key(seed * 1000 + r), mus, logvars)
        lowers.append(float(lo) / LN2)
        uppers.append(float(up) / LN2)
    return np.asarray(lowers), np.asarray(uppers)


def run_characterization(
    input_bits_list: Sequence[int] = (1, 2, 4, 6, 0),
    scales: Sequence[float] | None = None,
    batch_sizes: Sequence[int] = (64, 256, 1024),
    logvar: float = 0.0,
    embedding_dim: int = 8,
    num_repeats: int = 8,
    mc_samples: int = 20_000,
    seed: int = 0,
) -> list[CharacterizationResult]:
    """The full characterization sweep (notebook cells 3-4).

    ``input_bits_list`` includes 0 for the continuous channel. Returns one
    result per (channel-dims, scale, batch-size) cell.
    """
    if scales is None:
        scales = np.logspace(-1, 1, 7)
    results = []
    for bits in input_bits_list:
        for scale in scales:
            channel = SyntheticChannel(
                input_bits=bits, scale=float(scale),
                logvar=logvar, embedding_dim=embedding_dim,
            )
            truth = monte_carlo_mi_bits(channel, num_samples=mc_samples, seed=seed)
            for batch_size in batch_sizes:
                lowers, uppers = estimate_bounds_bits(
                    channel, batch_size, num_repeats, seed
                )
                results.append(CharacterizationResult(
                    channel=channel,
                    batch_size=batch_size,
                    mc_truth=truth,
                    lower_mean=float(lowers.mean()),
                    lower_std=float(lowers.std()),
                    upper_mean=float(uppers.mean()),
                    upper_std=float(uppers.std()),
                ))
    return results


def save_characterization_plots(
    results: list[CharacterizationResult], outdir: str
) -> list[str]:
    """Bounds-vs-truth curves and residual panels, one figure per channel
    dimensionality (the notebook's two summary figures generalized)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    os.makedirs(outdir, exist_ok=True)
    paths = []
    by_bits: dict[int, list[CharacterizationResult]] = {}
    for r in results:
        by_bits.setdefault(r.channel.input_bits, []).append(r)

    for bits, rows in by_bits.items():
        fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(12, 4.5))
        batch_sizes = sorted({r.batch_size for r in rows})
        for bs in batch_sizes:
            sub = sorted((r for r in rows if r.batch_size == bs),
                         key=lambda r: r.channel.scale)
            scales = [r.channel.scale for r in sub]
            ax1.errorbar(scales, [r.lower_mean for r in sub],
                         yerr=[r.lower_std for r in sub], marker="o",
                         label=f"lower, B={bs}")
            ax1.errorbar(scales, [r.upper_mean for r in sub],
                         yerr=[r.upper_std for r in sub], marker="s",
                         linestyle="--", label=f"upper, B={bs}")
            ax2.plot(scales, [r.lower_residual for r in sub], marker="o",
                     label=f"lower, B={bs}")
            ax2.plot(scales, [r.upper_residual for r in sub], marker="s",
                     linestyle="--", label=f"upper, B={bs}")
        truth = sorted({(r.channel.scale, r.mc_truth) for r in rows})
        ax1.plot([t[0] for t in truth], [t[1] for t in truth], "k:", lw=2,
                 label="MC truth")
        name = f"{bits}-bit X" if bits else "continuous X"
        ax1.set(xscale="log", xlabel="separation scale", ylabel="I(U;X) (bits)",
                title=f"Sandwich bounds, {name}")
        ax2.set(xscale="log", xlabel="separation scale",
                ylabel="bound - truth (bits)", title="Residuals")
        ax2.axhline(0.0, color="k", lw=0.5)
        ax1.legend(fontsize=7)
        path = os.path.join(outdir, f"characterization_{bits}bit.png")
        fig.savefig(path, dpi=150, bbox_inches="tight")
        plt.close(fig)
        paths.append(path)
    return paths
