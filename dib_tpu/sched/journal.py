"""Durable append-only job journal: the scheduler's only persistent state.

The whole scheduler (``dib_tpu/sched/scheduler.py``) is a fold over this
file — there is no database, no lock file, no state snapshot to go stale.
Every state transition (job submitted, unit added, lease granted/renewed/
released/expired, unit done/failed, job done/failed) is ONE JSON line
appended with the events.jsonl durability contract (telemetry/events.py):
a single ``os.write`` of one ``\\n``-terminated line on an ``O_APPEND``
fd, so concurrent appenders never interleave bytes and a writer killed
mid-append can tear at most the line it was writing. Replay
(:func:`read_journal`) skips torn lines with a count, so a scheduler
SIGKILLed mid-append restarts into exactly the queue it died with — the
one lost transition is re-derived (an un-journaled lease grant simply
never happened; the unit is still pending and is leased again).

Record envelope: ``v`` (journal schema version), ``seq`` (per-writer
sequence), ``t`` (unix time), ``w`` (writer id — distinguishes the
records of concurrent appenders sharing one fleet journal, so a reader
folding incrementally can skip its own already-folded records), ``kind``,
then the transition's fields.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid

__all__ = ["JOURNAL_FILENAME", "JOURNAL_VERSION", "JobJournal",
           "read_journal", "read_journal_from"]

JOURNAL_FILENAME = "journal.jsonl"
JOURNAL_VERSION = 1


class JobJournal:
    """Appends scheduler state transitions to ``<directory>/journal.jsonl``.

    Thread-safe: pool workers complete/fail units concurrently, and the
    lock keeps ``seq`` gapless and the record/write pairing consistent
    (the EventWriter.emit discipline).
    """

    def __init__(self, directory: str, filename: str = JOURNAL_FILENAME):
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.path = os.path.join(directory, filename)
        self._lock = threading.Lock()
        self._seq = 0
        # Writer id: a fleet journal has MANY appenders (one run-pool plus
        # every submit-only study controller); each record names which one
        # wrote it, so `Scheduler.refresh` can fold foreign records without
        # double-folding its own.
        self.writer_id = uuid.uuid4().hex[:8]
        self._fd = os.open(
            self.path, os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644
        )
        # Seal a torn final line (the previous scheduler died mid-append):
        # without the newline, THIS writer's first record would glue onto
        # the torn bytes and be lost to every future replay as part of one
        # unparseable line. A live writer's record is always one complete
        # \n-terminated os.write, so the only way the file ends without a
        # newline is a writer killed mid-append — the seal can never split
        # a live writer's record, even with concurrent fleet appenders.
        try:
            size = os.fstat(self._fd).st_size
            if size > 0:
                with open(self.path, "rb") as f:
                    f.seek(size - 1)
                    if f.read(1) != b"\n":
                        os.write(self._fd, b"\n")
        except OSError:
            pass

    def append(self, kind: str, **fields) -> dict:
        """Append one transition; returns the record as written. A closed
        journal drops the append (mirrors EventWriter: a racing shutdown
        must not crash the appending worker thread)."""
        with self._lock:
            if self._fd is None:
                return {}
            record = {
                "v": JOURNAL_VERSION,
                "seq": self._seq,
                "t": round(time.time(), 6),   # timing-ok: record
                # timestamp, not a measured interval
                "w": self.writer_id,
                "kind": kind,
                **fields,
            }
            self._seq += 1
            line = json.dumps(record, allow_nan=False) + "\n"
            # one write() per line on an O_APPEND fd: a kill can only
            # truncate the final line, never corrupt an earlier one
            os.write(self._fd, line.encode())
        return record

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None

    def __enter__(self) -> "JobJournal":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


def read_journal(path: str) -> tuple[list[dict], int]:
    """All parseable records of a journal file, oldest first, plus the
    count of torn lines skipped.

    A torn line is evidence of a writer killed mid-append (the SIGKILL
    the durability contract is designed around); the caller — scheduler
    replay — surfaces the count as a ``journal_recovered`` mitigation so
    crash recovery is never silent. A missing file replays as empty (a
    fresh scheduler directory).
    """
    if os.path.isdir(path):
        path = os.path.join(path, JOURNAL_FILENAME)
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return [], 0
    records: list[dict] = []
    torn = 0
    for line in raw.split(b"\n"):
        if not line.strip():
            continue
        try:
            record = json.loads(line)
        except json.JSONDecodeError:
            torn += 1
            continue
        if isinstance(record, dict):
            records.append(record)
        else:
            torn += 1
    return records, torn


def read_journal_from(path: str, offset: int) -> tuple[list[dict], int, int]:
    """Incremental read: parseable records appended after ``offset`` bytes,
    the count of torn COMPLETE lines skipped, and the new offset.

    The incremental contract differs from :func:`read_journal` on the
    final line: an un-terminated tail is NOT consumed — it may be a
    concurrent writer's append caught mid-flight (the reader raced the
    single ``os.write``, which is possible on some filesystems even though
    the write itself is atomic once visible), so the returned offset stops
    before it and the next call re-reads it once the newline lands. Only
    ``\\n``-terminated lines that still fail to parse count as torn.
    A missing file reads as empty at offset 0.
    """
    if os.path.isdir(path):
        path = os.path.join(path, JOURNAL_FILENAME)
    try:
        with open(path, "rb") as f:
            f.seek(offset)
            raw = f.read()
    except OSError:
        return [], 0, 0
    records: list[dict] = []
    torn = 0
    consumed = 0
    for line in raw.splitlines(keepends=True):
        if not line.endswith(b"\n"):
            break                      # un-terminated tail: re-read later
        consumed += len(line)
        if not line.strip():
            continue
        try:
            record = json.loads(line)
        except json.JSONDecodeError:
            torn += 1
            continue
        if isinstance(record, dict):
            records.append(record)
        else:
            torn += 1
    return records, torn, offset + consumed
