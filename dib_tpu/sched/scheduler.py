"""Lease-based β-grid scheduler: jobs in, chunk-resumable work units out.

The scheduling model (docs/robustness.md "Sweep as a service"):

  - A **job** is a β grid × seed ensemble (dense grids via
    :func:`dense_beta_grid`, refinement around info-plane transitions via
    :func:`refine_beta_grid`, explicit lists) plus training parameters
    and a per-job retry budget. Submission decomposes it into one
    **work unit** per (β, seed) — each independently trainable and
    chunk-resumable (the unit runner checkpoints every chunk and resumes
    from the newest intact step, so a unit can die and continue anywhere).
  - Workers **acquire** units under a lease: a grant names the worker,
    carries a wall-clock deadline, and must be renewed (the worker's
    chunk-boundary heartbeat) before it expires. The oldest eligible
    pending unit wins (FIFO, honoring retry backoff holds).
  - **Work-stealing**: :meth:`Scheduler.reap` re-queues any unit whose
    lease deadline passed — a straggler, a dead worker, a vanished pool —
    and the next ``acquire`` hands it to a live worker, which resumes
    from the unit's newest intact checkpoint. The superseded lease is
    remembered: a completion or renewal under it is **rejected**
    (returns False), so a presumed-dead worker that comes back cannot
    double-execute a unit or overwrite the thief's result.
  - **Retry with backoff**: a failed unit re-queues with an exponential
    not-before hold (``backoff_base_s * 2**(attempt-1)``) against the
    job's retry budget; exhaustion marks the unit AND the job failed
    (``retry_exhausted`` mitigation) instead of retrying forever.
  - **Graceful degradation**: lease expiry and cooperative preemption
    (:meth:`release`) re-queue budget-free — a dying worker is the
    pool's problem, never the job's (the watchdog's budget-free rc-75
    relaunch, at the scheduling layer).

The multi-tenant fleet layer (docs/scheduling.md):

  - **Fair share**: jobs carry a ``tenant`` (and optionally a ``study``
    and an integer ``priority``); :meth:`acquire` is deficit-weighted
    fair-share ACROSS tenants — among tenants with an eligible unit, the
    one with the least weighted service (journaled lease grants /
    policy weight) wins; WITHIN a tenant the order stays FIFO and
    retry-backoff holds are honored unchanged. Service counters fold
    from ``lease`` records, so a SIGKILLed scheduler restarts into the
    exact fair-share ledger.
  - **Admission control**: :class:`FleetPolicy` (``policy.json`` in the
    scheduler directory) bounds the pending queue fleet-wide and per
    tenant and caps per-tenant concurrent leases; an over-bound
    :meth:`submit` journals an ``admission`` record and raises
    :class:`AdmissionRejected` with an explicit retry horizon — the
    serve plane's ``TenantQuotas`` shape applied to the batch plane.
  - **Load shedding**: when the pool shrinks (:meth:`set_capacity`),
    pending units of the lowest priority classes PARK (reported as
    ``starved`` in :meth:`status`; the stored state stays ``pending`` —
    shedding is live-pool policy, never persisted) while leased units
    finish; a recovered pool unparks them by reassessing capacity.
  - **Circuit breaker**: a job whose units fail ``breaker_threshold``
    times consecutively is quarantined (journaled ``breaker`` trip)
    instead of burning the shared retry budget; after the probe horizon
    one half-open probe unit is leased, and its success resets the
    breaker while its failure re-trips it.

Durability: every transition is journaled BEFORE the in-memory state
changes (``sched/journal.py``); construction replays the journal, so a
SIGKILLed scheduler restarts into the exact queue it died with, torn
final line tolerated (surfaced as a ``journal_recovered`` mitigation).
A fleet journal has MANY writers (one run-pool, N submit-only study
controllers); :meth:`Scheduler.refresh` incrementally folds the OTHER
writers' records (by journal writer id), which is how the pool sees
cross-process submissions and a polling controller sees its units
drain.

Telemetry: with an ``EventWriter``, transitions land as typed ``job`` /
``lease`` events on the run's events.jsonl (docs/observability.md), and
recovery actions as ``mitigation`` events (``lease_stolen``,
``retry_exhausted``, ``preempt_requeue``, ``journal_recovered``) — the
same stream the chaos suite's faults land on, so ``telemetry summarize``
joins injections with the scheduler's reactions.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import threading
import time
import uuid
from typing import Sequence

from dib_tpu.sched.journal import (
    JOURNAL_FILENAME,
    JobJournal,
    read_journal,
    read_journal_from,
)

__all__ = ["AdmissionRejected", "FleetPolicy", "JobSpec", "Lease",
           "POLICY_FILENAME", "Scheduler", "TenantPolicy", "WorkUnit",
           "dense_beta_grid", "parked_snapshot", "refine_beta_grid"]

POLICY_FILENAME = "policy.json"


# ------------------------------------------------------------------ grids
def dense_beta_grid(start: float, stop: float, num: int) -> list[float]:
    """``num`` log-spaced β endpoints in [start, stop] — the dense-grid
    job shape (the paper's info plane is log-β structured, so linear
    spacing would waste most of the grid on the top decade)."""
    if num < 1 or start <= 0 or stop <= 0 or stop < start:
        raise ValueError(
            f"dense_beta_grid needs 0 < start <= stop and num >= 1; got "
            f"start={start}, stop={stop}, num={num}"
        )
    if num == 1:
        return [float(start)]
    lo, hi = math.log10(start), math.log10(stop)
    return [round(10 ** (lo + (hi - lo) * i / (num - 1)), 10)
            for i in range(num)]


def refine_beta_grid(around: Sequence[float], num: int = 4,
                     span_decades: float = 0.25) -> list[float]:
    """Refinement grid around info-plane transition βs: ``num`` log-spaced
    points within ±``span_decades`` of each center, merged/deduped/sorted.

    ``around`` is typically the β values of ``transition`` events
    (telemetry/slo.py detects per-channel KL threshold crossings) — the
    machine-readable signal this scheduler's refinement jobs key on.
    """
    out: set[float] = set()
    for center in around:
        if center <= 0:
            raise ValueError(f"refinement center must be positive, got {center}")
        out.update(dense_beta_grid(
            10 ** (math.log10(center) - span_decades),
            10 ** (math.log10(center) + span_decades), num,
        ))
    return sorted(out)


# ------------------------------------------------------------- dataclasses
@dataclasses.dataclass(frozen=True)
class JobSpec:
    """One β-grid job: the grid, the seeds, the training parameters the
    unit runner needs, the job's retry budget, and its fleet identity
    (``tenant``/``study`` for fair share, ``priority`` for shedding —
    higher numbers shed LAST)."""

    betas: tuple[float, ...]
    seeds: tuple[int, ...] = (0,)
    train: dict = dataclasses.field(default_factory=dict)
    retry_budget: int = 3
    name: str = ""
    tenant: str = ""
    study: str = ""
    priority: int = 0

    def __post_init__(self):
        if not self.betas:
            raise ValueError("a job needs at least one β endpoint")
        if not self.seeds:
            raise ValueError("a job needs at least one seed")
        if self.retry_budget < 0:
            raise ValueError("retry_budget must be >= 0")

    def to_dict(self) -> dict:
        return {
            "betas": [float(b) for b in self.betas],
            "seeds": [int(s) for s in self.seeds],
            "train": dict(self.train),
            "retry_budget": int(self.retry_budget),
            "name": self.name,
            "tenant": self.tenant,
            "study": self.study,
            "priority": int(self.priority),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "JobSpec":
        return cls(
            betas=tuple(d.get("betas") or ()),
            seeds=tuple(d.get("seeds") or (0,)),
            train=dict(d.get("train") or {}),
            retry_budget=int(d.get("retry_budget", 3)),
            name=d.get("name", ""),
            tenant=d.get("tenant", ""),
            study=d.get("study", ""),
            priority=int(d.get("priority", 0) or 0),
        )


# ------------------------------------------------------------------ policy
@dataclasses.dataclass(frozen=True)
class TenantPolicy:
    """One tenant's share of the fleet: fair-share ``weight``, a cap on
    concurrent leases, and a cap on queued (pending) units."""

    weight: float = 1.0
    max_leases: int | None = None
    max_pending: int | None = None

    def to_dict(self) -> dict:
        return {"weight": float(self.weight),
                "max_leases": self.max_leases,
                "max_pending": self.max_pending}

    @classmethod
    def from_dict(cls, d: dict) -> "TenantPolicy":
        return cls(
            weight=float(d.get("weight", 1.0) or 1.0),
            max_leases=(None if d.get("max_leases") is None
                        else int(d["max_leases"])),
            max_pending=(None if d.get("max_pending") is None
                         else int(d["max_pending"])),
        )


_DEFAULT_TENANT_POLICY = TenantPolicy()


@dataclasses.dataclass(frozen=True)
class FleetPolicy:
    """The fleet's admission/fairness/breaker policy — the serve plane's
    ``TenantQuotas`` shape applied to the batch plane. Persisted as
    ``policy.json`` next to the journal so every writer sharing the
    fleet directory (the run-pool AND each submitting controller)
    enforces the same bounds. Policy gates LIVE decisions only; every
    resulting state transition is journaled, so replay never needs the
    policy that produced it."""

    max_pending_units: int | None = None
    admission_retry_s: float = 5.0
    breaker_threshold: int = 0          # 0 disables the circuit breaker
    breaker_probe_after_s: float = 30.0
    tenants: dict = dataclasses.field(default_factory=dict)

    def for_tenant(self, tenant: str) -> TenantPolicy:
        return self.tenants.get(tenant, _DEFAULT_TENANT_POLICY)

    def to_dict(self) -> dict:
        return {
            "max_pending_units": self.max_pending_units,
            "admission_retry_s": float(self.admission_retry_s),
            "breaker_threshold": int(self.breaker_threshold),
            "breaker_probe_after_s": float(self.breaker_probe_after_s),
            "tenants": {name: tp.to_dict()
                        for name, tp in sorted(self.tenants.items())},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FleetPolicy":
        return cls(
            max_pending_units=(None if d.get("max_pending_units") is None
                               else int(d["max_pending_units"])),
            admission_retry_s=float(d.get("admission_retry_s", 5.0) or 5.0),
            breaker_threshold=int(d.get("breaker_threshold", 0) or 0),
            breaker_probe_after_s=float(
                d.get("breaker_probe_after_s", 30.0) or 30.0),
            tenants={name: TenantPolicy.from_dict(tp or {})
                     for name, tp in (d.get("tenants") or {}).items()},
        )

    @classmethod
    def load(cls, directory: str) -> "FleetPolicy | None":
        """The directory's persisted policy, or None without one (every
        bound open — the single-tenant legacy behavior)."""
        path = os.path.join(directory, POLICY_FILENAME)
        try:
            with open(path, encoding="utf-8") as f:
                return cls.from_dict(json.load(f))
        except (OSError, ValueError):
            return None

    def save(self, directory: str) -> str:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, POLICY_FILENAME)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        return path


class AdmissionRejected(RuntimeError):
    """An over-bound :meth:`Scheduler.submit` — the queue is full
    (fleet-wide or for this tenant). Carries the explicit retry horizon:
    the polite caller waits ``retry_after_s`` and resubmits; the journal
    already holds the ``admission`` record either way."""

    def __init__(self, tenant: str, retry_after_s: float, reason: str):
        super().__init__(
            f"admission rejected for tenant {tenant!r}: {reason} "
            f"(retry after {retry_after_s:g}s)")
        self.tenant = tenant
        self.retry_after_s = float(retry_after_s)
        self.reason = reason


@dataclasses.dataclass(frozen=True)
class WorkUnit:
    """One (β, seed) training unit of a job — the unit of leasing,
    stealing, retrying, and checkpoint-resumable execution."""

    unit_id: str
    job_id: str
    beta: float
    seed: int
    train: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class Lease:
    """One grant of one unit to one worker, valid until ``expires_t``."""

    unit_id: str
    lease_id: str
    worker: str
    expires_t: float
    attempt: int


class Scheduler:
    """The persistent β-grid scheduler over one journal directory.

    ``clock`` is injectable (tests drive lease expiry without sleeping);
    everything else reads wall-clock. All public methods are thread-safe
    — pool workers acquire/renew/complete concurrently.
    """

    def __init__(self, directory: str, telemetry=None,
                 lease_s: float = 60.0, backoff_base_s: float = 0.5,
                 clock=time.time, ctx=None, policy: FleetPolicy | None = None):
        from dib_tpu.telemetry.context import from_env

        self.directory = directory
        self.lease_s = float(lease_s)
        self.backoff_base_s = float(backoff_base_s)
        self._telemetry = telemetry
        # the cross-plane trace context submissions are journaled under
        # (telemetry/context.py): the caller's lineage (a study round, a
        # CLI --trace-id) or whatever a parent process pinned via env —
        # job records carry it verbatim, unit records carry a child ctx
        # whose parent is the sched:job:<job_id> ref
        self._ctx = ctx if ctx is not None else from_env()
        self._clock = clock
        self._lock = threading.RLock()
        self._jobs: dict[str, dict] = {}
        self._units: dict[str, dict] = {}
        self._order: list[str] = []      # unit submission order (FIFO base)
        # fair-share ledger: cumulative journaled lease grants per tenant
        # (folded from ``lease`` records, so replay restores it exactly)
        self._service: dict[str, float] = {}
        # per-tenant queue waits (bounded tail) for the status/rollup
        # percentiles, and per-tenant admission-reject counts
        self._tenant_waits: dict[str, list[float]] = {}
        self._admission_rejects: dict[str, int] = {}
        # load-shed floor: LIVE pool policy only (set_capacity), never
        # replayed — a restarted pool reassesses its own capacity. The
        # last journaled ``shed`` record is kept for observability.
        self._shed_floor: int | None = None
        self._last_shed: dict | None = None
        self.policy = (policy if policy is not None
                       else (FleetPolicy.load(directory) or FleetPolicy()))
        self.replayed_records = 0
        self.replayed_torn = 0
        journal_path = os.path.join(directory, JOURNAL_FILENAME)
        records, torn, offset = read_journal_from(journal_path, 0)
        for record in records:
            self._fold(record)
        self.replayed_records = len(records)
        self.replayed_torn = torn
        # journal opened AFTER replay: the replay must never read the
        # fd this instance is about to append with
        self._journal = JobJournal(directory)
        self._read_offset = offset
        # the open sealed any torn tail and a concurrent fleet writer may
        # have appended during replay — fold the remainder before serving
        self.replayed_records += self.refresh()
        torn = self.replayed_torn
        if torn:
            # crash recovery is never silent: a torn line means a writer
            # died mid-append and the transition it was recording is
            # re-derived from the surviving state
            if telemetry is not None:
                telemetry.mitigation(
                    mtype="journal_recovered", detail=(
                        f"replayed {self.replayed_records} journal "
                        f"record(s), skipped {torn} torn line(s)"),
                )

    # -------------------------------------------------------------- replay
    def _fold(self, r: dict) -> None:
        """Apply one journal record to the in-memory state (replay path;
        the live paths journal first, then call this)."""
        kind = r.get("kind")
        if kind == "job":
            spec = JobSpec.from_dict(r.get("spec") or {})
            self._jobs[r["job_id"]] = {
                "spec": spec,
                "status": "running", "retries_used": 0, "units": [],
                "tenant": spec.tenant or "default",
                "study": spec.study,
                "priority": int(spec.priority),
                "consec_fails": 0,
                "breaker": None,          # open breaker: {until, probe_unit}
                "breaker_trips": 0,
            }
        elif kind == "unit":
            unit = WorkUnit(
                unit_id=r["unit_id"], job_id=r["job_id"],
                beta=float(r["beta"]), seed=int(r["seed"]),
                train=dict(r.get("train") or {}),
            )
            self._units[unit.unit_id] = {
                "unit": unit, "status": "pending", "attempts": 0,
                "not_before": 0.0, "lease": None,
                "enqueue_t": r.get("t", 0.0),
            }
            self._order.append(unit.unit_id)
            job = self._jobs.get(unit.job_id)
            if job is not None:
                job["units"].append(unit.unit_id)
        elif kind == "lease":
            entry = self._units.get(r["unit_id"])
            if entry is not None:
                tenant = self._tenant_of(entry)
                # the fair-share ledger and queue-wait tail fold from the
                # grant record itself, so they survive SIGKILL exactly
                self._service[tenant] = self._service.get(tenant, 0.0) + 1.0
                waits = self._tenant_waits.setdefault(tenant, [])
                waits.append(max(r.get("t", 0.0) - entry["enqueue_t"], 0.0))
                if len(waits) > 512:
                    del waits[:len(waits) - 512]
                entry["status"] = "leased"
                entry["lease"] = {
                    "lease_id": r["lease_id"], "worker": r.get("worker"),
                    "expires_t": r.get("expires_t", 0.0),
                    "attempt": r.get("attempt", 0),
                }
        elif kind == "renew":
            entry = self._units.get(r["unit_id"])
            if entry is not None and entry.get("lease") \
                    and entry["lease"]["lease_id"] == r.get("lease_id"):
                entry["lease"]["expires_t"] = r.get("expires_t", 0.0)
        elif kind in ("release", "expire"):
            entry = self._units.get(r["unit_id"])
            if entry is not None:
                # superseding is implemented by clearing the live lease:
                # _current() compares lease ids against it, so every
                # older lease is rejected from here on
                entry["status"] = "pending"
                entry["lease"] = None
                entry["enqueue_t"] = r.get("t", 0.0)
                self._clear_probe(entry, r["unit_id"])
        elif kind == "fail":
            entry = self._units.get(r["unit_id"])
            if entry is not None:
                entry["attempts"] += 1
                entry["lease"] = None
                if r.get("requeued"):
                    entry["status"] = "pending"
                    entry["not_before"] = r.get("not_before", 0.0)
                    entry["enqueue_t"] = r.get("t", 0.0)
                else:
                    entry["status"] = "failed"
                job = self._jobs.get(entry["unit"].job_id)
                # only an actual RETRY spends the budget: the final,
                # non-requeued failure is the budget being enforced, and
                # counting it would report retries = budget+1 and trip
                # the sched_retry_ceiling SLO on correct fail-fast
                if job is not None and r.get("requeued"):
                    job["retries_used"] += 1
                if job is not None:
                    job["consec_fails"] += 1
                self._clear_probe(entry, r["unit_id"])
        elif kind == "done":
            entry = self._units.get(r["unit_id"])
            if entry is not None:
                entry["status"] = "done"
                entry["lease"] = None
                entry["result"] = r.get("result")
                job = self._jobs.get(entry["unit"].job_id)
                if job is not None:
                    job["consec_fails"] = 0
                self._clear_probe(entry, r["unit_id"])
        elif kind == "breaker":
            job = self._jobs.get(r.get("job_id"))
            if job is not None:
                action = r.get("action")
                if action == "trip":
                    job["breaker"] = {"until": r.get("until", 0.0),
                                      "probe_unit": None}
                    job["breaker_trips"] += 1
                elif action == "probe" and job["breaker"] is not None:
                    job["breaker"]["probe_unit"] = r.get("unit_id")
                elif action == "reset":
                    job["breaker"] = None
                    job["consec_fails"] = 0
        elif kind == "admission":
            if r.get("action") == "rejected":
                tenant = r.get("tenant", "default")
                self._admission_rejects[tenant] = (
                    self._admission_rejects.get(tenant, 0) + 1)
        elif kind == "shed":
            # observability only: the floor itself is live-pool policy
            # (set_capacity), never restored by replay
            self._last_shed = {"floor": r.get("floor"),
                               "alive": r.get("alive"),
                               "total": r.get("total"), "t": r.get("t")}
        elif kind == "job_done":
            job = self._jobs.get(r["job_id"])
            if job is not None:
                job["status"] = "done"
        elif kind == "job_failed":
            job = self._jobs.get(r["job_id"])
            if job is not None:
                job["status"] = "failed"

    def _tenant_of(self, entry: dict) -> str:
        job = self._jobs.get(entry["unit"].job_id)
        return job["tenant"] if job is not None else "default"

    def _clear_probe(self, entry: dict, unit_id: str) -> None:
        """A probe unit leaving the leased state (done/fail/release/
        expire) clears the half-open marker; the breaker itself is only
        closed by an explicit journaled reset, so a crash between the
        probe's ``done`` and the ``reset`` merely costs one extra probe."""
        job = self._jobs.get(entry["unit"].job_id)
        if job is not None and job.get("breaker") is not None \
                and job["breaker"].get("probe_unit") == unit_id:
            job["breaker"]["probe_unit"] = None

    # --------------------------------------------------------------- submit
    def _admit_locked(self, tenant: str, n_units: int) -> None:
        """Admission control: reject a submit that would overflow the
        bounded queue (fleet-wide or per tenant). The rejection is
        journaled — replay restores the per-tenant reject counters — and
        raised with the explicit retry horizon."""
        cap = self.policy.max_pending_units
        tp = self.policy.for_tenant(tenant)
        reason = None
        if cap is not None or tp.max_pending is not None:
            pending = t_pending = 0
            for e in self._units.values():
                if e["status"] != "pending":
                    continue
                pending += 1
                if self._tenant_of(e) == tenant:
                    t_pending += 1
            if cap is not None and pending + n_units > cap:
                reason = (f"fleet queue full: {pending} pending + "
                          f"{n_units} would exceed the {cap}-unit bound")
            elif tp.max_pending is not None \
                    and t_pending + n_units > tp.max_pending:
                reason = (f"tenant queue full: {t_pending} pending + "
                          f"{n_units} would exceed the tenant's "
                          f"{tp.max_pending}-unit bound")
        if reason is None:
            return
        retry_after = float(self.policy.admission_retry_s)
        self._fold(self._journal.append(
            "admission", action="rejected", tenant=tenant, units=n_units,
            reason=reason, retry_after_s=retry_after))
        if self._telemetry is not None:
            self._telemetry.job(
                job_id=f"admission:{tenant}", action="rejected",
                tenant=tenant, units=n_units, reason=reason,
                retry_after_s=retry_after)
        raise AdmissionRejected(tenant, retry_after, reason)

    def submit(self, spec: JobSpec) -> str:
        """Decompose a job into (β, seed) units and enqueue them FIFO.
        Returns the job id. Raises :class:`AdmissionRejected` when the
        policy's queue bounds would overflow."""
        with self._lock:
            tenant = spec.tenant or "default"
            self._admit_locked(tenant, len(spec.betas) * len(spec.seeds))
            job_id = f"job-{len(self._jobs):04d}-{uuid.uuid4().hex[:6]}"
            job_extra = ({"ctx": self._ctx.to_dict()}
                         if self._ctx is not None else {})
            self._fold(self._journal.append(
                "job", job_id=job_id, spec=spec.to_dict(), **job_extra))
            unit_ctx = (self._ctx.child(f"sched:job:{job_id}",
                                        origin="sched")
                        if self._ctx is not None else None)
            unit_extra = ({"ctx": unit_ctx.to_dict()}
                          if unit_ctx is not None else {})
            for i, beta in enumerate(spec.betas):
                for seed in spec.seeds:
                    unit_id = f"{job_id}/u{i:03d}s{seed}"
                    self._fold(self._journal.append(
                        "unit", unit_id=unit_id, job_id=job_id,
                        beta=float(beta), seed=int(seed),
                        train=dict(spec.train), **unit_extra))
            if self._telemetry is not None:
                extra = {}
                if spec.study:
                    extra["study"] = spec.study
                self._telemetry.job(
                    job_id=job_id, action="submitted",
                    units=len(spec.betas) * len(spec.seeds),
                    betas=[float(b) for b in spec.betas],
                    seeds=[int(s) for s in spec.seeds],
                    retry_budget=spec.retry_budget,
                    tenant=tenant, priority=int(spec.priority), **extra)
            return job_id

    # -------------------------------------------------------------- leasing
    def _parked_locked(self, job: dict) -> bool:
        """True while the job's pending units are shed below the live
        pool's capacity floor (priority classes are shed lowest-first)."""
        return (self._shed_floor is not None
                and job["priority"] < self._shed_floor)

    def acquire(self, worker: str, lease_s: float | None = None) -> Lease | None:
        """Lease one pending unit to ``worker``; None when nothing is
        currently eligible (empty queue, backoff holds, shed parking,
        quarantine, or quota).

        Selection is deficit-weighted fair share: each tenant's FIRST
        eligible unit in submission order is its candidate (FIFO within
        the tenant), then the tenant with the least ``service/weight``
        wins (ties to the older candidate). With one tenant this
        degenerates to the original global FIFO. Ineligible means: the
        unit's backoff hold, the job parked below the shed floor, the
        job's breaker open (unless the probe horizon passed — then the
        single half-open probe grant), or the tenant at its concurrent-
        lease quota."""
        with self._lock:
            now = self._clock()
            leased_by_tenant: dict[str, int] = {}
            for e in self._units.values():
                if e["status"] == "leased":
                    t = self._tenant_of(e)
                    leased_by_tenant[t] = leased_by_tenant.get(t, 0) + 1
            # tenant -> (unit_id, entry, probe_job_id|None)
            candidates: dict[str, tuple] = {}
            for unit_id in self._order:
                entry = self._units[unit_id]
                if entry["status"] != "pending" or entry["not_before"] > now:
                    continue
                job = self._jobs.get(entry["unit"].job_id)
                tenant = job["tenant"] if job is not None else "default"
                if tenant in candidates:
                    continue
                tp = self.policy.for_tenant(tenant)
                if tp.max_leases is not None \
                        and leased_by_tenant.get(tenant, 0) >= tp.max_leases:
                    continue
                probe_job = None
                if job is not None:
                    if self._parked_locked(job):
                        continue
                    breaker = job.get("breaker")
                    if breaker is not None:
                        if breaker.get("probe_unit") is not None \
                                or breaker.get("until", 0.0) > now:
                            continue      # quarantined / probe in flight
                        probe_job = entry["unit"].job_id
                candidates[tenant] = (unit_id, entry, probe_job)
            if not candidates:
                return None

            def _deficit(tenant: str):
                weight = max(self.policy.for_tenant(tenant).weight, 1e-9)
                return (self._service.get(tenant, 0.0) / weight,
                        candidates[tenant][1]["enqueue_t"], tenant)

            tenant = min(candidates, key=_deficit)
            unit_id, entry, probe_job = candidates[tenant]
            if probe_job is not None:
                self._fold(self._journal.append(
                    "breaker", job_id=probe_job, action="probe",
                    unit_id=unit_id))
                if self._telemetry is not None:
                    self._telemetry.breaker(
                        action="probe", via="sched", job_id=probe_job,
                        tenant=tenant, unit=unit_id)
            attempt = entry["attempts"] + 1
            lease = Lease(
                unit_id=unit_id,
                lease_id=f"{unit_id}#a{attempt}-{uuid.uuid4().hex[:6]}",
                worker=worker,
                expires_t=now + (lease_s or self.lease_s),
                attempt=attempt,
            )
            queue_wait = max(now - entry["enqueue_t"], 0.0)
            self._fold(self._journal.append(
                "lease", unit_id=unit_id, lease_id=lease.lease_id,
                worker=worker, expires_t=lease.expires_t,
                attempt=attempt))
            if self._telemetry is not None:
                self._telemetry.lease(
                    unit=unit_id, action="granted", worker=worker,
                    lease=lease.lease_id,
                    job_id=entry["unit"].job_id,
                    expires_s=round(lease.expires_t - now, 3),
                    queue_wait_s=round(queue_wait, 3),
                    attempt=attempt, tenant=tenant)
            return lease

    def _current(self, lease: Lease) -> dict | None:
        """The unit entry iff ``lease`` is still the unit's live lease."""
        entry = self._units.get(lease.unit_id)
        if entry is None or entry.get("lease") is None:
            return None
        if entry["lease"]["lease_id"] != lease.lease_id:
            return None
        return entry

    def _reject_stale(self, lease: Lease, action: str) -> bool:
        if self._telemetry is not None:
            self._telemetry.lease(
                unit=lease.unit_id, action="rejected", worker=lease.worker,
                lease=lease.lease_id, reason=f"superseded lease ({action})")
        return False

    def renew(self, lease: Lease, lease_s: float | None = None) -> bool:
        """Extend a live lease (the worker's heartbeat). False when the
        lease was superseded — the caller must ABANDON the unit: someone
        else owns it now, and continuing would double-execute it."""
        with self._lock:
            entry = self._current(lease)
            if entry is None:
                return self._reject_stale(lease, "renew")
            expires_t = self._clock() + (lease_s or self.lease_s)
            self._fold(self._journal.append(
                "renew", unit_id=lease.unit_id, lease_id=lease.lease_id,
                expires_t=expires_t))
            if self._telemetry is not None:
                self._telemetry.lease(
                    unit=lease.unit_id, action="renewed",
                    worker=lease.worker, lease=lease.lease_id)
            return True

    # ------------------------------------------------------------ terminals
    def complete(self, lease: Lease, result: dict | None = None) -> bool:
        """Mark the unit done. False (and NO state change) under a
        superseded lease — the double-execution guard: the thief's result
        stands, the returned worker's is dropped."""
        with self._lock:
            entry = self._current(lease)
            if entry is None:
                return self._reject_stale(lease, "complete")
            unit = entry["unit"]
            job = self._jobs.get(unit.job_id)
            was_probe = (job is not None and job.get("breaker") is not None
                         and job["breaker"].get("probe_unit")
                         == lease.unit_id)
            self._fold(self._journal.append(
                "done", unit_id=lease.unit_id, lease_id=lease.lease_id,
                result=result))
            if was_probe:
                # half-open probe succeeded: close the breaker (journaled,
                # so replay restores the closed state)
                self._fold(self._journal.append(
                    "breaker", job_id=unit.job_id, action="reset",
                    via="probe"))
                if self._telemetry is not None:
                    self._telemetry.breaker(
                        action="reset", via="probe", job_id=unit.job_id,
                        tenant=job["tenant"], unit=lease.unit_id)
            if self._telemetry is not None:
                self._telemetry.job(
                    job_id=unit.job_id, action="unit_done",
                    unit=lease.unit_id, worker=lease.worker,
                    beta=unit.beta, seed=unit.seed,
                    tenant=job["tenant"])
            self._maybe_finish_job(unit.job_id)
            return True

    def fail(self, lease: Lease, error: str) -> str | bool:
        """Record a unit failure: re-queue with exponential backoff while
        the job's retry budget lasts (returns ``"requeued"``), else mark
        the unit AND job failed (returns ``"exhausted"``). False under a
        superseded lease (the failure belongs to a stolen attempt)."""
        with self._lock:
            entry = self._current(lease)
            if entry is None:
                return self._reject_stale(lease, "fail")
            unit = entry["unit"]
            job = self._jobs[unit.job_id]
            budget = job["spec"].retry_budget
            requeued = job["retries_used"] < budget
            backoff = (self.backoff_base_s * (2 ** entry["attempts"])
                       if requeued else 0.0)
            was_probe = (job.get("breaker") is not None
                         and job["breaker"].get("probe_unit")
                         == lease.unit_id)
            self._fold(self._journal.append(
                "fail", unit_id=lease.unit_id, lease_id=lease.lease_id,
                error=str(error)[:500], requeued=requeued,
                not_before=self._clock() + backoff))
            self._maybe_trip_breaker(job, unit.job_id, lease.unit_id,
                                     was_probe, requeued)
            if self._telemetry is not None:
                self._telemetry.job(
                    job_id=unit.job_id, action="unit_failed",
                    unit=lease.unit_id, error=str(error)[:300],
                    retries=job["retries_used"],
                    retry_budget=budget,
                    backoff_s=round(backoff, 3),
                    tenant=job["tenant"])
            if not requeued:
                self._fold(self._journal.append(
                    "job_failed", job_id=unit.job_id))
                if self._telemetry is not None:
                    self._telemetry.mitigation(
                        mtype="retry_exhausted", reason=(
                            f"unit {lease.unit_id} failed with the job's "
                            f"retry budget ({budget}) spent"),
                        detail=str(error)[:300])
                    self._telemetry.job(
                        job_id=unit.job_id, action="failed",
                        unit=lease.unit_id,
                        reason="retry budget exhausted")
                return "exhausted"
            return "requeued"

    def _maybe_trip_breaker(self, job: dict, job_id: str, unit_id: str,
                            was_probe: bool, requeued: bool) -> None:
        """Trip (or re-trip) the per-job circuit breaker after a failure:
        ``breaker_threshold`` consecutive failures quarantine the job
        until the probe horizon instead of burning the shared retry
        budget on a study that keeps failing; a failed half-open probe
        re-trips immediately. Caller holds the lock; the ``fail`` record
        is already folded (so ``consec_fails`` counts this failure)."""
        threshold = int(self.policy.breaker_threshold)
        if threshold <= 0 or not requeued or job["status"] != "running":
            return
        if not was_probe and (job.get("breaker") is not None
                              or job["consec_fails"] < threshold):
            return
        until = self._clock() + float(self.policy.breaker_probe_after_s)
        self._fold(self._journal.append(
            "breaker", job_id=job_id, action="trip", until=until,
            consecutive=job["consec_fails"]))
        if self._telemetry is not None:
            self._telemetry.breaker(
                action="trip", via="probe" if was_probe else "sched",
                job_id=job_id, tenant=job["tenant"],
                consecutive=job["consec_fails"], threshold=threshold,
                until=round(until, 3))
            self._telemetry.mitigation(
                mtype="breaker_quarantine", reason=(
                    f"job {job_id} quarantined after "
                    f"{job['consec_fails']} consecutive unit failures "
                    f"(threshold {threshold}); one probe unit is allowed "
                    f"after {self.policy.breaker_probe_after_s:g}s instead "
                    "of burning the shared retry budget"),
                detail=f"unit {unit_id}")

    def release(self, lease: Lease, reason: str = "preempt") -> bool:
        """Budget-free re-queue (cooperative preemption / clean worker
        shutdown): no attempt burned, no backoff hold — the exit-75
        contract at the scheduling layer."""
        with self._lock:
            entry = self._current(lease)
            if entry is None:
                return self._reject_stale(lease, "release")
            self._fold(self._journal.append(
                "release", unit_id=lease.unit_id, lease_id=lease.lease_id))
            if self._telemetry is not None:
                self._telemetry.lease(
                    unit=lease.unit_id, action="released",
                    worker=lease.worker, lease=lease.lease_id,
                    reason=reason)
                if reason == "preempt":
                    self._telemetry.mitigation(
                        mtype="preempt_requeue",
                        reason=(f"unit {lease.unit_id} re-enqueued "
                                "lease-free after cooperative preemption"))
            return True

    # ------------------------------------------------------- work-stealing
    def reap(self, now: float | None = None) -> list[str]:
        """Re-queue every unit whose lease deadline passed (straggler /
        dead worker / vanished pool). The next ``acquire`` hands each to
        a live worker — work-stealing; the superseded lease is rejected
        forever after."""
        with self._lock:
            now = self._clock() if now is None else now
            stolen = []
            for unit_id, entry in self._units.items():
                lease = entry.get("lease")
                if entry["status"] != "leased" or lease is None:
                    continue
                if lease["expires_t"] <= now:
                    self._expire_locked(unit_id, entry, "lease expired")
                    stolen.append(unit_id)
            return stolen

    def force_expire(self, unit_id: str, reason: str) -> bool:
        """Expire a unit's live lease NOW (reaper path for a provably dead
        worker; also the chaos suite's ``lease_expire`` injector)."""
        with self._lock:
            entry = self._units.get(unit_id)
            if entry is None or entry["status"] != "leased" \
                    or entry.get("lease") is None:
                return False
            self._expire_locked(unit_id, entry, reason)
            return True

    def _expire_locked(self, unit_id: str, entry: dict, reason: str) -> None:
        lease = entry["lease"]
        self._fold(self._journal.append(
            "expire", unit_id=unit_id, lease_id=lease["lease_id"],
            reason=reason))
        if self._telemetry is not None:
            self._telemetry.lease(
                unit=unit_id, action="expired", worker=lease.get("worker"),
                lease=lease["lease_id"], reason=reason)
            self._telemetry.mitigation(
                mtype="lease_stolen", reason=(
                    f"unit {unit_id} re-queued from worker "
                    f"{lease.get('worker')} ({reason}); the next acquire "
                    "resumes it from its newest intact checkpoint"))

    # ---------------------------------------------------- fleet operations
    def refresh(self) -> int:
        """Incrementally fold records OTHER writers appended to the
        shared journal since the last read (by writer id — this
        instance's own records were folded at append time). The fleet
        pool calls this from its reaper to see cross-process
        submissions; a submit-only controller calls it while polling its
        round's units to completion. Returns the count folded."""
        with self._lock:
            records, torn, self._read_offset = read_journal_from(
                self._journal.path, self._read_offset)
            self.replayed_torn += torn
            folded = 0
            for r in records:
                if r.get("w") == self._journal.writer_id:
                    continue
                self._fold(r)
                folded += 1
            return folded

    def set_capacity(self, alive: int, total: int) -> dict:
        """Reassess the load-shed floor for the pool's live capacity:
        with ``alive`` of ``total`` workers left, only the top
        ``ceil(classes * alive/total)`` priority classes stay runnable
        (never fewer than one) and lower classes' pending units PARK —
        reported as ``starved``, never failed or lost. Leased units are
        untouched; a recovered pool clears the floor the same way."""
        with self._lock:
            alive = max(int(alive), 0)
            total = max(int(total), 0)
            floor: int | None = None
            if total > 0 and alive < total:
                prios = sorted(
                    {job["priority"] for job in self._jobs.values()
                     if any(self._units[u]["status"] in ("pending", "leased")
                            for u in job["units"])},
                    reverse=True)
                if prios:
                    keep = max(1, math.ceil(len(prios) * alive / total))
                    if keep < len(prios):
                        floor = prios[keep - 1]
            if floor != self._shed_floor:
                self._shed_floor = floor
                self._fold(self._journal.append(
                    "shed", floor=floor, alive=alive, total=total))
                starved = self._starved_locked()
                if self._telemetry is not None:
                    if floor is not None:
                        self._telemetry.mitigation(
                            mtype="load_shed", floor=floor, reason=(
                                f"pool at {alive}/{total} workers: parking "
                                f"pending units below priority {floor} "
                                f"({starved} starved) so the surviving "
                                "capacity drains the highest classes"))
                    else:
                        self._telemetry.mitigation(
                            mtype="load_shed_cleared", reason=(
                                f"pool back at {alive}/{total} workers: "
                                "parked units released"))
            return {"floor": self._shed_floor,
                    "starved": self._starved_locked()}

    def _starved_locked(self) -> int:
        starved = 0
        for e in self._units.values():
            if e["status"] != "pending":
                continue
            job = self._jobs.get(e["unit"].job_id)
            if job is not None and self._parked_locked(job):
                starved += 1
        return starved

    def parked_only(self) -> bool:
        """True when the queue is blocked SOLELY by load shedding: no
        live leases and every pending unit parked below the shed floor.
        The pool uses this to idle cheaply (or exit) instead of
        busy-spinning on a queue that cannot progress until capacity
        returns; backoff/quarantine holds do NOT count — those horizons
        pass on their own."""
        with self._lock:
            if self._shed_floor is None:
                return False
            saw_parked = False
            for e in self._units.values():
                if e["status"] == "leased":
                    return False
                if e["status"] != "pending":
                    continue
                job = self._jobs.get(e["unit"].job_id)
                if job is None or not self._parked_locked(job):
                    return False
                saw_parked = True
            return saw_parked

    def job_units_terminal(self, job_id: str) -> bool:
        """True when every unit of ``job_id`` is done or failed — the
        submit-only controller's poll condition (a job can be terminal-
        FAILED while stragglers still run; the controller must wait for
        the units, not the job status)."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None or not job["units"]:
                return False
            return all(self._units[u]["status"] in ("done", "failed")
                       for u in job["units"])

    def job_unit_counts(self, job_id: str) -> dict:
        """One job's unit outcome tally — the submit-only controller's
        live progress view while it polls a shared fleet journal."""
        with self._lock:
            job = self._jobs.get(job_id)
            units = [self._units[u] for u in (job["units"] if job else ())]
            return {
                "total": len(units),
                "done": sum(1 for u in units if u["status"] == "done"),
                "failed": sum(1 for u in units
                              if u["status"] == "failed"),
            }

    # ------------------------------------------------------------- queries
    def drained(self) -> bool:
        """True when every unit is terminal (done or failed)."""
        with self._lock:
            return all(e["status"] in ("done", "failed")
                       for e in self._units.values())

    def has_pending(self) -> bool:
        with self._lock:
            return any(e["status"] == "pending"
                       for e in self._units.values())

    def unit(self, unit_id: str) -> dict:
        with self._lock:
            entry = self._units[unit_id]
            return {"unit": entry["unit"], "status": entry["status"],
                    "attempts": entry["attempts"],
                    "not_before": entry["not_before"]}

    def status(self) -> dict:
        """Queue snapshot for the CLI / tests: per-job, per-tenant, and
        aggregate unit state counts. ``counts`` keeps its original four
        keys (a parked unit still counts ``pending``); the fleet view
        lives in ``tenants`` / ``starved`` / ``shed_floor``."""
        with self._lock:
            counts = {"pending": 0, "leased": 0, "done": 0, "failed": 0}
            tenants: dict[str, dict] = {}
            units = []
            for unit_id in self._order:
                entry = self._units[unit_id]
                counts[entry["status"]] += 1
                lease = entry.get("lease")
                job = self._jobs.get(entry["unit"].job_id)
                tenant = job["tenant"] if job is not None else "default"
                starved = (entry["status"] == "pending" and job is not None
                           and self._parked_locked(job))
                quarantined = (entry["status"] == "pending"
                               and job is not None
                               and job.get("breaker") is not None)
                tstats = tenants.setdefault(tenant, {
                    "pending": 0, "leased": 0, "starved": 0,
                    "quarantined": 0, "done": 0, "failed": 0})
                tstats[entry["status"]] += 1
                if starved:
                    tstats["starved"] += 1
                if quarantined:
                    tstats["quarantined"] += 1
                units.append({
                    "unit_id": unit_id, "status": entry["status"],
                    "beta": entry["unit"].beta, "seed": entry["unit"].seed,
                    "attempts": entry["attempts"],
                    "worker": lease.get("worker") if lease else None,
                    "job_id": entry["unit"].job_id, "tenant": tenant,
                    "starved": starved,
                })
            for tenant, tstats in tenants.items():
                waits = sorted(self._tenant_waits.get(tenant, ()))
                tstats["service"] = self._service.get(tenant, 0.0)
                tstats["weight"] = self.policy.for_tenant(tenant).weight
                tstats["queue_wait_p50_s"] = _pctl(waits, 0.50)
                tstats["queue_wait_p99_s"] = _pctl(waits, 0.99)
                tstats["admission_rejected"] = (
                    self._admission_rejects.get(tenant, 0))
            for tenant, rejects in self._admission_rejects.items():
                # a tenant rejected before landing any unit still shows up
                if tenant not in tenants:
                    tenants[tenant] = {
                        "pending": 0, "leased": 0, "starved": 0,
                        "quarantined": 0, "done": 0, "failed": 0,
                        "service": self._service.get(tenant, 0.0),
                        "weight": self.policy.for_tenant(tenant).weight,
                        "queue_wait_p50_s": None, "queue_wait_p99_s": None,
                        "admission_rejected": rejects}
            jobs = {
                job_id: {
                    "status": job["status"],
                    "retries_used": job["retries_used"],
                    "retry_budget": job["spec"].retry_budget,
                    "units": len(job["units"]),
                    "name": job["spec"].name,
                    "tenant": job["tenant"],
                    "study": job["study"],
                    "priority": job["priority"],
                    "consec_fails": job["consec_fails"],
                    "breaker_open": job.get("breaker") is not None,
                    "breaker_trips": job["breaker_trips"],
                }
                for job_id, job in self._jobs.items()
            }
            return {"jobs": jobs, "units": units, "counts": counts,
                    "tenants": tenants,
                    "starved": self._starved_locked(),
                    "shed_floor": self._shed_floor,
                    "drained": all(e["status"] in ("done", "failed")
                                   for e in self._units.values())}

    def starved(self) -> int:
        with self._lock:
            return self._starved_locked()

    def _maybe_finish_job(self, job_id: str) -> None:
        job = self._jobs.get(job_id)
        if job is None or job["status"] != "running":
            return
        if all(self._units[u]["status"] == "done" for u in job["units"]):
            self._fold(self._journal.append("job_done", job_id=job_id))
            if self._telemetry is not None:
                self._telemetry.job(job_id=job_id, action="done",
                                    units=len(job["units"]))

    def close(self) -> None:
        self._journal.close()


def _pctl(sorted_vals: Sequence[float], q: float) -> float | None:
    """Nearest-rank percentile of an ascending list; None when empty."""
    if not sorted_vals:
        return None
    idx = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return round(float(sorted_vals[idx]), 6)


def parked_snapshot(path: str) -> dict:
    """Journal-only view of how parked the queue died: unit terminality
    plus the last journaled shed floor, WITHOUT opening a writer.

    The watchdog uses this to tell 'the pool exited with every runnable
    unit starved below the shed floor' (a healthy idle fleet — relaunch
    budget-free) apart from zero-progress crash-looping (budgeted).
    Returns ``nonterminal`` / ``parked`` / ``terminal`` counts and the
    ``floor``; ``parked == nonterminal > 0`` is the all-parked signal.
    """
    records, _ = read_journal(path)
    status: dict[str, str] = {}
    unit_job: dict[str, str] = {}
    job_prio: dict[str, int] = {}
    floor = None
    for r in records:
        kind = r.get("kind")
        if kind == "job":
            spec = r.get("spec") or {}
            job_prio[r.get("job_id", "")] = int(spec.get("priority", 0) or 0)
        elif kind == "unit":
            status[r["unit_id"]] = "pending"
            unit_job[r["unit_id"]] = r.get("job_id", "")
        elif kind == "lease":
            if r.get("unit_id") in status:
                status[r["unit_id"]] = "leased"
        elif kind in ("release", "expire"):
            if r.get("unit_id") in status:
                status[r["unit_id"]] = "pending"
        elif kind == "fail":
            if r.get("unit_id") in status:
                status[r["unit_id"]] = ("pending" if r.get("requeued")
                                        else "failed")
        elif kind == "done":
            if r.get("unit_id") in status:
                status[r["unit_id"]] = "done"
        elif kind == "shed":
            floor = r.get("floor")
    nonterminal = [u for u, s in status.items() if s in ("pending", "leased")]
    parked = [u for u in nonterminal
              if status[u] == "pending" and floor is not None
              and job_prio.get(unit_job.get(u, ""), 0) < floor]
    return {"nonterminal": len(nonterminal), "parked": len(parked),
            "terminal": len(status) - len(nonterminal), "floor": floor}
