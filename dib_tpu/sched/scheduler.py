"""Lease-based β-grid scheduler: jobs in, chunk-resumable work units out.

The scheduling model (docs/robustness.md "Sweep as a service"):

  - A **job** is a β grid × seed ensemble (dense grids via
    :func:`dense_beta_grid`, refinement around info-plane transitions via
    :func:`refine_beta_grid`, explicit lists) plus training parameters
    and a per-job retry budget. Submission decomposes it into one
    **work unit** per (β, seed) — each independently trainable and
    chunk-resumable (the unit runner checkpoints every chunk and resumes
    from the newest intact step, so a unit can die and continue anywhere).
  - Workers **acquire** units under a lease: a grant names the worker,
    carries a wall-clock deadline, and must be renewed (the worker's
    chunk-boundary heartbeat) before it expires. The oldest eligible
    pending unit wins (FIFO, honoring retry backoff holds).
  - **Work-stealing**: :meth:`Scheduler.reap` re-queues any unit whose
    lease deadline passed — a straggler, a dead worker, a vanished pool —
    and the next ``acquire`` hands it to a live worker, which resumes
    from the unit's newest intact checkpoint. The superseded lease is
    remembered: a completion or renewal under it is **rejected**
    (returns False), so a presumed-dead worker that comes back cannot
    double-execute a unit or overwrite the thief's result.
  - **Retry with backoff**: a failed unit re-queues with an exponential
    not-before hold (``backoff_base_s * 2**(attempt-1)``) against the
    job's retry budget; exhaustion marks the unit AND the job failed
    (``retry_exhausted`` mitigation) instead of retrying forever.
  - **Graceful degradation**: lease expiry and cooperative preemption
    (:meth:`release`) re-queue budget-free — a dying worker is the
    pool's problem, never the job's (the watchdog's budget-free rc-75
    relaunch, at the scheduling layer).

Durability: every transition is journaled BEFORE the in-memory state
changes (``sched/journal.py``); construction replays the journal, so a
SIGKILLed scheduler restarts into the exact queue it died with, torn
final line tolerated (surfaced as a ``journal_recovered`` mitigation).

Telemetry: with an ``EventWriter``, transitions land as typed ``job`` /
``lease`` events on the run's events.jsonl (docs/observability.md), and
recovery actions as ``mitigation`` events (``lease_stolen``,
``retry_exhausted``, ``preempt_requeue``, ``journal_recovered``) — the
same stream the chaos suite's faults land on, so ``telemetry summarize``
joins injections with the scheduler's reactions.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
import uuid
from typing import Sequence

from dib_tpu.sched.journal import JobJournal, read_journal

__all__ = ["JobSpec", "Lease", "Scheduler", "WorkUnit", "dense_beta_grid",
           "refine_beta_grid"]


# ------------------------------------------------------------------ grids
def dense_beta_grid(start: float, stop: float, num: int) -> list[float]:
    """``num`` log-spaced β endpoints in [start, stop] — the dense-grid
    job shape (the paper's info plane is log-β structured, so linear
    spacing would waste most of the grid on the top decade)."""
    if num < 1 or start <= 0 or stop <= 0 or stop < start:
        raise ValueError(
            f"dense_beta_grid needs 0 < start <= stop and num >= 1; got "
            f"start={start}, stop={stop}, num={num}"
        )
    if num == 1:
        return [float(start)]
    lo, hi = math.log10(start), math.log10(stop)
    return [round(10 ** (lo + (hi - lo) * i / (num - 1)), 10)
            for i in range(num)]


def refine_beta_grid(around: Sequence[float], num: int = 4,
                     span_decades: float = 0.25) -> list[float]:
    """Refinement grid around info-plane transition βs: ``num`` log-spaced
    points within ±``span_decades`` of each center, merged/deduped/sorted.

    ``around`` is typically the β values of ``transition`` events
    (telemetry/slo.py detects per-channel KL threshold crossings) — the
    machine-readable signal this scheduler's refinement jobs key on.
    """
    out: set[float] = set()
    for center in around:
        if center <= 0:
            raise ValueError(f"refinement center must be positive, got {center}")
        out.update(dense_beta_grid(
            10 ** (math.log10(center) - span_decades),
            10 ** (math.log10(center) + span_decades), num,
        ))
    return sorted(out)


# ------------------------------------------------------------- dataclasses
@dataclasses.dataclass(frozen=True)
class JobSpec:
    """One β-grid job: the grid, the seeds, the training parameters the
    unit runner needs, and the job's retry budget."""

    betas: tuple[float, ...]
    seeds: tuple[int, ...] = (0,)
    train: dict = dataclasses.field(default_factory=dict)
    retry_budget: int = 3
    name: str = ""

    def __post_init__(self):
        if not self.betas:
            raise ValueError("a job needs at least one β endpoint")
        if not self.seeds:
            raise ValueError("a job needs at least one seed")
        if self.retry_budget < 0:
            raise ValueError("retry_budget must be >= 0")

    def to_dict(self) -> dict:
        return {
            "betas": [float(b) for b in self.betas],
            "seeds": [int(s) for s in self.seeds],
            "train": dict(self.train),
            "retry_budget": int(self.retry_budget),
            "name": self.name,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "JobSpec":
        return cls(
            betas=tuple(d.get("betas") or ()),
            seeds=tuple(d.get("seeds") or (0,)),
            train=dict(d.get("train") or {}),
            retry_budget=int(d.get("retry_budget", 3)),
            name=d.get("name", ""),
        )


@dataclasses.dataclass(frozen=True)
class WorkUnit:
    """One (β, seed) training unit of a job — the unit of leasing,
    stealing, retrying, and checkpoint-resumable execution."""

    unit_id: str
    job_id: str
    beta: float
    seed: int
    train: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class Lease:
    """One grant of one unit to one worker, valid until ``expires_t``."""

    unit_id: str
    lease_id: str
    worker: str
    expires_t: float
    attempt: int


class Scheduler:
    """The persistent β-grid scheduler over one journal directory.

    ``clock`` is injectable (tests drive lease expiry without sleeping);
    everything else reads wall-clock. All public methods are thread-safe
    — pool workers acquire/renew/complete concurrently.
    """

    def __init__(self, directory: str, telemetry=None,
                 lease_s: float = 60.0, backoff_base_s: float = 0.5,
                 clock=time.time, ctx=None):
        from dib_tpu.telemetry.context import from_env

        self.directory = directory
        self.lease_s = float(lease_s)
        self.backoff_base_s = float(backoff_base_s)
        self._telemetry = telemetry
        # the cross-plane trace context submissions are journaled under
        # (telemetry/context.py): the caller's lineage (a study round, a
        # CLI --trace-id) or whatever a parent process pinned via env —
        # job records carry it verbatim, unit records carry a child ctx
        # whose parent is the sched:job:<job_id> ref
        self._ctx = ctx if ctx is not None else from_env()
        self._clock = clock
        self._lock = threading.RLock()
        self._jobs: dict[str, dict] = {}
        self._units: dict[str, dict] = {}
        self._order: list[str] = []      # unit submission order (FIFO base)
        self.replayed_records = 0
        self.replayed_torn = 0
        records, torn = read_journal(directory)
        for record in records:
            self._fold(record)
        self.replayed_records = len(records)
        self.replayed_torn = torn
        # journal opened AFTER replay: the replay must never read the
        # fd this instance is about to append with
        self._journal = JobJournal(directory)
        if torn:
            # crash recovery is never silent: a torn line means a writer
            # died mid-append and the transition it was recording is
            # re-derived from the surviving state
            if telemetry is not None:
                telemetry.mitigation(
                    mtype="journal_recovered", detail=(
                        f"replayed {len(records)} journal record(s), "
                        f"skipped {torn} torn line(s)"),
                )

    # -------------------------------------------------------------- replay
    def _fold(self, r: dict) -> None:
        """Apply one journal record to the in-memory state (replay path;
        the live paths journal first, then call this)."""
        kind = r.get("kind")
        if kind == "job":
            self._jobs[r["job_id"]] = {
                "spec": JobSpec.from_dict(r.get("spec") or {}),
                "status": "running", "retries_used": 0, "units": [],
            }
        elif kind == "unit":
            unit = WorkUnit(
                unit_id=r["unit_id"], job_id=r["job_id"],
                beta=float(r["beta"]), seed=int(r["seed"]),
                train=dict(r.get("train") or {}),
            )
            self._units[unit.unit_id] = {
                "unit": unit, "status": "pending", "attempts": 0,
                "not_before": 0.0, "lease": None,
                "enqueue_t": r.get("t", 0.0),
            }
            self._order.append(unit.unit_id)
            job = self._jobs.get(unit.job_id)
            if job is not None:
                job["units"].append(unit.unit_id)
        elif kind == "lease":
            entry = self._units.get(r["unit_id"])
            if entry is not None:
                entry["status"] = "leased"
                entry["lease"] = {
                    "lease_id": r["lease_id"], "worker": r.get("worker"),
                    "expires_t": r.get("expires_t", 0.0),
                    "attempt": r.get("attempt", 0),
                }
        elif kind == "renew":
            entry = self._units.get(r["unit_id"])
            if entry is not None and entry.get("lease") \
                    and entry["lease"]["lease_id"] == r.get("lease_id"):
                entry["lease"]["expires_t"] = r.get("expires_t", 0.0)
        elif kind in ("release", "expire"):
            entry = self._units.get(r["unit_id"])
            if entry is not None:
                # superseding is implemented by clearing the live lease:
                # _current() compares lease ids against it, so every
                # older lease is rejected from here on
                entry["status"] = "pending"
                entry["lease"] = None
                entry["enqueue_t"] = r.get("t", 0.0)
        elif kind == "fail":
            entry = self._units.get(r["unit_id"])
            if entry is not None:
                entry["attempts"] += 1
                entry["lease"] = None
                if r.get("requeued"):
                    entry["status"] = "pending"
                    entry["not_before"] = r.get("not_before", 0.0)
                    entry["enqueue_t"] = r.get("t", 0.0)
                else:
                    entry["status"] = "failed"
                job = self._jobs.get(entry["unit"].job_id)
                # only an actual RETRY spends the budget: the final,
                # non-requeued failure is the budget being enforced, and
                # counting it would report retries = budget+1 and trip
                # the sched_retry_ceiling SLO on correct fail-fast
                if job is not None and r.get("requeued"):
                    job["retries_used"] += 1
        elif kind == "done":
            entry = self._units.get(r["unit_id"])
            if entry is not None:
                entry["status"] = "done"
                entry["lease"] = None
                entry["result"] = r.get("result")
        elif kind == "job_done":
            job = self._jobs.get(r["job_id"])
            if job is not None:
                job["status"] = "done"
        elif kind == "job_failed":
            job = self._jobs.get(r["job_id"])
            if job is not None:
                job["status"] = "failed"

    # --------------------------------------------------------------- submit
    def submit(self, spec: JobSpec) -> str:
        """Decompose a job into (β, seed) units and enqueue them FIFO.
        Returns the job id."""
        with self._lock:
            job_id = f"job-{len(self._jobs):04d}-{uuid.uuid4().hex[:6]}"
            job_extra = ({"ctx": self._ctx.to_dict()}
                         if self._ctx is not None else {})
            self._fold(self._journal.append(
                "job", job_id=job_id, spec=spec.to_dict(), **job_extra))
            unit_ctx = (self._ctx.child(f"sched:job:{job_id}",
                                        origin="sched")
                        if self._ctx is not None else None)
            unit_extra = ({"ctx": unit_ctx.to_dict()}
                          if unit_ctx is not None else {})
            for i, beta in enumerate(spec.betas):
                for seed in spec.seeds:
                    unit_id = f"{job_id}/u{i:03d}s{seed}"
                    self._fold(self._journal.append(
                        "unit", unit_id=unit_id, job_id=job_id,
                        beta=float(beta), seed=int(seed),
                        train=dict(spec.train), **unit_extra))
            if self._telemetry is not None:
                self._telemetry.job(
                    job_id=job_id, action="submitted",
                    units=len(spec.betas) * len(spec.seeds),
                    betas=[float(b) for b in spec.betas],
                    seeds=[int(s) for s in spec.seeds],
                    retry_budget=spec.retry_budget)
            return job_id

    # -------------------------------------------------------------- leasing
    def acquire(self, worker: str, lease_s: float | None = None) -> Lease | None:
        """Lease the oldest eligible pending unit to ``worker``; None when
        nothing is currently eligible (empty queue or backoff holds)."""
        with self._lock:
            now = self._clock()
            for unit_id in self._order:
                entry = self._units[unit_id]
                if entry["status"] != "pending" or entry["not_before"] > now:
                    continue
                attempt = entry["attempts"] + 1
                lease = Lease(
                    unit_id=unit_id,
                    lease_id=f"{unit_id}#a{attempt}-{uuid.uuid4().hex[:6]}",
                    worker=worker,
                    expires_t=now + (lease_s or self.lease_s),
                    attempt=attempt,
                )
                queue_wait = max(now - entry["enqueue_t"], 0.0)
                self._fold(self._journal.append(
                    "lease", unit_id=unit_id, lease_id=lease.lease_id,
                    worker=worker, expires_t=lease.expires_t,
                    attempt=attempt))
                if self._telemetry is not None:
                    self._telemetry.lease(
                        unit=unit_id, action="granted", worker=worker,
                        lease=lease.lease_id,
                        job_id=entry["unit"].job_id,
                        expires_s=round(lease.expires_t - now, 3),
                        queue_wait_s=round(queue_wait, 3),
                        attempt=attempt)
                return lease
            return None

    def _current(self, lease: Lease) -> dict | None:
        """The unit entry iff ``lease`` is still the unit's live lease."""
        entry = self._units.get(lease.unit_id)
        if entry is None or entry.get("lease") is None:
            return None
        if entry["lease"]["lease_id"] != lease.lease_id:
            return None
        return entry

    def _reject_stale(self, lease: Lease, action: str) -> bool:
        if self._telemetry is not None:
            self._telemetry.lease(
                unit=lease.unit_id, action="rejected", worker=lease.worker,
                lease=lease.lease_id, reason=f"superseded lease ({action})")
        return False

    def renew(self, lease: Lease, lease_s: float | None = None) -> bool:
        """Extend a live lease (the worker's heartbeat). False when the
        lease was superseded — the caller must ABANDON the unit: someone
        else owns it now, and continuing would double-execute it."""
        with self._lock:
            entry = self._current(lease)
            if entry is None:
                return self._reject_stale(lease, "renew")
            expires_t = self._clock() + (lease_s or self.lease_s)
            self._fold(self._journal.append(
                "renew", unit_id=lease.unit_id, lease_id=lease.lease_id,
                expires_t=expires_t))
            if self._telemetry is not None:
                self._telemetry.lease(
                    unit=lease.unit_id, action="renewed",
                    worker=lease.worker, lease=lease.lease_id)
            return True

    # ------------------------------------------------------------ terminals
    def complete(self, lease: Lease, result: dict | None = None) -> bool:
        """Mark the unit done. False (and NO state change) under a
        superseded lease — the double-execution guard: the thief's result
        stands, the returned worker's is dropped."""
        with self._lock:
            entry = self._current(lease)
            if entry is None:
                return self._reject_stale(lease, "complete")
            unit = entry["unit"]
            self._fold(self._journal.append(
                "done", unit_id=lease.unit_id, lease_id=lease.lease_id,
                result=result))
            if self._telemetry is not None:
                self._telemetry.job(
                    job_id=unit.job_id, action="unit_done",
                    unit=lease.unit_id, worker=lease.worker,
                    beta=unit.beta, seed=unit.seed)
            self._maybe_finish_job(unit.job_id)
            return True

    def fail(self, lease: Lease, error: str) -> str | bool:
        """Record a unit failure: re-queue with exponential backoff while
        the job's retry budget lasts (returns ``"requeued"``), else mark
        the unit AND job failed (returns ``"exhausted"``). False under a
        superseded lease (the failure belongs to a stolen attempt)."""
        with self._lock:
            entry = self._current(lease)
            if entry is None:
                return self._reject_stale(lease, "fail")
            unit = entry["unit"]
            job = self._jobs[unit.job_id]
            budget = job["spec"].retry_budget
            requeued = job["retries_used"] < budget
            backoff = (self.backoff_base_s * (2 ** entry["attempts"])
                       if requeued else 0.0)
            self._fold(self._journal.append(
                "fail", unit_id=lease.unit_id, lease_id=lease.lease_id,
                error=str(error)[:500], requeued=requeued,
                not_before=self._clock() + backoff))
            if self._telemetry is not None:
                self._telemetry.job(
                    job_id=unit.job_id, action="unit_failed",
                    unit=lease.unit_id, error=str(error)[:300],
                    retries=job["retries_used"],
                    retry_budget=budget,
                    backoff_s=round(backoff, 3))
            if not requeued:
                self._fold(self._journal.append(
                    "job_failed", job_id=unit.job_id))
                if self._telemetry is not None:
                    self._telemetry.mitigation(
                        mtype="retry_exhausted", reason=(
                            f"unit {lease.unit_id} failed with the job's "
                            f"retry budget ({budget}) spent"),
                        detail=str(error)[:300])
                    self._telemetry.job(
                        job_id=unit.job_id, action="failed",
                        unit=lease.unit_id,
                        reason="retry budget exhausted")
                return "exhausted"
            return "requeued"

    def release(self, lease: Lease, reason: str = "preempt") -> bool:
        """Budget-free re-queue (cooperative preemption / clean worker
        shutdown): no attempt burned, no backoff hold — the exit-75
        contract at the scheduling layer."""
        with self._lock:
            entry = self._current(lease)
            if entry is None:
                return self._reject_stale(lease, "release")
            self._fold(self._journal.append(
                "release", unit_id=lease.unit_id, lease_id=lease.lease_id))
            if self._telemetry is not None:
                self._telemetry.lease(
                    unit=lease.unit_id, action="released",
                    worker=lease.worker, lease=lease.lease_id,
                    reason=reason)
                if reason == "preempt":
                    self._telemetry.mitigation(
                        mtype="preempt_requeue",
                        reason=(f"unit {lease.unit_id} re-enqueued "
                                "lease-free after cooperative preemption"))
            return True

    # ------------------------------------------------------- work-stealing
    def reap(self, now: float | None = None) -> list[str]:
        """Re-queue every unit whose lease deadline passed (straggler /
        dead worker / vanished pool). The next ``acquire`` hands each to
        a live worker — work-stealing; the superseded lease is rejected
        forever after."""
        with self._lock:
            now = self._clock() if now is None else now
            stolen = []
            for unit_id, entry in self._units.items():
                lease = entry.get("lease")
                if entry["status"] != "leased" or lease is None:
                    continue
                if lease["expires_t"] <= now:
                    self._expire_locked(unit_id, entry, "lease expired")
                    stolen.append(unit_id)
            return stolen

    def force_expire(self, unit_id: str, reason: str) -> bool:
        """Expire a unit's live lease NOW (reaper path for a provably dead
        worker; also the chaos suite's ``lease_expire`` injector)."""
        with self._lock:
            entry = self._units.get(unit_id)
            if entry is None or entry["status"] != "leased" \
                    or entry.get("lease") is None:
                return False
            self._expire_locked(unit_id, entry, reason)
            return True

    def _expire_locked(self, unit_id: str, entry: dict, reason: str) -> None:
        lease = entry["lease"]
        self._fold(self._journal.append(
            "expire", unit_id=unit_id, lease_id=lease["lease_id"],
            reason=reason))
        if self._telemetry is not None:
            self._telemetry.lease(
                unit=unit_id, action="expired", worker=lease.get("worker"),
                lease=lease["lease_id"], reason=reason)
            self._telemetry.mitigation(
                mtype="lease_stolen", reason=(
                    f"unit {unit_id} re-queued from worker "
                    f"{lease.get('worker')} ({reason}); the next acquire "
                    "resumes it from its newest intact checkpoint"))

    # ------------------------------------------------------------- queries
    def drained(self) -> bool:
        """True when every unit is terminal (done or failed)."""
        with self._lock:
            return all(e["status"] in ("done", "failed")
                       for e in self._units.values())

    def has_pending(self) -> bool:
        with self._lock:
            return any(e["status"] == "pending"
                       for e in self._units.values())

    def unit(self, unit_id: str) -> dict:
        with self._lock:
            entry = self._units[unit_id]
            return {"unit": entry["unit"], "status": entry["status"],
                    "attempts": entry["attempts"],
                    "not_before": entry["not_before"]}

    def status(self) -> dict:
        """Queue snapshot for the CLI / tests: per-job and aggregate unit
        state counts."""
        with self._lock:
            counts = {"pending": 0, "leased": 0, "done": 0, "failed": 0}
            units = []
            for unit_id in self._order:
                entry = self._units[unit_id]
                counts[entry["status"]] += 1
                lease = entry.get("lease")
                units.append({
                    "unit_id": unit_id, "status": entry["status"],
                    "beta": entry["unit"].beta, "seed": entry["unit"].seed,
                    "attempts": entry["attempts"],
                    "worker": lease.get("worker") if lease else None,
                })
            jobs = {
                job_id: {
                    "status": job["status"],
                    "retries_used": job["retries_used"],
                    "retry_budget": job["spec"].retry_budget,
                    "units": len(job["units"]),
                    "name": job["spec"].name,
                }
                for job_id, job in self._jobs.items()
            }
            return {"jobs": jobs, "units": units, "counts": counts,
                    "drained": all(e["status"] in ("done", "failed")
                                   for e in self._units.values())}

    def _maybe_finish_job(self, job_id: str) -> None:
        job = self._jobs.get(job_id)
        if job is None or job["status"] != "running":
            return
        if all(self._units[u]["status"] == "done" for u in job["units"]):
            self._fold(self._journal.append("job_done", job_id=job_id))
            if self._telemetry is not None:
                self._telemetry.job(job_id=job_id, action="done",
                                    units=len(job["units"]))

    def close(self) -> None:
        self._journal.close()
