"""Per-unit trainer: one β point × seed — or one whole β-sweep on a mesh —
chunk-checkpointed, resumable.

The runner is where the scheduling layer meets the PR 4/5 worker
machinery: every unit trains with a ``CheckpointHook`` at every chunk
boundary under the unit's OWN directory
(``<base_dir>/units/<unit_id>/ckpt``), and a unit that arrives with a
checkpoint on disk — because its previous holder was killed, preempted,
or stalled out of its lease — resumes from the newest intact step via
``restore_latest_intact`` and continues the exact PRNG chain. The
``DIBCheckpointer`` chunk-size contract makes the continuation
bit-identical to an uninterrupted run, which is precisely what the chaos
suite asserts per β (``CHAOS_SCHED.json``).

Boundary hook order is load-bearing:

  1. the pool's ``heartbeat`` (lease renewal) runs FIRST, so a worker
     whose lease was stolen aborts with ``LeaseLost`` *before* touching
     the unit's checkpoint directory or artifacts — the thief may
     already be writing there;
  2. the ``CheckpointHook`` persists the clean chunk-aligned state;
  3. the injected ``boundary_hook`` (the chaos suite's fault injector)
     runs LAST, so a kill/preempt fault always finds the checkpoint it
     will be resumed from already durable — the ``apply_train_fault``
     ordering, one layer up.

Mesh units: a unit whose train spec carries ``betas`` (a list of end-β
values) trains the WHOLE grid as one ``BetaSweepTrainer`` on the mesh the
runner was handed (``TrainingUnitRunner(mesh=...)``) — the scheduler
gives one job a whole mesh instead of one device. Resume goes through
``parallel/elastic.py:restore_sweep_resharded``, so a unit stolen by a
worker with a DIFFERENT mesh (or re-submitted at a different grid width)
reshards its checkpoint instead of wedging: matched members continue
bit-identically, the mesh layout is whatever the new holder has.
"""

from __future__ import annotations

import os

__all__ = ["DEFAULT_TRAIN_SPEC", "TrainingUnitRunner"]

#: Training-spec defaults for a unit (the fault-drill tiny-model scale —
#: the scheduler schedules; callers size the science via JobSpec.train).
DEFAULT_TRAIN_SPEC: dict = {
    "dataset": "boolean_circuit",
    "encoder_hidden": (8,),
    "integration_hidden": (16,),
    "embedding_dim": 2,
    "batch_size": 64,
    "beta_start": 1e-4,
    "num_pretraining_epochs": 2,
    "num_annealing_epochs": 6,
    "steps_per_epoch": 2,
    "max_val_points": 128,
    "chunk_epochs": 2,
}


class TrainingUnitRunner:
    """Builds and fits one ``DIBTrainer`` per work unit.

    ``boundary_hook(unit, epoch)``, when given, is called at every chunk
    boundary after the checkpoint hook — the chaos suite raises its
    faults (``WorkerKilled`` / ``TrainingPreempted``) from it.
    ``preempt`` (a ``PreemptionGuard``) is forwarded to ``fit`` so a
    pool-level SIGTERM checkpoints chunk-aligned and unwinds
    cooperatively.
    """

    def __init__(self, base_dir: str, telemetry=None, boundary_hook=None,
                 preempt=None, mesh=None):
        self.base_dir = base_dir
        self._telemetry = telemetry
        self._boundary_hook = boundary_hook
        self._preempt = preempt
        # the whole mesh this runner's units may use (sweep units; None =
        # single-device serial units, the legacy shape)
        self._mesh = mesh

    def unit_dir(self, unit) -> str:
        return os.path.join(self.base_dir, "units",
                            unit.unit_id.replace("/", "__"))

    def history_path(self, unit) -> str:
        return os.path.join(self.unit_dir(unit), "history.npz")

    def _fallback_reporter(self, info: dict) -> None:
        """A corrupt step skipped during a unit resume is a mitigation
        (plus a ``quarantine`` event for the moved step) on the
        scheduler's stream — recovery is never silent."""
        from dib_tpu.train.checkpoint import fallback_reporter

        fallback_reporter(self._telemetry, source="sched unit resume",
                          log=lambda msg: None)(info)

    def __call__(self, unit, heartbeat=None) -> dict:
        import jax
        import numpy as np

        from dib_tpu.data import get_dataset
        from dib_tpu.models import DistributedIBModel
        from dib_tpu.train import (
            CheckpointHook,
            DIBCheckpointer,
            DIBTrainer,
            TrainConfig,
        )

        spec = dict(DEFAULT_TRAIN_SPEC)
        spec.update(unit.train or {})
        bundle = get_dataset(spec["dataset"])
        model = DistributedIBModel(
            feature_dimensionalities=tuple(bundle.feature_dimensionalities),
            encoder_hidden=tuple(spec["encoder_hidden"]),
            integration_hidden=tuple(spec["integration_hidden"]),
            output_dim=bundle.output_dimensionality,
            embedding_dim=int(spec["embedding_dim"]),
        )
        config = TrainConfig(
            batch_size=int(spec["batch_size"]),
            beta_start=float(spec["beta_start"]),
            beta_end=float(unit.beta),
            num_pretraining_epochs=int(spec["num_pretraining_epochs"]),
            num_annealing_epochs=int(spec["num_annealing_epochs"]),
            steps_per_epoch=int(spec["steps_per_epoch"]),
            max_val_points=int(spec["max_val_points"]),
        )
        if spec.get("betas"):
            # mesh unit: the whole β grid as ONE sweep on the runner's mesh
            return self._run_sweep_unit(unit, spec, heartbeat, model,
                                        bundle, config)
        trainer = DIBTrainer(model, bundle, config)
        chunk = int(spec["chunk_epochs"])
        udir = self.unit_dir(unit)
        os.makedirs(udir, exist_ok=True)
        ckpt = DIBCheckpointer(os.path.join(udir, "ckpt"))

        hooks = []
        if heartbeat is not None:
            # FIRST: a stolen lease aborts here, before any write
            hooks.append(lambda trainer, state, epoch: heartbeat())
        hooks.append(CheckpointHook(ckpt))
        if self._boundary_hook is not None:
            boundary_hook = self._boundary_hook
            hooks.append(
                lambda trainer, state, epoch: boundary_hook(unit, epoch))

        try:
            resume_state = resume_history = None
            remaining = None
            key = jax.random.key(int(unit.seed))
            if ckpt.latest_step is not None:
                # a retried/stolen unit continues its own trajectory: the
                # newest INTACT step (a step torn by the previous holder's
                # death must not wedge the retry)
                resume_state, resume_history, key = ckpt.restore_latest_intact(
                    trainer, chunk_size=chunk,
                    on_fallback=self._fallback_reporter,
                )
                done = int(jax.device_get(resume_state.epoch))
                remaining = max(config.num_epochs - done, 0)
            _, history = trainer.fit(
                key, num_epochs=remaining, hooks=hooks, hook_every=chunk,
                state=resume_state, history=resume_history,
                preempt=self._preempt,
            )
        finally:
            ckpt.close()

        bits = history.to_bits(bundle.loss_is_info_based)
        np.savez(self.history_path(unit),
                 beta=bits.beta, kl_per_feature=bits.kl_per_feature,
                 loss=bits.loss, val_loss=bits.val_loss)
        return {
            "beta": float(unit.beta),
            "seed": int(unit.seed),
            "epochs": int(bits.loss.shape[0]),
            "final_loss": float(bits.loss[-1]),
            "final_val_loss": float(bits.val_loss[-1]),
            "history_path": self.history_path(unit),
        }

    def _run_sweep_unit(self, unit, spec, heartbeat, model, bundle,
                        config) -> dict:
        """One WHOLE β-sweep as a single unit on the runner's mesh.

        The unit's ``betas`` spec is the logical grid; the mesh (if any)
        is whatever this runner was handed — a unit resumed on a holder
        with a different mesh, or re-submitted at a different width,
        reshards through ``restore_sweep_resharded`` (matched members
        continue bit-identically; new members need ``unit.seed``-derived
        keys). The hook order contract is the serial unit's."""
        import jax
        import numpy as np

        from dib_tpu.parallel import BetaSweepTrainer, restore_sweep_resharded
        from dib_tpu.train import CheckpointHook, DIBCheckpointer

        ends = [float(b) for b in spec["betas"]]
        sweep = BetaSweepTrainer(
            model, bundle, config, float(spec["beta_start"]), ends,
            mesh=self._mesh,
        )
        chunk = int(spec["chunk_epochs"])
        udir = self.unit_dir(unit)
        os.makedirs(udir, exist_ok=True)
        ckpt = DIBCheckpointer(os.path.join(udir, "ckpt"))

        hooks = []
        if heartbeat is not None:
            # FIRST: a stolen lease aborts here, before any write (the
            # serial unit's hook-order contract, __call__ above)
            hooks.append(lambda trainer, state, epoch: heartbeat())
        hooks.append(CheckpointHook(ckpt))
        if self._boundary_hook is not None:
            boundary_hook = self._boundary_hook
            hooks.append(
                lambda trainer, state, epoch: boundary_hook(unit, epoch))

        try:
            resume_state = resume_history = None
            remaining = None
            keys = jax.random.split(jax.random.key(int(unit.seed)),
                                    sweep.num_replicas)
            if ckpt.latest_step is not None:
                # width- and mesh-portable resume: the previous holder may
                # have run a different mesh (or grid) — matched members
                # continue their exact trajectories
                resume_state, resume_history, keys, reshard_info = (
                    restore_sweep_resharded(
                        ckpt, sweep, chunk_size=chunk,
                        # folded namespace, NOT key(seed + 1): consecutive
                        # unit seeds are the natural grid convention, and
                        # key(seed + 1) IS the cold-start stream of the
                        # seed+1 unit — two "independent" members would
                        # share init and noise draws
                        new_member_keys=jax.random.split(
                            jax.random.fold_in(
                                jax.random.key(int(unit.seed)), 1),
                            sweep.num_replicas),
                        on_fallback=self._fallback_reporter,
                        telemetry=self._telemetry,
                    )
                )
                member_epochs = np.asarray(jax.device_get(
                    resume_state.epoch)).astype(int).reshape(-1)
                done = int(member_epochs.max())
                remaining = max(config.num_epochs - done, 0)
                if (member_epochs < done).any():
                    resume_state, resume_history, keys = (
                        self._level_new_members(
                            model, bundle, config,
                            float(spec["beta_start"]), ends,
                            resume_state, resume_history, keys,
                            member_epochs, done, chunk, heartbeat))
            _, records = sweep.fit(
                keys, num_epochs=remaining, hooks=hooks, hook_every=chunk,
                states=resume_state, histories=resume_history,
                preempt=self._preempt,
            )
        finally:
            ckpt.close()

        bits = [r.to_bits(bundle.loss_is_info_based) for r in records]

        def stack_padded(arrs):
            # _level_new_members keeps grow-at-resume lanes rectangular,
            # but a preempted/partial lane can still fall short; NaN-pad
            # each lane's tail so the stacked npz stays rectangular
            # without inventing training that never ran
            epochs = max(a.shape[0] for a in arrs)
            return np.stack([
                np.pad(np.asarray(a, np.float64),
                       [(0, epochs - a.shape[0])] + [(0, 0)] * (a.ndim - 1),
                       constant_values=np.nan)
                for a in arrs
            ])

        np.savez(
            self.history_path(unit),
            beta=stack_padded([b.beta for b in bits]),
            kl_per_feature=stack_padded([b.kl_per_feature for b in bits]),
            loss=stack_padded([b.loss for b in bits]),
            val_loss=stack_padded([b.val_loss for b in bits]),
            beta_ends=np.asarray(ends),
        )
        return {
            "betas": ends,
            "replicas": sweep.num_replicas,
            "seed": int(unit.seed),
            "engine": sweep.engine,
            "epochs": max(int(b.loss.shape[0]) for b in bits),
            "final_loss": [float(b.loss[-1]) if b.loss.size else None
                           for b in bits],
            "final_val_loss": [float(b.val_loss[-1]) if b.val_loss.size
                               else None for b in bits],
            "history_path": self.history_path(unit),
        }

    def _level_new_members(self, model, bundle, config, beta_start, ends,
                           states, histories, keys, member_epochs, done,
                           chunk, heartbeat):
        """Bring grow-at-resume members up to the matched members' epoch.

        ``restore_sweep_resharded`` hands fresh members back at epoch 0
        while the matched members sit at ``done``; the lockstep fit
        advances every member by the SAME count, so without leveling a
        new member would finish ``done`` epochs short of its β schedule —
        zero epochs, on a unit that was already complete — while the unit
        still reported success. Each lagging group trains in its own
        carve-out sub-sweep (member lanes are embarrassingly parallel, so
        a carve-out realizes the same schedule) up to ``done`` and is
        spliced back; a retried unit replays the same seed-derived keys,
        so the top-up is deterministic. Returns the leveled
        ``(states, histories, keys)``."""
        import jax
        import numpy as np

        from dib_tpu.parallel import BetaSweepTrainer
        from dib_tpu.parallel.sweep import _splice_keys, _splice_member

        def member_gather(tree, idx):
            return jax.tree.map(lambda a: a[idx], tree)

        hooks = ([] if heartbeat is None
                 else [lambda trainer, state, epoch: heartbeat()])
        for epoch in sorted({int(e) for e in member_epochs if e < done}):
            idx = np.asarray([r for r, e in enumerate(member_epochs)
                              if int(e) == epoch])
            sub = BetaSweepTrainer(model, bundle, config, beta_start,
                                   [ends[int(r)] for r in idx])
            sub_states, _ = sub.fit(
                member_gather(keys, idx), num_epochs=done - epoch,
                hooks=hooks, hook_every=chunk,
                states=member_gather(states, idx),
                histories=member_gather(histories, idx),
                preempt=self._preempt,
            )
            sub_histories = sub.latest_history
            sub_keys = sub.resume_key
            for j, r in enumerate(idx.tolist()):
                states = _splice_member(states, sub_states, r, src=j)
                histories = _splice_member(histories, sub_histories, r,
                                           src=j)
                keys = _splice_keys(keys, r, sub_keys, src=j)
        return states, histories, keys
