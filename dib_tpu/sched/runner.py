"""Per-unit trainer: one β point × seed, chunk-checkpointed, resumable.

The runner is where the scheduling layer meets the PR 4/5 worker
machinery: every unit trains with a ``CheckpointHook`` at every chunk
boundary under the unit's OWN directory
(``<base_dir>/units/<unit_id>/ckpt``), and a unit that arrives with a
checkpoint on disk — because its previous holder was killed, preempted,
or stalled out of its lease — resumes from the newest intact step via
``restore_latest_intact`` and continues the exact PRNG chain. The
``DIBCheckpointer`` chunk-size contract makes the continuation
bit-identical to an uninterrupted run, which is precisely what the chaos
suite asserts per β (``CHAOS_SCHED.json``).

Boundary hook order is load-bearing:

  1. the pool's ``heartbeat`` (lease renewal) runs FIRST, so a worker
     whose lease was stolen aborts with ``LeaseLost`` *before* touching
     the unit's checkpoint directory or artifacts — the thief may
     already be writing there;
  2. the ``CheckpointHook`` persists the clean chunk-aligned state;
  3. the injected ``boundary_hook`` (the chaos suite's fault injector)
     runs LAST, so a kill/preempt fault always finds the checkpoint it
     will be resumed from already durable — the ``apply_train_fault``
     ordering, one layer up.
"""

from __future__ import annotations

import os

__all__ = ["DEFAULT_TRAIN_SPEC", "TrainingUnitRunner"]

#: Training-spec defaults for a unit (the fault-drill tiny-model scale —
#: the scheduler schedules; callers size the science via JobSpec.train).
DEFAULT_TRAIN_SPEC: dict = {
    "dataset": "boolean_circuit",
    "encoder_hidden": (8,),
    "integration_hidden": (16,),
    "embedding_dim": 2,
    "batch_size": 64,
    "beta_start": 1e-4,
    "num_pretraining_epochs": 2,
    "num_annealing_epochs": 6,
    "steps_per_epoch": 2,
    "max_val_points": 128,
    "chunk_epochs": 2,
}


class TrainingUnitRunner:
    """Builds and fits one ``DIBTrainer`` per work unit.

    ``boundary_hook(unit, epoch)``, when given, is called at every chunk
    boundary after the checkpoint hook — the chaos suite raises its
    faults (``WorkerKilled`` / ``TrainingPreempted``) from it.
    ``preempt`` (a ``PreemptionGuard``) is forwarded to ``fit`` so a
    pool-level SIGTERM checkpoints chunk-aligned and unwinds
    cooperatively.
    """

    def __init__(self, base_dir: str, telemetry=None, boundary_hook=None,
                 preempt=None):
        self.base_dir = base_dir
        self._telemetry = telemetry
        self._boundary_hook = boundary_hook
        self._preempt = preempt

    def unit_dir(self, unit) -> str:
        return os.path.join(self.base_dir, "units",
                            unit.unit_id.replace("/", "__"))

    def history_path(self, unit) -> str:
        return os.path.join(self.unit_dir(unit), "history.npz")

    def _fallback_reporter(self, info: dict) -> None:
        """A corrupt step skipped during a unit resume is a mitigation on
        the scheduler's stream — recovery is never silent."""
        if self._telemetry is not None:
            self._telemetry.mitigation(mtype="checkpoint_fallback", **info)

    def __call__(self, unit, heartbeat=None) -> dict:
        import jax
        import numpy as np

        from dib_tpu.data import get_dataset
        from dib_tpu.models import DistributedIBModel
        from dib_tpu.train import (
            CheckpointHook,
            DIBCheckpointer,
            DIBTrainer,
            TrainConfig,
        )

        spec = dict(DEFAULT_TRAIN_SPEC)
        spec.update(unit.train or {})
        bundle = get_dataset(spec["dataset"])
        model = DistributedIBModel(
            feature_dimensionalities=tuple(bundle.feature_dimensionalities),
            encoder_hidden=tuple(spec["encoder_hidden"]),
            integration_hidden=tuple(spec["integration_hidden"]),
            output_dim=bundle.output_dimensionality,
            embedding_dim=int(spec["embedding_dim"]),
        )
        config = TrainConfig(
            batch_size=int(spec["batch_size"]),
            beta_start=float(spec["beta_start"]),
            beta_end=float(unit.beta),
            num_pretraining_epochs=int(spec["num_pretraining_epochs"]),
            num_annealing_epochs=int(spec["num_annealing_epochs"]),
            steps_per_epoch=int(spec["steps_per_epoch"]),
            max_val_points=int(spec["max_val_points"]),
        )
        trainer = DIBTrainer(model, bundle, config)
        chunk = int(spec["chunk_epochs"])
        udir = self.unit_dir(unit)
        os.makedirs(udir, exist_ok=True)
        ckpt = DIBCheckpointer(os.path.join(udir, "ckpt"))

        hooks = []
        if heartbeat is not None:
            # FIRST: a stolen lease aborts here, before any write
            hooks.append(lambda trainer, state, epoch: heartbeat())
        hooks.append(CheckpointHook(ckpt))
        if self._boundary_hook is not None:
            boundary_hook = self._boundary_hook
            hooks.append(
                lambda trainer, state, epoch: boundary_hook(unit, epoch))

        try:
            resume_state = resume_history = None
            remaining = None
            key = jax.random.key(int(unit.seed))
            if ckpt.latest_step is not None:
                # a retried/stolen unit continues its own trajectory: the
                # newest INTACT step (a step torn by the previous holder's
                # death must not wedge the retry)
                resume_state, resume_history, key = ckpt.restore_latest_intact(
                    trainer, chunk_size=chunk,
                    on_fallback=self._fallback_reporter,
                )
                done = int(jax.device_get(resume_state.epoch))
                remaining = max(config.num_epochs - done, 0)
            _, history = trainer.fit(
                key, num_epochs=remaining, hooks=hooks, hook_every=chunk,
                state=resume_state, history=resume_history,
                preempt=self._preempt,
            )
        finally:
            ckpt.close()

        bits = history.to_bits(bundle.loss_is_info_based)
        np.savez(self.history_path(unit),
                 beta=bits.beta, kl_per_feature=bits.kl_per_feature,
                 loss=bits.loss, val_loss=bits.val_loss)
        return {
            "beta": float(unit.beta),
            "seed": int(unit.seed),
            "epochs": int(bits.loss.shape[0]),
            "final_loss": float(bits.loss[-1]),
            "final_val_loss": float(bits.val_loss[-1]),
            "history_path": self.history_path(unit),
        }
