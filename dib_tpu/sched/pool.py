"""Worker pool: drain the scheduler's queue, degrade gracefully, steal work.

The pool is the live half of the scheduling layer: N worker threads
acquire units under leases, run them through the unit runner
(``sched/runner.py``), renew their leases at chunk boundaries, and report
terminal outcomes back. Its failure semantics mirror the watchdog's
(docs/robustness.md):

  - **Worker death shrinks the pool, never loses a unit**: a worker that
    dies mid-unit (the chaos suite's :class:`WorkerKilled`, or a real
    crash) leaves its lease silent; the reaper thread notices the dead
    thread and force-expires the lease immediately — the SLO-gap
    heartbeat-silence path — so a live worker steals the unit and
    resumes it from its newest intact checkpoint. Wall-clock lease
    expiry (:meth:`Scheduler.reap`) covers workers that die without a
    trace (SIGKILLed pool processes).
  - **Stale workers abandon, never double-execute**: a lease renewal
    rejected by the scheduler (the unit was stolen while this worker
    stalled) surfaces as :class:`LeaseLost` at the worker's next chunk
    boundary — BEFORE it writes a checkpoint or a result — and the
    worker drops the unit on the floor. The thief's execution is the
    only one that lands.
  - **Cooperative preemption re-queues budget-free**: a unit unwinding
    with ``TrainingPreempted`` (the armed guard's chunk-aligned exit) is
    released lease-free — no retry burned, no backoff — exactly like the
    watchdog's budget-free rc-75 relaunch.
  - Any other unit exception is a FAILURE: retried with exponential
    backoff against the job's retry budget (``Scheduler.fail``).

Fleet mode (docs/scheduling.md): with ``stay_alive=True`` the pool is a
long-lived shared fleet — workers do NOT exit when the queue drains;
they idle on an exponential backoff (``poll_s`` doubling up to
``idle_max_s``, so an empty or fully-parked queue costs a few wakeups a
second, not a busy-spin) and the reaper folds OTHER writers' journal
records (``Scheduler.refresh``) each cycle, which is how submit-only
study controllers' cross-process submissions become visible. The reaper
also feeds live worker capacity into ``Scheduler.set_capacity`` so
worker death sheds load by priority (low-priority pending units park as
``starved``) instead of letting the queue collapse.

``DIB_POOL_FAULT=kill_worker@<n>`` arms the chaos injector: one worker
raises :class:`WorkerKilled` mid-unit once ``n`` units have completed —
the worker-loss drill's real-CLI entry point.

The pool never imports jax — device work lives in the runner.
"""

from __future__ import annotations

import os
import threading
import time
import uuid

from dib_tpu.train.preempt import TrainingPreempted

__all__ = ["LeaseLost", "WorkerKilled", "WorkerPool"]

FAULT_ENV = "DIB_POOL_FAULT"


class WorkerKilled(Exception):
    """Injected sudden worker death (chaos suite): the worker thread dies
    where it stands — no release, no fail, its lease just goes silent."""


class LeaseLost(Exception):
    """Raised by the pool's heartbeat when a renewal is rejected: the
    unit was stolen; the holder must abandon it WITHOUT completing."""


class WorkerPool:
    """N worker threads + a reaper draining one :class:`Scheduler`.

    ``runner(unit, heartbeat=...)`` executes one unit; ``heartbeat()``
    (pool-provided) renews the worker's lease and raises
    :class:`LeaseLost` when the renewal is rejected. ``preempt`` (a
    ``PreemptionGuard``) stops the pool cooperatively: workers finish or
    release their in-flight unit and exit, and :meth:`run` reports
    ``preempted`` so the CLI can exit with the preemption code.
    """

    def __init__(self, scheduler, runner, num_workers: int = 2,
                 poll_s: float = 0.05, reap_every_s: float = 0.25,
                 telemetry=None, preempt=None, name: str = "pool",
                 stay_alive: bool = False, idle_max_s: float = 1.0):
        self.scheduler = scheduler
        self.runner = runner
        self.num_workers = int(num_workers)
        self.poll_s = float(poll_s)
        self.reap_every_s = float(reap_every_s)
        self.stay_alive = bool(stay_alive)
        self.idle_max_s = float(idle_max_s)
        # Instance-unique worker-name prefix: a relaunched pool (same
        # process name, same worker indices) must NOT alias the dead
        # pool's lease holders in the journal, or _reap_dead_workers
        # would mistake an orphaned lease for its own live worker's and
        # wait out the wall-clock deadline instead of stealing now.
        self.name = f"{name}-{uuid.uuid4().hex[:6]}"
        self._telemetry = telemetry
        self._preempt = preempt
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._workers_done = threading.Event()
        self._threads: dict[str, threading.Thread] = {}
        self._dead_reported: set[str] = set()
        self.stats = {"completed": 0, "failed": 0, "released": 0,
                      "stale_abandoned": 0, "stale_completions": 0,
                      "workers_died": 0, "stolen": 0}
        # chaos injector: kill_worker@<n> kills ONE worker mid-unit once
        # n units have completed (fired at most once per pool)
        self._fault_kill_after: int | None = None
        fault = os.environ.get(FAULT_ENV, "")
        if fault.startswith("kill_worker@"):
            self._fault_kill_after = int(fault.split("@", 1)[1])
        self._fault_fired = False

    # ------------------------------------------------------------- workers
    def _heartbeat_for(self, lease):
        def heartbeat() -> bool:
            if not self.scheduler.renew(lease):
                raise LeaseLost(
                    f"lease {lease.lease_id} for unit {lease.unit_id} was "
                    "superseded — the unit was stolen; abandoning it")
            return True

        return heartbeat

    def _worker(self, worker_name: str) -> None:
        idle = 0
        while not self._stop.is_set():
            if self._preempt is not None and self._preempt.requested:
                return
            lease = self.scheduler.acquire(worker_name)
            if lease is None:
                if not self.stay_alive:
                    if self.scheduler.drained():
                        return
                    parked_only = getattr(self.scheduler, "parked_only",
                                          None)
                    if parked_only is not None and parked_only():
                        # everything runnable is shed-parked below the
                        # capacity floor: nothing can progress until
                        # capacity returns, so a bounded pool exits
                        # instead of waiting out its whole duration
                        return
                # idle exponential backoff: an empty (or fully parked)
                # queue must idle cheaply, not busy-spin at poll_s
                idle += 1
                delay = min(self.poll_s * (2 ** min(idle - 1, 6)),
                            self.idle_max_s)
                if self._stop.wait(delay):
                    return
                continue
            idle = 0
            if (self._fault_kill_after is not None
                    and not self._fault_fired
                    and self.stats["completed"] >= self._fault_kill_after):
                with self._lock:
                    fire = not self._fault_fired
                    self._fault_fired = True
                if fire:
                    # injected sudden death WITH a live lease: the reaper
                    # must steal the unit and the pool must degrade
                    with self._lock:
                        self.stats["workers_died"] += 1
                    return
            unit = self.scheduler.unit(lease.unit_id)["unit"]
            try:
                result = self.runner(
                    unit, heartbeat=self._heartbeat_for(lease))
            except LeaseLost:
                with self._lock:
                    self.stats["stale_abandoned"] += 1
                continue
            except TrainingPreempted:
                # cooperative: the runner checkpointed chunk-aligned;
                # re-queue lease-free (no retry burned, no backoff)
                self.scheduler.release(lease, reason="preempt")
                with self._lock:
                    self.stats["released"] += 1
                continue
            except WorkerKilled:
                # sudden death: the lease goes silent and the reaper
                # steals the unit; the pool degrades to N-1 workers
                with self._lock:
                    self.stats["workers_died"] += 1
                return
            except Exception as exc:
                self.scheduler.fail(
                    lease, f"{type(exc).__name__}: {exc}")
                with self._lock:
                    self.stats["failed"] += 1
                continue
            if self.scheduler.complete(
                    lease, result if isinstance(result, dict) else None):
                with self._lock:
                    self.stats["completed"] += 1
            else:
                with self._lock:
                    self.stats["stale_completions"] += 1

    # -------------------------------------------------------------- reaper
    def _reap_dead_workers(self) -> None:
        """Force-expire leases held by provably dead holders: a worker
        thread of THIS pool that is no longer alive, or a holder this
        pool never spawned (a previous pool instance that crashed — one
        pool per scheduler directory is the deployment contract). Both
        are heartbeat-silent forever, so waiting out the wall-clock
        deadline only delays the steal."""
        for row in self.scheduler.status()["units"]:
            if row["status"] != "leased" or not row["worker"]:
                continue
            holder = row["worker"]
            thread = self._threads.get(holder)
            if thread is not None and thread.is_alive():
                continue
            if self.scheduler.force_expire(
                    row["unit_id"],
                    "worker dead" if thread is not None
                    else "holder not in this pool (previous pool died)"):
                with self._lock:
                    self.stats["stolen"] += 1
                if (self._telemetry is not None
                        and holder not in self._dead_reported):
                    self._dead_reported.add(holder)
                    self._telemetry.mitigation(
                        mtype="worker_dead", detail=holder,
                        reason=("pool worker died mid-unit; its lease was "
                                "force-expired and the unit re-queued"))

    def _reaper(self) -> None:
        while not self._workers_done.wait(self.reap_every_s):
            # fold cross-process submissions first (fleet mode: submit-only
            # controllers append to the same journal), then steal
            refresh = getattr(self.scheduler, "refresh", None)
            if refresh is not None:
                refresh()
            self._reap_dead_workers()
            with self._lock:
                self.stats["stolen"] += len(self.scheduler.reap())
            # feed live capacity into the shed floor: worker death parks
            # the lowest priority classes instead of collapsing the queue
            set_capacity = getattr(self.scheduler, "set_capacity", None)
            if set_capacity is not None:
                alive = sum(1 for t in self._threads.values()
                            if t.is_alive())
                set_capacity(alive, self.num_workers)

    # ----------------------------------------------------------------- run
    def run(self, duration_s: float | None = None) -> dict:
        """Drain the queue: returns the stats dict plus ``drained`` and
        ``preempted``. Workers exit when every unit is terminal (or the
        pool is preempted/stopped); ``duration_s`` bounds the run — past
        it the pool stops accepting units, each worker finishes (and
        completes) its in-flight unit, and the rest of the queue is left
        for the next pool. ``duration_s=0`` stops after at most one unit
        per worker. With ``stay_alive`` (fleet mode) workers idle past a
        drained queue and only ``duration_s``, preemption, or a stop
        ends the run."""
        for i in range(self.num_workers):
            worker_name = f"{self.name}-w{i}"
            thread = threading.Thread(
                target=self._worker, args=(worker_name,),
                name=worker_name, daemon=True)
            self._threads[worker_name] = thread
            thread.start()
        reaper = threading.Thread(target=self._reaper, name=f"{self.name}-reaper",
                                  daemon=True)
        reaper.start()
        deadline = ((time.time() + duration_s)     # timing-ok: host-side
                    if duration_s is not None else None)  # deadline pacing
        try:
            for thread in self._threads.values():
                while thread.is_alive():
                    if deadline is not None \
                            and time.time() >= deadline:   # timing-ok: pacing
                        self._stop.set()
                    # floor the join timeout so a passed deadline waits
                    # out the worker's in-flight unit without spinning a
                    # core the training threads need
                    timeout = (min(1.0, max(deadline - time.time(), 0.05))  # timing-ok: pacing
                               if deadline is not None else 1.0)
                    thread.join(timeout=timeout)
        finally:
            self._stop.set()
            self._workers_done.set()
            reaper.join(timeout=5.0)
        with self._lock:
            out = dict(self.stats)
        out["drained"] = self.scheduler.drained()
        out["preempted"] = bool(
            self._preempt is not None and self._preempt.requested)
        out["workers"] = self.num_workers
        out["stay_alive"] = self.stay_alive
        starved = getattr(self.scheduler, "starved", None)
        out["starved"] = int(starved()) if starved is not None else 0
        parked_only = getattr(self.scheduler, "parked_only", None)
        out["parked"] = bool(parked_only()) if parked_only is not None \
            else False
        return out
