"""Sweep-as-a-service: a fault-tolerant work-stealing β-grid scheduler.

The paper's scientific product is the whole annealing trajectory across
many β points and seeds (NORTHSTAR_ENSEMBLE is exactly that), yet before
this package one sweep = one fixed launch: an ejected replica permanently
degraded the sweep and a dead host lost its slice of the grid. PR 4/5
built the *worker* half of durability — chunk-aligned checkpoints,
exit-75 preemption, a 13/13-green fault matrix — and this package is the
scheduling layer above it (docs/robustness.md "Sweep as a service"):

  - :mod:`dib_tpu.sched.journal` — durable append-only job journal
    (``journal.jsonl``, the events.jsonl durability contract: one
    ``O_APPEND`` write per record, torn-final-line tolerant on replay) —
    the scheduler's ENTIRE state is a fold over this file, so a
    SIGKILLed scheduler restarts into exactly the queue it died with;
  - :mod:`dib_tpu.sched.scheduler` — β-grid jobs (dense grids,
    refinement around info-plane transitions, multi-seed ensembles)
    decomposed into chunk-resumable work units, handed to workers under
    **leases**: a unit whose lease expires (or whose worker dies) is
    re-leased to a live worker — work-stealing — and a completion or
    renewal under a superseded lease is REJECTED, so a presumed-dead
    worker that returns can never double-execute a unit. Failures retry
    with exponential backoff against a per-job retry budget; budget
    exhaustion marks the job failed instead of retrying forever;
  - :mod:`dib_tpu.sched.pool` — a worker pool draining the queue:
    worker death shrinks the pool (its leased unit is stolen, never
    lost), cooperative preemption re-enqueues lease-free exactly like
    the watchdog's budget-free relaunch;
  - :mod:`dib_tpu.sched.runner` — the per-unit trainer: one β point ×
    seed trained with chunk-aligned checkpoints under the unit's own
    directory, resuming from the newest intact step
    (``restore_latest_intact``) so a stolen or retried unit continues
    bit-identically to an uninterrupted run;
  - :mod:`dib_tpu.sched.cli` — ``python -m dib_tpu sched
    submit|status|run-pool``.

``scripts/chaos_suite.py`` runs the fault matrix *against this layer
under load* — killing workers, expiring leases, tearing the journal
mid-append — and the committed ``CHAOS_SCHED.json`` proves zero lost
units, no double-executions, and bit-identical per-β histories.
"""

from dib_tpu.sched.journal import JOURNAL_FILENAME, JobJournal, read_journal
from dib_tpu.sched.scheduler import (
    JobSpec,
    Lease,
    Scheduler,
    WorkUnit,
    dense_beta_grid,
    refine_beta_grid,
)
from dib_tpu.sched.pool import LeaseLost, WorkerKilled, WorkerPool
from dib_tpu.sched.runner import TrainingUnitRunner

__all__ = [
    "JOURNAL_FILENAME",
    "JobJournal",
    "JobSpec",
    "Lease",
    "LeaseLost",
    "Scheduler",
    "TrainingUnitRunner",
    "WorkUnit",
    "WorkerKilled",
    "WorkerPool",
    "dense_beta_grid",
    "read_journal",
    "refine_beta_grid",
]
