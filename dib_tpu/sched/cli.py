"""``python -m dib_tpu sched submit|status|policy|run-pool`` — sweep as
a service.

``submit`` appends a β-grid job to a scheduler directory's durable
journal (with ``--tenant``/``--study``/``--priority`` fleet identity;
an over-bound submit is rejected with a retry horizon and exit 75);
``status`` replays the journal into a queue snapshot (per-tenant queue
views, starved/quarantined units); ``policy`` shows or sets the fleet's
admission/fair-share/breaker policy; and ``run-pool`` drains the queue
with a worker pool of training unit runners — with ``--serve`` it is
the long-lived shared FLEET that submit-only study controllers target
(docs/scheduling.md) — optionally under watchdog supervision
(``--watchdog``: crash-relaunched, rc-75 preemptions relaunched
budget-free while the journal shows progress or every runnable unit is
shed-parked). The scheduler directory is also the run directory:
``journal.jsonl`` next to ``events.jsonl``, so ``telemetry
tail``/``summarize``/``check`` see the queue's ``job`` / ``lease``
events alongside everything else (docs/robustness.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Sequence

__all__ = ["sched_main"]


def _add_sched_dir(parser) -> None:
    parser.add_argument("--sched-dir", "--sched_dir", dest="sched_dir",
                        required=True,
                        help="Scheduler directory: holds the durable "
                             "journal.jsonl, the run's events.jsonl, and "
                             "per-unit checkpoints/artifacts under units/.")


def build_sched_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="dib_tpu sched",
        description="Fault-tolerant work-stealing β-grid scheduler "
                    "(docs/robustness.md 'Sweep as a service').",
    )
    sub = parser.add_subparsers(dest="action", required=True)

    p_sub = sub.add_parser(
        "submit", help="Append a β-grid job (dense grid, refinement, or "
                       "explicit list × seeds) to the journal.")
    _add_sched_dir(p_sub)
    p_sub.add_argument("--betas", type=float, nargs="+", default=None,
                       help="Explicit β endpoints.")
    p_sub.add_argument("--grid", type=float, nargs=3, default=None,
                       metavar=("START", "STOP", "NUM"),
                       help="Dense log-spaced grid: start stop num.")
    p_sub.add_argument("--refine-around", type=float, nargs="+",
                       default=None, dest="refine_around",
                       help="Refinement grid around these β values (e.g. "
                            "info-plane transition events).")
    p_sub.add_argument("--refine-num", type=int, default=4,
                       dest="refine_num",
                       help="Points per refinement center (default 4).")
    p_sub.add_argument("--seeds", type=int, nargs="+", default=[0],
                       help="Seeds per β point (multi-seed ensembles).")
    p_sub.add_argument("--retry-budget", type=int, default=3,
                       dest="retry_budget",
                       help="Per-job retry budget: unit failures beyond "
                            "it mark the job failed (default 3).")
    p_sub.add_argument("--name", default="", help="Job label.")
    p_sub.add_argument("--tenant", default="",
                       help="Fair-share tenant the job bills to "
                            "(default: the shared 'default' tenant).")
    p_sub.add_argument("--study", default="",
                       help="Study id the job belongs to (submit-only "
                            "study controllers set this).")
    p_sub.add_argument("--priority", type=int, default=0,
                       help="Shed priority: when the pool loses workers, "
                            "LOWER priorities park first (default 0).")
    p_sub.add_argument("--set", action="append", default=[],
                       metavar="FIELD=VALUE",
                       help="Unit training-spec override (repeatable), "
                            "e.g. --set num_annealing_epochs=6")
    p_sub.add_argument("--trace-id", "--trace_id", dest="trace_id",
                       default=None,
                       help="Cross-plane trace id the job/unit journal "
                            "records carry (docs/observability.md 'Fleet "
                            "causality'; default: inherit DIB_TRACE_ID "
                            "or mint a fresh one).")

    p_stat = sub.add_parser(
        "status", help="Replay the journal into a queue snapshot.")
    _add_sched_dir(p_stat)
    p_stat.add_argument("--json", action="store_true",
                        help="Machine-readable snapshot.")

    p_pol = sub.add_parser(
        "policy", help="Show or set the fleet's admission/fairness "
                       "policy (policy.json next to the journal).")
    _add_sched_dir(p_pol)
    p_pol.add_argument("--max-pending", type=int, default=None,
                       dest="max_pending",
                       help="Fleet-wide bound on queued (pending) units; "
                            "an over-bound submit is rejected with a "
                            "retry horizon.")
    p_pol.add_argument("--admission-retry-s", type=float, default=None,
                       dest="admission_retry_s",
                       help="Retry horizon returned with admission "
                            "rejects (default 5).")
    p_pol.add_argument("--breaker-threshold", type=int, default=None,
                       dest="breaker_threshold",
                       help="Consecutive unit failures that quarantine a "
                            "job (0 disables the circuit breaker).")
    p_pol.add_argument("--breaker-probe-after-s", type=float, default=None,
                       dest="breaker_probe_after_s",
                       help="Quarantine horizon before one half-open "
                            "probe unit is allowed (default 30).")
    p_pol.add_argument("--tenant", action="append", default=[],
                       dest="tenant_specs",
                       metavar="NAME=WEIGHT[:MAX_LEASES[:MAX_PENDING]]",
                       help="Per-tenant policy (repeatable): fair-share "
                            "weight, optional concurrent-lease cap, "
                            "optional pending-queue cap — e.g. "
                            "'autopilot=2' or 'greedy=1:4:40'.")

    p_pool = sub.add_parser(
        "run-pool", help="Drain the queue with a pool of training "
                         "workers (work-stealing, retry/backoff, "
                         "preemption-tolerant).")
    _add_sched_dir(p_pool)
    p_pool.add_argument("--workers", type=int, default=2)
    p_pool.add_argument("--lease-s", type=float, default=60.0,
                        dest="lease_s",
                        help="Lease duration; a unit unrenewed past it is "
                             "stolen by a live worker (default 60).")
    p_pool.add_argument("--duration-s", type=float, default=None,
                        dest="duration_s",
                        help="Stop the pool after this long even if the "
                             "queue is not drained.")
    p_pool.add_argument("--serve", action="store_true",
                        help="Fleet mode: stay alive past a drained "
                             "queue (idling on exponential backoff) and "
                             "fold cross-process submissions from the "
                             "shared journal — the long-lived fleet that "
                             "submit-only study controllers target. Ends "
                             "at --duration-s (exit 0) or preemption.")
    p_pool.add_argument("--preempt_grace_s", type=float, default=30.0,
                        help="SIGTERM/SIGINT grace budget: in-flight "
                             "units checkpoint chunk-aligned, re-enqueue "
                             "lease-free, and the pool exits with the "
                             "preemption code (75). 0 disables.")
    p_pool.add_argument("--watchdog", action="store_true",
                        help="Supervise this pool (train/watchdog.py "
                             "supervise_pool): crashes relaunch with "
                             "backoff against a restart budget; rc-75 "
                             "preemptions relaunch immediately and "
                             "budget-free while units keep finishing "
                             "(terminal journal records).")
    p_pool.add_argument("--max-restarts", type=int, default=3,
                        dest="max_restarts")
    p_pool.add_argument("--telemetry-dir", "--telemetry_dir",
                        dest="telemetry_dir", type=str, default=None,
                        help="Events stream directory (default: the "
                             "scheduler dir; '' disables).")
    p_pool.add_argument("--runs-root", "--runs_root", dest="runs_root",
                        type=str, default="",
                        help="Register the pool run in the fleet registry "
                             "(default: DIB_RUNS_ROOT when set, else off).")
    return parser


def _resolve_betas(args) -> list[float]:
    from dib_tpu.sched.scheduler import dense_beta_grid, refine_beta_grid

    given = [name for name, value in (
        ("--betas", args.betas), ("--grid", args.grid),
        ("--refine-around", args.refine_around)) if value]
    if len(given) != 1:
        raise SystemExit(
            "sched submit: pass exactly one of --betas / --grid / "
            f"--refine-around (got {given or 'none'})")
    if args.betas:
        return [float(b) for b in args.betas]
    if args.grid:
        start, stop, num = args.grid
        return dense_beta_grid(start, stop, int(num))
    return refine_beta_grid(args.refine_around, num=args.refine_num)


def _parse_spec_sets(pairs: Sequence[str]) -> dict:
    from dib_tpu.cli import _parse_sets

    return _parse_sets(pairs)


def _submit_main(args) -> int:
    from dib_tpu.sched.scheduler import AdmissionRejected, JobSpec, Scheduler
    from dib_tpu.telemetry.context import ensure_context
    from dib_tpu.train.preempt import PREEMPT_EXIT_CODE

    betas = _resolve_betas(args)
    spec = JobSpec(betas=tuple(betas), seeds=tuple(args.seeds),
                   train=_parse_spec_sets(args.set),
                   retry_budget=args.retry_budget, name=args.name,
                   tenant=args.tenant, study=args.study,
                   priority=args.priority)
    ctx = ensure_context("sched", trace_id=args.trace_id)
    scheduler = Scheduler(args.sched_dir, ctx=ctx)
    try:
        try:
            job_id = scheduler.submit(spec)
        except AdmissionRejected as exc:
            # explicit reject with a retry horizon: the temp-failure exit
            # code tells the caller to wait retry_after_s and resubmit
            print(json.dumps({
                "rejected": True, "tenant": exc.tenant,
                "retry_after_s": exc.retry_after_s, "reason": exc.reason,
            }))
            return PREEMPT_EXIT_CODE
        counts = scheduler.status()["counts"]
    finally:
        scheduler.close()
    print(json.dumps({"job_id": job_id, "units": len(betas) * len(args.seeds),
                      "betas": betas, "seeds": list(args.seeds),
                      "queue": counts, "trace_id": ctx.trace_id}))
    return 0


def _policy_main(args) -> int:
    from dib_tpu.sched.scheduler import FleetPolicy, TenantPolicy

    current = FleetPolicy.load(args.sched_dir) or FleetPolicy()
    changed = {}
    for field in ("max_pending_units", "admission_retry_s",
                  "breaker_threshold", "breaker_probe_after_s"):
        arg = "max_pending" if field == "max_pending_units" else field
        value = getattr(args, arg)
        if value is not None:
            changed[field] = value
    tenants = dict(current.tenants)
    for spec in args.tenant_specs:
        name, _, rest = spec.partition("=")
        if not name or not rest:
            raise SystemExit(
                f"sched policy: bad --tenant {spec!r} (want "
                "NAME=WEIGHT[:MAX_LEASES[:MAX_PENDING]])")
        parts = rest.split(":")
        tenants[name] = TenantPolicy(
            weight=float(parts[0]),
            max_leases=int(parts[1]) if len(parts) > 1 and parts[1] else None,
            max_pending=int(parts[2]) if len(parts) > 2 and parts[2] else None,
        )
    if changed or args.tenant_specs:
        merged = FleetPolicy.from_dict(
            {**current.to_dict(), **changed,
             "tenants": {n: tp.to_dict() for n, tp in tenants.items()}})
        merged.save(args.sched_dir)
        current = merged
    print(json.dumps({"policy": current.to_dict()}, indent=1))
    return 0


def _status_main(args) -> int:
    from dib_tpu.sched.scheduler import Scheduler

    scheduler = Scheduler(args.sched_dir)
    try:
        snapshot = scheduler.status()
        snapshot["replayed_records"] = scheduler.replayed_records
        snapshot["replayed_torn"] = scheduler.replayed_torn
    finally:
        scheduler.close()
    if args.json:
        print(json.dumps(snapshot, indent=1))
        return 0
    counts = snapshot["counts"]
    starved = snapshot.get("starved", 0)
    print(f"queue: {counts['pending']} pending / {counts['leased']} leased "
          f"/ {counts['done']} done / {counts['failed']} failed"
          + (f" / {starved} starved (shed floor "
             f"{snapshot.get('shed_floor')})" if starved else "")
          + (f"  (journal: {snapshot['replayed_records']} records, "
             f"{snapshot['replayed_torn']} torn)"
             if snapshot["replayed_torn"] else ""))
    tenants = snapshot.get("tenants") or {}
    if len(tenants) > 1 or any(t.get("admission_rejected")
                               for t in tenants.values()):
        for name in sorted(tenants):
            t = tenants[name]
            waits = ""
            if t.get("queue_wait_p99_s") is not None:
                waits = (f"  wait p50={t['queue_wait_p50_s']:.2f}s "
                         f"p99={t['queue_wait_p99_s']:.2f}s")
            rejects = (f"  rejected={t['admission_rejected']}"
                       if t.get("admission_rejected") else "")
            print(f"tenant {name:16} {t['pending']} pending / "
                  f"{t['leased']} leased / {t['starved']} starved / "
                  f"{t['done']} done / {t['failed']} failed  "
                  f"share={t['service']:.0f}/{t['weight']:g}"
                  f"{waits}{rejects}")
    for job_id, job in snapshot["jobs"].items():
        breaker = " BREAKER-OPEN" if job.get("breaker_open") else ""
        tenant = (f" tenant={job['tenant']}"
                  if job.get("tenant", "default") != "default" else "")
        print(f"job {job_id}  {job['status']:8} units={job['units']} "
              f"retries={job['retries_used']}/{job['retry_budget']}"
              f"{tenant}{breaker}"
              + (f"  [{job['name']}]" if job["name"] else ""))
    for row in snapshot["units"]:
        worker = f"  worker={row['worker']}" if row["worker"] else ""
        shown = "starved" if row.get("starved") else row["status"]
        print(f"  {row['unit_id']:28} {shown:8} "
              f"beta={row['beta']:<10g} seed={row['seed']} "
              f"attempts={row['attempts']}{worker}")
    return 0


def _run_pool_supervised(args, argv: Sequence[str]) -> int:
    """Re-exec this run-pool command as a supervised worker process: the
    journal makes a relaunched pool resume the exact queue, so crash
    supervision needs no heartbeat file — rc-75 preemptions relaunch
    budget-free while the journal grew (the epoch-progress gate's
    journal-shaped twin)."""
    from dib_tpu.sched.journal import JOURNAL_FILENAME
    from dib_tpu.telemetry import open_writer, shared_run_id
    from dib_tpu.telemetry.context import ensure_context
    from dib_tpu.train.watchdog import WatchdogConfig, supervise_pool

    run_id = shared_run_id()
    os.environ["DIB_TELEMETRY_RUN_ID"] = run_id
    # pin the pool's causal lineage next to the run id: the re-exec'd
    # worker processes (and any watchdog relaunches) inherit the same
    # trace_id from the env instead of minting fresh roots
    ctx = ensure_context("sched_pool")
    ctx.activate()
    telemetry = open_writer(args.telemetry_dir, args.sched_dir,
                            run_id=run_id, process_index=0,
                            tags={"src": "supervisor"}, ctx=ctx)
    # remove only the FIRST token that spells the flag — argparse
    # accepts unambiguous prefixes (--watch, --watchd, ...), so exact
    # .remove("--watchdog") would crash on an abbreviated spelling; and
    # filtering by value equality would also strip an argument VALUE
    # that happens to spell the same. Option values can never start
    # with "--", so a prefix match here is always the flag itself.
    worker = list(argv)
    for i, token in enumerate(worker):
        if token.startswith("--wa") and "--watchdog".startswith(token):
            del worker[i]
            break
    result = supervise_pool(
        [sys.executable, "-m", "dib_tpu.cli", "sched", "run-pool", *worker],
        config=WatchdogConfig(max_restarts=args.max_restarts),
        telemetry=telemetry,
        journal_path=os.path.join(args.sched_dir, JOURNAL_FILENAME),
    )
    if telemetry is not None:
        telemetry.close()
    print(json.dumps({"watchdog": result}))
    return 0 if result["returncode"] == 0 else 1


def _run_pool_main(args, argv: Sequence[str]) -> int:
    if args.watchdog:
        return _run_pool_supervised(args, argv)

    import jax

    from dib_tpu.sched.pool import WorkerPool
    from dib_tpu.sched.runner import TrainingUnitRunner
    from dib_tpu.sched.scheduler import Scheduler
    from dib_tpu.telemetry import open_writer, runtime_manifest, shared_run_id
    from dib_tpu.train.preempt import (
        PREEMPT_EXIT_CODE,
        PreemptionGuard,
    )

    os.makedirs(args.sched_dir, exist_ok=True)
    telemetry = open_writer(args.telemetry_dir, args.sched_dir,
                            run_id=shared_run_id(),
                            process_index=jax.process_index())
    if telemetry is not None:
        telemetry.run_start(runtime_manifest(extra={
            "mode": "sched_pool", "sched_dir": os.path.abspath(args.sched_dir),
            "workers": args.workers, "lease_s": args.lease_s,
            "serve": bool(args.serve),
        }))
    guard = None
    if args.preempt_grace_s and args.preempt_grace_s > 0:

        def _grace_flush():
            if telemetry is not None:
                telemetry.run_end(status="preempted", aborted_chunk=True)
                telemetry.close()

        guard = PreemptionGuard(args.preempt_grace_s,
                                on_grace_expired=_grace_flush)

    scheduler = Scheduler(args.sched_dir, telemetry=telemetry,
                          lease_s=args.lease_s)
    runner = TrainingUnitRunner(args.sched_dir, telemetry=telemetry,
                                preempt=guard)
    pool = WorkerPool(scheduler, runner, num_workers=args.workers,
                      telemetry=telemetry, preempt=guard,
                      stay_alive=bool(args.serve))
    try:
        if guard is not None:
            with guard:
                stats = pool.run(duration_s=args.duration_s)
        else:
            stats = pool.run(duration_s=args.duration_s)
    finally:
        scheduler.close()
    stats["queue"] = scheduler.status()["counts"]
    if telemetry is not None:
        telemetry.run_end(
            status="preempted" if stats["preempted"] else "ok")
        telemetry.close()
        root = args.runs_root or os.environ.get("DIB_RUNS_ROOT")
        if root:
            from dib_tpu.telemetry.registry import register_run

            register_run(os.path.dirname(telemetry.path), root=root)
    print(json.dumps(stats))
    if stats["preempted"]:
        return PREEMPT_EXIT_CODE
    if args.serve:
        # a fleet shift that reached its duration ended cleanly — an
        # undrained queue is the NEXT shift's work, not a failure
        return 0
    if not stats["drained"] and stats.get("parked"):
        # everything runnable is shed-parked below the capacity floor:
        # a temporary condition (rc 75, like preemption), and the
        # watchdog's parked-snapshot gate relaunches budget-free with
        # restored capacity instead of counting a crash
        return PREEMPT_EXIT_CODE
    return 0 if stats["drained"] else 1


def sched_main(argv: Sequence[str]) -> int:
    argv = list(argv)
    args = build_sched_parser().parse_args(argv)
    if args.action == "submit":
        return _submit_main(args)
    if args.action == "status":
        return _status_main(args)
    if args.action == "policy":
        return _policy_main(args)
    # the subparser action is positionally first (the parser defines no
    # pre-subcommand flags); strip it by POSITION — filtering by value
    # would also eat e.g. a --sched-dir literally named "run-pool"
    return _run_pool_main(args, argv[1:])
