"""``python -m dib_tpu sched submit|status|run-pool`` — sweep as a service.

``submit`` appends a β-grid job to a scheduler directory's durable
journal; ``status`` replays the journal into a queue snapshot; and
``run-pool`` drains the queue with a worker pool of training unit
runners, optionally under watchdog supervision (``--watchdog``:
crash-relaunched, rc-75 preemptions relaunched budget-free while the
journal shows progress). The scheduler directory is also the run
directory: ``journal.jsonl`` next to ``events.jsonl``, so
``telemetry tail``/``summarize``/``check`` see the queue's ``job`` /
``lease`` events alongside everything else (docs/robustness.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Sequence

__all__ = ["sched_main"]


def _add_sched_dir(parser) -> None:
    parser.add_argument("--sched-dir", "--sched_dir", dest="sched_dir",
                        required=True,
                        help="Scheduler directory: holds the durable "
                             "journal.jsonl, the run's events.jsonl, and "
                             "per-unit checkpoints/artifacts under units/.")


def build_sched_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="dib_tpu sched",
        description="Fault-tolerant work-stealing β-grid scheduler "
                    "(docs/robustness.md 'Sweep as a service').",
    )
    sub = parser.add_subparsers(dest="action", required=True)

    p_sub = sub.add_parser(
        "submit", help="Append a β-grid job (dense grid, refinement, or "
                       "explicit list × seeds) to the journal.")
    _add_sched_dir(p_sub)
    p_sub.add_argument("--betas", type=float, nargs="+", default=None,
                       help="Explicit β endpoints.")
    p_sub.add_argument("--grid", type=float, nargs=3, default=None,
                       metavar=("START", "STOP", "NUM"),
                       help="Dense log-spaced grid: start stop num.")
    p_sub.add_argument("--refine-around", type=float, nargs="+",
                       default=None, dest="refine_around",
                       help="Refinement grid around these β values (e.g. "
                            "info-plane transition events).")
    p_sub.add_argument("--refine-num", type=int, default=4,
                       dest="refine_num",
                       help="Points per refinement center (default 4).")
    p_sub.add_argument("--seeds", type=int, nargs="+", default=[0],
                       help="Seeds per β point (multi-seed ensembles).")
    p_sub.add_argument("--retry-budget", type=int, default=3,
                       dest="retry_budget",
                       help="Per-job retry budget: unit failures beyond "
                            "it mark the job failed (default 3).")
    p_sub.add_argument("--name", default="", help="Job label.")
    p_sub.add_argument("--set", action="append", default=[],
                       metavar="FIELD=VALUE",
                       help="Unit training-spec override (repeatable), "
                            "e.g. --set num_annealing_epochs=6")
    p_sub.add_argument("--trace-id", "--trace_id", dest="trace_id",
                       default=None,
                       help="Cross-plane trace id the job/unit journal "
                            "records carry (docs/observability.md 'Fleet "
                            "causality'; default: inherit DIB_TRACE_ID "
                            "or mint a fresh one).")

    p_stat = sub.add_parser(
        "status", help="Replay the journal into a queue snapshot.")
    _add_sched_dir(p_stat)
    p_stat.add_argument("--json", action="store_true",
                        help="Machine-readable snapshot.")

    p_pool = sub.add_parser(
        "run-pool", help="Drain the queue with a pool of training "
                         "workers (work-stealing, retry/backoff, "
                         "preemption-tolerant).")
    _add_sched_dir(p_pool)
    p_pool.add_argument("--workers", type=int, default=2)
    p_pool.add_argument("--lease-s", type=float, default=60.0,
                        dest="lease_s",
                        help="Lease duration; a unit unrenewed past it is "
                             "stolen by a live worker (default 60).")
    p_pool.add_argument("--duration-s", type=float, default=None,
                        dest="duration_s",
                        help="Stop the pool after this long even if the "
                             "queue is not drained.")
    p_pool.add_argument("--preempt_grace_s", type=float, default=30.0,
                        help="SIGTERM/SIGINT grace budget: in-flight "
                             "units checkpoint chunk-aligned, re-enqueue "
                             "lease-free, and the pool exits with the "
                             "preemption code (75). 0 disables.")
    p_pool.add_argument("--watchdog", action="store_true",
                        help="Supervise this pool (train/watchdog.py "
                             "supervise_pool): crashes relaunch with "
                             "backoff against a restart budget; rc-75 "
                             "preemptions relaunch immediately and "
                             "budget-free while units keep finishing "
                             "(terminal journal records).")
    p_pool.add_argument("--max-restarts", type=int, default=3,
                        dest="max_restarts")
    p_pool.add_argument("--telemetry-dir", "--telemetry_dir",
                        dest="telemetry_dir", type=str, default=None,
                        help="Events stream directory (default: the "
                             "scheduler dir; '' disables).")
    p_pool.add_argument("--runs-root", "--runs_root", dest="runs_root",
                        type=str, default="",
                        help="Register the pool run in the fleet registry "
                             "(default: DIB_RUNS_ROOT when set, else off).")
    return parser


def _resolve_betas(args) -> list[float]:
    from dib_tpu.sched.scheduler import dense_beta_grid, refine_beta_grid

    given = [name for name, value in (
        ("--betas", args.betas), ("--grid", args.grid),
        ("--refine-around", args.refine_around)) if value]
    if len(given) != 1:
        raise SystemExit(
            "sched submit: pass exactly one of --betas / --grid / "
            f"--refine-around (got {given or 'none'})")
    if args.betas:
        return [float(b) for b in args.betas]
    if args.grid:
        start, stop, num = args.grid
        return dense_beta_grid(start, stop, int(num))
    return refine_beta_grid(args.refine_around, num=args.refine_num)


def _parse_spec_sets(pairs: Sequence[str]) -> dict:
    from dib_tpu.cli import _parse_sets

    return _parse_sets(pairs)


def _submit_main(args) -> int:
    from dib_tpu.sched.scheduler import JobSpec, Scheduler
    from dib_tpu.telemetry.context import ensure_context

    betas = _resolve_betas(args)
    spec = JobSpec(betas=tuple(betas), seeds=tuple(args.seeds),
                   train=_parse_spec_sets(args.set),
                   retry_budget=args.retry_budget, name=args.name)
    ctx = ensure_context("sched", trace_id=args.trace_id)
    scheduler = Scheduler(args.sched_dir, ctx=ctx)
    try:
        job_id = scheduler.submit(spec)
        counts = scheduler.status()["counts"]
    finally:
        scheduler.close()
    print(json.dumps({"job_id": job_id, "units": len(betas) * len(args.seeds),
                      "betas": betas, "seeds": list(args.seeds),
                      "queue": counts, "trace_id": ctx.trace_id}))
    return 0


def _status_main(args) -> int:
    from dib_tpu.sched.scheduler import Scheduler

    scheduler = Scheduler(args.sched_dir)
    try:
        snapshot = scheduler.status()
        snapshot["replayed_records"] = scheduler.replayed_records
        snapshot["replayed_torn"] = scheduler.replayed_torn
    finally:
        scheduler.close()
    if args.json:
        print(json.dumps(snapshot, indent=1))
        return 0
    counts = snapshot["counts"]
    print(f"queue: {counts['pending']} pending / {counts['leased']} leased "
          f"/ {counts['done']} done / {counts['failed']} failed"
          + (f"  (journal: {snapshot['replayed_records']} records, "
             f"{snapshot['replayed_torn']} torn)"
             if snapshot["replayed_torn"] else ""))
    for job_id, job in snapshot["jobs"].items():
        print(f"job {job_id}  {job['status']:8} units={job['units']} "
              f"retries={job['retries_used']}/{job['retry_budget']}"
              + (f"  [{job['name']}]" if job["name"] else ""))
    for row in snapshot["units"]:
        worker = f"  worker={row['worker']}" if row["worker"] else ""
        print(f"  {row['unit_id']:28} {row['status']:8} "
              f"beta={row['beta']:<10g} seed={row['seed']} "
              f"attempts={row['attempts']}{worker}")
    return 0


def _run_pool_supervised(args, argv: Sequence[str]) -> int:
    """Re-exec this run-pool command as a supervised worker process: the
    journal makes a relaunched pool resume the exact queue, so crash
    supervision needs no heartbeat file — rc-75 preemptions relaunch
    budget-free while the journal grew (the epoch-progress gate's
    journal-shaped twin)."""
    from dib_tpu.sched.journal import JOURNAL_FILENAME
    from dib_tpu.telemetry import open_writer, shared_run_id
    from dib_tpu.telemetry.context import ensure_context
    from dib_tpu.train.watchdog import WatchdogConfig, supervise_pool

    run_id = shared_run_id()
    os.environ["DIB_TELEMETRY_RUN_ID"] = run_id
    # pin the pool's causal lineage next to the run id: the re-exec'd
    # worker processes (and any watchdog relaunches) inherit the same
    # trace_id from the env instead of minting fresh roots
    ctx = ensure_context("sched_pool")
    ctx.activate()
    telemetry = open_writer(args.telemetry_dir, args.sched_dir,
                            run_id=run_id, process_index=0,
                            tags={"src": "supervisor"}, ctx=ctx)
    # remove only the FIRST token that spells the flag — argparse
    # accepts unambiguous prefixes (--watch, --watchd, ...), so exact
    # .remove("--watchdog") would crash on an abbreviated spelling; and
    # filtering by value equality would also strip an argument VALUE
    # that happens to spell the same. Option values can never start
    # with "--", so a prefix match here is always the flag itself.
    worker = list(argv)
    for i, token in enumerate(worker):
        if token.startswith("--wa") and "--watchdog".startswith(token):
            del worker[i]
            break
    result = supervise_pool(
        [sys.executable, "-m", "dib_tpu.cli", "sched", "run-pool", *worker],
        config=WatchdogConfig(max_restarts=args.max_restarts),
        telemetry=telemetry,
        journal_path=os.path.join(args.sched_dir, JOURNAL_FILENAME),
    )
    if telemetry is not None:
        telemetry.close()
    print(json.dumps({"watchdog": result}))
    return 0 if result["returncode"] == 0 else 1


def _run_pool_main(args, argv: Sequence[str]) -> int:
    if args.watchdog:
        return _run_pool_supervised(args, argv)

    import jax

    from dib_tpu.sched.pool import WorkerPool
    from dib_tpu.sched.runner import TrainingUnitRunner
    from dib_tpu.sched.scheduler import Scheduler
    from dib_tpu.telemetry import open_writer, runtime_manifest, shared_run_id
    from dib_tpu.train.preempt import (
        PREEMPT_EXIT_CODE,
        PreemptionGuard,
    )

    os.makedirs(args.sched_dir, exist_ok=True)
    telemetry = open_writer(args.telemetry_dir, args.sched_dir,
                            run_id=shared_run_id(),
                            process_index=jax.process_index())
    if telemetry is not None:
        telemetry.run_start(runtime_manifest(extra={
            "mode": "sched_pool", "sched_dir": os.path.abspath(args.sched_dir),
            "workers": args.workers, "lease_s": args.lease_s,
        }))
    guard = None
    if args.preempt_grace_s and args.preempt_grace_s > 0:

        def _grace_flush():
            if telemetry is not None:
                telemetry.run_end(status="preempted", aborted_chunk=True)
                telemetry.close()

        guard = PreemptionGuard(args.preempt_grace_s,
                                on_grace_expired=_grace_flush)

    scheduler = Scheduler(args.sched_dir, telemetry=telemetry,
                          lease_s=args.lease_s)
    runner = TrainingUnitRunner(args.sched_dir, telemetry=telemetry,
                                preempt=guard)
    pool = WorkerPool(scheduler, runner, num_workers=args.workers,
                      telemetry=telemetry, preempt=guard)
    try:
        if guard is not None:
            with guard:
                stats = pool.run(duration_s=args.duration_s)
        else:
            stats = pool.run(duration_s=args.duration_s)
    finally:
        scheduler.close()
    stats["queue"] = scheduler.status()["counts"]
    if telemetry is not None:
        telemetry.run_end(
            status="preempted" if stats["preempted"] else "ok")
        telemetry.close()
        root = args.runs_root or os.environ.get("DIB_RUNS_ROOT")
        if root:
            from dib_tpu.telemetry.registry import register_run

            register_run(os.path.dirname(telemetry.path), root=root)
    print(json.dumps(stats))
    if stats["preempted"]:
        return PREEMPT_EXIT_CODE
    return 0 if stats["drained"] else 1


def sched_main(argv: Sequence[str]) -> int:
    argv = list(argv)
    args = build_sched_parser().parse_args(argv)
    if args.action == "submit":
        return _submit_main(args)
    if args.action == "status":
        return _status_main(args)
    # the subparser action is positionally first (the parser defines no
    # pre-subcommand flags); strip it by POSITION — filtering by value
    # would also eat e.g. a --sched-dir literally named "run-pool"
    return _run_pool_main(args, argv[1:])
