"""The shipped lint passes. Importing a module registers its pass(es);
``dib_tpu/analysis/__init__.py`` imports them all. Each module carries
one pass and names, in its docstring, the runtime incident that pass
exists to prevent — see docs/static-analysis.md for the catalog."""
