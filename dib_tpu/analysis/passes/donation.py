"""donation-safety: reads of donated buffers are use-after-free.

The incident (PR 4, docs/robustness.md "Checkpoint corruption"): orbax's
async save read zero-copy host buffers that ``run_chunk``'s
``donate_argnames`` donation had already reused — checkpoint steps landed
on disk holding a LATER epoch's bytes, poisoning the divergence-rollback
target. The bug class is decidable from the AST, and this pass decides
it, two ways:

1. **read-after-donation**: an argument bound to a ``donate_argnames`` /
   ``donate_argnums`` parameter is dead after the donating call — XLA owns
   (and will reuse) its buffer. Any later read of that name in the same
   scope is flagged, unless the name was rebound first (the
   ``state, history = self.run_chunk(state, history, ...)`` idiom rebinds
   at the same statement and is clean).

2. **async-save-of-device-buffers**: a jitted-call result handed to an
   (async) checkpoint ``save``/``async_save`` without an intervening host
   copy — the background writer races the next chunk's donation for the
   same memory. Rebinding through ``jax.device_get`` / ``np.array`` /
   ``.copy()`` clears the taint; synchronous writers (``np.save`` etc.)
   are exempt.

3. **overlap-alias read-after-donation** (the raw-speed-PR bug shape): a
   plain ALIAS of a donated name's subtree (``snap = state.params``) taken
   before the donating call and read after it — the exact hazard of an
   overlapped measurement dispatched on "a snapshot" that is not actually
   a copy: by the time the measurement executes, the aliased buffers
   belong to the next chunk's donation. A rebind through ANY call —
   ``dib_tpu.train.overlap.snapshot_params``, ``jax.device_get``,
   ``jnp.copy`` — is not an alias and stays clean; only bare
   attribute/subscript chains are tracked.

All three analyses run over lexical statement order per scope — and,
since the interprocedural engine (``analysis/project.py``), the set of
"donating callables" is no longer just the module's own jitted defs: a
helper that passes its parameter into a donating call (transitively,
across modules, through re-exported imports and ``self.method`` /
typed-local calls) donates that parameter too, and a helper that
returns an un-copied jitted result propagates the async-save taint to
its callers. Findings through a helper boundary name the chain
(``fit → run_chunk``) so the reader sees where the donation actually
happens. Dynamic dispatch (``for hook in hooks: hook(...)``) stays
invisible by design — see the project-engine docstring for the exact
boundary contract.
"""

from __future__ import annotations

import ast

from dib_tpu.analysis.core import (
    Finding,
    LintPass,
    Module,
    assigned_names,
    register,
    statements_in_order,
    walk_stmt_exprs,
)
from dib_tpu.analysis.jaxutil import jitted_callables, match_callable

#: Attribute names treated as an async checkpoint save sink.
_SAVE_ATTRS = {"save", "async_save"}
#: Receivers whose ``.save`` is a synchronous host write, not an async
#: checkpointer (numpy/matplotlib/json et al read the buffer before
#: returning, which is safe — donation only reuses buffers on the NEXT
#: jitted call, by which point a synchronous save has completed).
_SYNC_SAVE_BASES = {"np", "numpy", "jnp", "plt", "pickle", "json", "os"}
def _names_read(stmt: ast.stmt) -> list[ast.Name]:
    """Every bare-Name load owned by one statement (compound-statement
    bodies and nested defs excluded — they are analyzed on their own)."""
    return [n for n in walk_stmt_exprs(stmt)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)]


def _calls(stmt: ast.stmt) -> list[ast.Call]:
    return [n for n in walk_stmt_exprs(stmt) if isinstance(n, ast.Call)]


@register
class DonationSafetyPass(LintPass):
    id = "donation-safety"
    description = ("reads of donated buffers after the donating call, and "
                   "jitted results handed to async checkpoint saves "
                   "without a host copy")
    incident = ("PR 4: async orbax saves read buffers run_chunk's donation "
                "had already reused — checkpoint steps held a later "
                "epoch's bytes (docs/robustness.md)")

    def check_module(self, module: Module) -> list[Finding]:
        return self.check_module_with_project(module, None)

    def check_module_with_project(self, module: Module,
                                  project) -> list[Finding]:
        registry = dict(jitted_callables(module))
        fresh_returners: set[str] = set()
        if project is not None:
            # summarized donating callables visible in this module — local
            # jit facts win on a name collision (they are the precise ones)
            for name, fn in project.donation_registry(module).items():
                registry.setdefault(name, fn)
            fresh_returners = project.fresh_returners()
        if not registry:
            return []
        findings: list[Finding] = []
        for fn in module.functions():
            findings.extend(self._check_scope(module, fn, registry,
                                              project, fresh_returners))
        return findings

    @staticmethod
    def _display(target) -> str:
        """How a finding names the donating callee: the call-site name,
        plus the helper chain when the donation is interprocedural."""
        return (f"{target.name} [donates through: {target.via}]"
                if target.via else target.name)

    def _check_scope(self, module, fn, registry, project=None,
                     fresh_returners=frozenset()) -> list[Finding]:
        findings: list[Finding] = []
        # name -> (donating call lineno, callee name); dead after donation
        dead: dict[str, tuple[int, str]] = {}
        # alias name -> (root name, aliased expr line): bare
        # attribute/subscript views of a (potentially donated) tree —
        # `snap = state.params`. Dead when their root is donated.
        aliases: dict[str, tuple[str, int]] = {}
        # alias name -> (donating call lineno, callee, root)
        dead_aliases: dict[str, tuple[int, str, str]] = {}
        # name -> (assigning lineno, callee name); device-fresh jit results
        fresh: dict[str, tuple[int, str]] = {}
        for stmt in statements_in_order(fn):
            # 1. reads of donated names (before this stmt's own donations:
            #    the donating call's own argument reads are legal)
            for name_node in _names_read(stmt):
                hit = dead.get(name_node.id)
                if hit is not None:
                    call_line, callee = hit
                    findings.append(self.finding(
                        module, name_node.lineno,
                        f"`{name_node.id}` was donated to `{callee}` at "
                        f"line {call_line} — its buffer now belongs to XLA "
                        "and may hold the next call's output; rebind the "
                        "name to the call's result or fetch what you need "
                        "before the donating call",
                    ))
                    continue
                alias_hit = dead_aliases.get(name_node.id)
                if alias_hit is not None:
                    call_line, callee, root = alias_hit
                    findings.append(self.finding(
                        module, name_node.lineno,
                        f"`{name_node.id}` is a bare alias of `{root}`, "
                        f"which was donated to `{callee}` at line "
                        f"{call_line} — an overlapped measurement reading "
                        "it races XLA's reuse of the donated buffers; "
                        "take a real on-device copy BEFORE the donating "
                        "call (dib_tpu.train.overlap.snapshot_params)",
                    ))
            # 2. async checkpoint saves of device-fresh jit results
            for call in _calls(stmt):
                func = call.func
                if not (isinstance(func, ast.Attribute)
                        and func.attr in _SAVE_ATTRS):
                    continue
                base = func.value
                base_name = base.id if isinstance(base, ast.Name) else None
                if base_name in _SYNC_SAVE_BASES:
                    continue
                tainted = None
                for expr in (*call.args,
                             *(kw.value for kw in call.keywords)):
                    for node in ast.walk(expr):
                        if isinstance(node, ast.Name) and node.id in fresh:
                            tainted = node.id
                            break
                    if tainted:
                        break
                if tainted:
                    src_line, callee = fresh[tainted]
                    findings.append(self.finding(
                        module, call.lineno,
                        f"`{tainted}` (result of jitted `{callee}` at "
                        f"line {src_line}) handed to an async checkpoint "
                        f"`{func.attr}` without a host copy — the "
                        "background writer reads it zero-copy while the "
                        "next donating call reuses the same buffer (the "
                        "PR 4 incident); `jax.device_get` it first, or "
                        "wait for the save before the next chunk",
                    ))
            # 3. this stmt's donations kill their argument names — and any
            #    bare alias taken from them earlier (the overlap hazard) …
            #    EXCEPT when the donating call rides a `return`: control
            #    has left the scope, so lexically-later statements are
            #    unreachable from it (the `return self._fit_overlapped(
            #    key, state, ...)` dispatch shape the interprocedural
            #    summaries made visible — a real donation for the
            #    caller's summary, never a hazard for this scope's tail)
            if isinstance(stmt, ast.Return):
                continue
            for call in _calls(stmt):
                target = match_callable(call, registry)
                if target is None or not target.donated:
                    continue
                for name, _line in target.donated_args(call).items():
                    dead[name] = (call.lineno, self._display(target))
                    for alias, (root, _aline) in aliases.items():
                        if root == name:
                            dead_aliases[alias] = (
                                call.lineno, self._display(target), name)
            # 4. … and any (re)assignment resurrects / re-taints names.
            #    Assignment runs after the RHS call, so the
            #    `x, y = f(x, y)` rebind idiom ends up alive, and a name
            #    assigned from a jitted call becomes device-fresh (a host
            #    copy clears the taint instead).
            assigned = assigned_names(stmt)
            if assigned:
                value = getattr(stmt, "value", None)
                value_jit = (match_callable(value, registry)
                             if isinstance(value, ast.Call) else None)
                # device-fresh taint also flows OUT of helpers: a call
                # resolved to a project function that returns an
                # un-copied jitted result taints its binding the same way
                # a direct jitted call does (analysis/project.py)
                fresh_name: str | None = None
                if value_jit is not None and not value_jit.via:
                    fresh_name = value_jit.name
                elif project is not None and isinstance(value, ast.Call):
                    resolved = project.resolve_call(module, value, scope=fn)
                    if (resolved is not None
                            and resolved.qualname in fresh_returners):
                        fresh_name = resolved.name
                alias_root = _bare_chain_root(value)
                for name in assigned:
                    dead.pop(name, None)
                    dead_aliases.pop(name, None)
                    aliases.pop(name, None)
                    # a rebind of an alias's ROOT orphans the alias: it
                    # views the PREVIOUS (nameless, never-donated) tree, so
                    # a later donation of the new binding must not kill it
                    for alias in [a for a, (root, _l) in aliases.items()
                                  if root == name]:
                        aliases.pop(alias, None)
                    if fresh_name is not None:
                        fresh[name] = (stmt.lineno, fresh_name)
                    else:
                        # any other assignment — including a host copy
                        # (jax.device_get / np.array / .copy()) — clears
                        # the device-buffer taint
                        fresh.pop(name, None)
                    if alias_root is not None and len(assigned) == 1:
                        # `snap = state.params`: a bare view, NOT a copy —
                        # dies with its root's donation. Any Call on the
                        # RHS (snapshot_params, jnp.copy, device_get)
                        # breaks the chain and is not recorded.
                        aliases[name] = (alias_root, stmt.lineno)
            if isinstance(stmt, ast.Delete):
                for target in stmt.targets:
                    if isinstance(target, ast.Name):
                        dead.pop(target.id, None)
                        fresh.pop(target.id, None)
                        aliases.pop(target.id, None)
                        dead_aliases.pop(target.id, None)
        return findings


def _bare_chain_root(node) -> str | None:
    """The root Name of a PURE attribute/subscript chain (`state.params`,
    `states.params["model"]`) — None when the expression involves a call
    or anything else (those produce fresh values, not aliases)."""
    if not isinstance(node, (ast.Attribute, ast.Subscript)):
        return None
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None
