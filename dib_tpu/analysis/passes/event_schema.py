"""event-schema: every emit call site agrees with EVENT_SCHEMA, and so
do the docs.

The incident: the event stream grew to 13 kinds across six PRs with the
schema living only in prose — ``events.py``'s docstring claimed mid-chunk
heartbeats carry ``chunk_elapsed_s`` while the code emitted
``phase_elapsed_s`` (found by writing this pass), and nothing stopped a
call site from inventing a kind or misspelling a field that ``summarize``
would then silently never roll up. The registry
(``dib_tpu.telemetry.events.EVENT_SCHEMA``) is now the single source of
truth; this pass holds the other two surfaces to it:

- **call sites**: every ``<writer>.emit("<kind>", ...)`` and typed-helper
  call (``.mitigation(...)``, ``.heartbeat(...)``, …) on a recognized
  writer is checked — the kind must exist, explicit keyword fields must
  be in the kind's vocabulary, and a literal-kind ``emit`` must pass
  every required field (``**kwargs`` forwarding defers to runtime, where
  ``DIB_TELEMETRY_STRICT=1`` still gates kind membership);
- **docs**: the record-type table in docs/observability.md must list
  exactly the schema's kinds (``request``/``batch`` are documented
  aliases of ``span``);
- **docs, serving rollup** (ISSUE 11 — the PR 10 rollup grew faster
  than its table): the "Serving-rollup keys" list in
  docs/observability.md must name EXACTLY the keys
  ``telemetry/summary.py``'s ``serving_rollup`` emits — extracted from
  the function's AST (``out[...] =`` assigns, ``out.update({...})``
  literals, and keys bound through a for-loop over a literal tuple), so
  the next rollup key cannot ship undocumented.

Writers are recognized conservatively by receiver shape (``telemetry``,
``writer``, ``self.telemetry``, ``self._telemetry``, or a local assigned
from ``EventWriter(...)``/``open_writer(...)``) — a ``.save()``-shaped
heuristic that never fires is worse than one that misses an exotic
alias, and every emitting module in the tree uses these names.
"""

from __future__ import annotations

import ast
import os
import re

from dib_tpu.analysis.core import (
    Finding,
    LintPass,
    Module,
    dotted_name,
    register,
)

#: Receiver spellings recognized as an EventWriter.
_WRITER_RECEIVERS = {"telemetry", "writer", "self.telemetry",
                     "self._telemetry", "self.writer", "self._writer"}
#: Kinds documented in docs/observability.md as named span events.
_DOC_SPAN_ALIASES = {"request", "batch"}

#: Typed helpers whose parameter names differ from the wire field they
#: emit (``EventWriter.span(span_id=..., parent_id=...)`` writes
#: ``span``/``parent``); call-site kwargs are translated before the
#: vocabulary check.
_HELPER_PARAM_ALIASES = {
    "span": {"span_id": "span", "parent_id": "parent"},
}

_DOC_KIND_RE = re.compile(r"\*\*`([a-z_]+)`\*\*")
#: The docs table header that opens the envelope-field table (the rows
#: from here to the first non-`|` line are the documented envelope).
_ENVELOPE_MARKER = "| field | meaning |"
#: The docs table header that opens the request-phase table (ISSUE 17
#: "Request anatomy"): the documented phase vocabulary must mirror
#: telemetry/events.py REQUEST_PHASES exactly.
_PHASE_MARKER = "| phase | meaning |"
#: The docs line that opens the serving-rollup key list (the list itself
#: is the backticked names from here to the next blank line).
_SERVING_KEYS_MARKER = "Serving-rollup keys"
_BACKTICKED_RE = re.compile(r"`([a-z_0-9]+)`")

#: Every summarize rollup whose key list docs/observability.md must
#: mirror exactly: (summary.py function name, docs marker line). The
#: serving row is the PR 10 incident's guard; the streaming row extends
#: it to the dib_tpu/stream control plane (ISSUE 12) — same rule, the
#: code is the source of truth.
_ROLLUP_DOC_CHECKS = (
    ("serving_rollup", _SERVING_KEYS_MARKER),
    ("streaming_rollup", "Streaming-rollup keys"),
    # ISSUE 14: the numerical-integrity rollup (anomaly/quarantine view)
    ("integrity_rollup", "Integrity-rollup keys"),
    # ISSUE 15: the closed-loop study rollup (dib_tpu/study) — the SLO
    # gate keys (rounds_over_budget / unconverged_full_budget) must stay
    # documented as they grow
    ("study_rollup", "Study-rollup keys"),
    # ISSUE 19: the drift-autopilot rollup (dib_tpu/autopilot) — the
    # exactly-once gate key (duplicate_studies) and the breaker/latency
    # gate keys must stay documented as the control plane grows
    ("autopilot_rollup", "Autopilot-rollup keys"),
)


def _schema():
    from dib_tpu.telemetry.events import EVENT_SCHEMA

    return EVENT_SCHEMA


def _writer_locals(module: Module) -> set[str]:
    """Local names assigned from EventWriter(...) / open_writer(...)."""
    out: set[str] = set()
    if module.tree is None:
        return out
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)):
            continue
        callee = dotted_name(node.value.func)
        if callee and callee.split(".")[-1] in ("EventWriter",
                                                "open_writer"):
            out.add(node.targets[0].id)
    return out


@register
class EventSchemaPass(LintPass):
    id = "event-schema"
    description = ("emit/typed-helper call sites checked against the "
                   "EVENT_SCHEMA registry; docs/observability.md checked "
                   "against the same rows")
    incident = ("events.py documented a heartbeat field the code never "
                "emitted (chunk_elapsed_s vs phase_elapsed_s); a "
                "misspelled field is invisible to summarize forever")

    def check_module(self, module: Module) -> list[Finding]:
        if module.tree is None:
            return []
        if module.rel == "dib_tpu/telemetry/events.py":
            # the registry's own module: typed helpers forward to emit()
            # with a variable kind — nothing checkable at this layer
            return []
        schema = _schema()
        helper_kinds = set(schema)  # every typed helper is named its kind
        receivers = set(_WRITER_RECEIVERS) | _writer_locals(module)
        findings: list[Finding] = []
        for call in ast.walk(module.tree):
            if not (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)):
                continue
            method = call.func.attr
            if method != "emit" and method not in helper_kinds:
                continue
            recv = dotted_name(call.func.value)
            if recv not in receivers:
                continue
            if method == "emit":
                if not (call.args
                        and isinstance(call.args[0], ast.Constant)
                        and isinstance(call.args[0].value, str)):
                    continue  # variable kind: runtime strict mode owns it
                kind = call.args[0].value
                if kind not in schema:
                    findings.append(self.finding(
                        module, call.lineno,
                        f"emit of unknown event kind {kind!r} — add a row "
                        "to telemetry/events.py EVENT_SCHEMA and document "
                        "it in docs/observability.md",
                    ))
                    continue
            else:
                kind = method
            spec = schema[kind]
            vocab = set(spec.required) | set(spec.optional)
            has_splat = any(kw.arg is None for kw in call.keywords)
            aliases = _HELPER_PARAM_ALIASES.get(kind, {})
            explicit = {aliases.get(kw.arg, kw.arg)
                        for kw in call.keywords if kw.arg}
            unknown = sorted(explicit - vocab)
            if unknown:
                findings.append(self.finding(
                    module, call.lineno,
                    f"event kind {kind!r} has no field(s) {unknown} in "
                    "EVENT_SCHEMA — add them to the kind's row (and "
                    "docs/observability.md) or fix the spelling",
                ))
            if method == "emit" and not has_splat:
                missing = sorted(set(spec.required) - explicit)
                if missing:
                    findings.append(self.finding(
                        module, call.lineno,
                        f"emit of kind {kind!r} is missing required "
                        f"field(s) {missing} — or use the typed "
                        f"`.{kind}(...)` helper, whose signature binds "
                        "them",
                    ))
        return findings

    # ------------------------------------------------------ project level
    @staticmethod
    def serving_rollup_keys(root: str) -> set[str] | None:
        """The serving rollup's emitted keys (back-compat spelling of
        :meth:`rollup_keys`)."""
        return EventSchemaPass.rollup_keys(root, "serving_rollup")

    @staticmethod
    def rollup_keys(root: str, fn_name: str) -> set[str] | None:
        """The top-level keys a summarize rollup actually emits, read
        from telemetry/summary.py's AST (None when the function cannot
        be found — the caller reports that as its own drift)."""
        path = os.path.join(root, "dib_tpu", "telemetry", "summary.py")
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            return None
        fn = next((node for node in tree.body
                   if isinstance(node, ast.FunctionDef)
                   and node.name == fn_name), None)
        if fn is None:
            return None
        keys: set[str] = set()
        # loop-bound key names: `for prefix, key in ((..., "x"), ...):`
        loop_keys: dict[str, set[str]] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.For) \
                    and isinstance(node.target, ast.Tuple) \
                    and isinstance(node.iter, (ast.Tuple, ast.List)):
                for pos, elt in enumerate(node.target.elts):
                    if not isinstance(elt, ast.Name):
                        continue
                    values = {
                        row.elts[pos].value
                        for row in node.iter.elts
                        if isinstance(row, (ast.Tuple, ast.List))
                        and pos < len(row.elts)
                        and isinstance(row.elts[pos], ast.Constant)
                        and isinstance(row.elts[pos].value, str)
                    }
                    if values:
                        loop_keys.setdefault(elt.id, set()).update(values)
        for node in ast.walk(fn):
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                target = node.target
            if isinstance(target, ast.Subscript) \
                    and isinstance(target.value, ast.Name) \
                    and target.value.id == "out":
                index = target.slice
                if isinstance(index, ast.Constant) \
                        and isinstance(index.value, str):
                    keys.add(index.value)
                elif isinstance(index, ast.Name):
                    keys.update(loop_keys.get(index.id, ()))
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "update" \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "out":
                for arg in node.args:
                    if isinstance(arg, ast.Dict):
                        keys.update(k.value for k in arg.keys
                                    if isinstance(k, ast.Constant)
                                    and isinstance(k.value, str))
        return keys

    @staticmethod
    def envelope_fields(root: str) -> set[str] | None:
        """ENVELOPE_FIELDS as telemetry/events.py declares it, read from
        the AST (None when the module or the tuple cannot be found)."""
        path = os.path.join(root, "dib_tpu", "telemetry", "events.py")
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            return None
        for node in tree.body:
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "ENVELOPE_FIELDS"):
                continue
            if isinstance(node.value, (ast.Tuple, ast.List)):
                return {elt.value for elt in node.value.elts
                        if isinstance(elt, ast.Constant)
                        and isinstance(elt.value, str)}
        return None

    @staticmethod
    def request_phases(root: str) -> set[str] | None:
        """REQUEST_PHASES as telemetry/events.py declares it, read from
        the AST (None when the module or the tuple cannot be found)."""
        path = os.path.join(root, "dib_tpu", "telemetry", "events.py")
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            return None
        for node in tree.body:
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "REQUEST_PHASES"):
                continue
            if isinstance(node.value, (ast.Tuple, ast.List)):
                return {elt.value for elt in node.value.elts
                        if isinstance(elt, ast.Constant)
                        and isinstance(elt.value, str)}
        return None

    def _check_phase_docs(self, root: str,
                          lines: list[str]) -> list[Finding]:
        """The request-phase table in docs/observability.md must name
        exactly events.py's REQUEST_PHASES (ISSUE 17 — the phase clock's
        vocabulary is closed: a phase the server stamps cannot ship
        undocumented, and a documented phase the clock dropped is
        drift)."""
        doc_rel = "docs/observability.md"
        events_rel = "dib_tpu/telemetry/events.py"
        declared = self.request_phases(root)
        if declared is None:
            if os.path.exists(os.path.join(root, events_rel)):
                return [Finding(
                    self.id, events_rel, 1,
                    "REQUEST_PHASES not found as a top-level tuple in "
                    "telemetry/events.py — the phase-table docs guard "
                    "has lost its anchor")]
            return []
        marker_line = None
        documented: dict[str, int] = {}
        for lineno, line in enumerate(lines, 1):
            if marker_line is None:
                if line.strip().startswith(_PHASE_MARKER):
                    marker_line = lineno
                continue
            stripped = line.strip()
            if not stripped.startswith("|"):
                break
            cells = stripped.split("|")
            if len(cells) > 1:
                for phase in _BACKTICKED_RE.findall(cells[1]):
                    documented.setdefault(phase, lineno)
        if marker_line is None:
            return [Finding(
                self.id, doc_rel, 1,
                "docs/observability.md has no request-phase table "
                f"({_PHASE_MARKER!r}) — the phase-clock vocabulary must "
                "stay documented")]
        findings: list[Finding] = []
        for phase in sorted(declared - set(documented)):
            findings.append(Finding(
                self.id, doc_rel, marker_line,
                f"request phase {phase!r} is in telemetry/events.py "
                "REQUEST_PHASES but missing from the phase table"))
        for phase, lineno in sorted(documented.items()):
            if phase not in declared and phase != "---":
                findings.append(Finding(
                    self.id, doc_rel, lineno,
                    f"documented request phase {phase!r} is not in "
                    "telemetry/events.py REQUEST_PHASES — the code is "
                    "the source of truth"))
        return findings

    def _check_envelope_docs(self, root: str,
                             lines: list[str]) -> list[Finding]:
        """The envelope table in docs/observability.md must name exactly
        events.py's ENVELOPE_FIELDS (ISSUE 16 — the `ctx` trace envelope
        joined the wire format; the next envelope field cannot ship
        undocumented, and a documented field the writer dropped is
        drift)."""
        doc_rel = "docs/observability.md"
        events_rel = "dib_tpu/telemetry/events.py"
        declared = self.envelope_fields(root)
        if declared is None:
            if os.path.exists(os.path.join(root, events_rel)):
                return [Finding(
                    self.id, events_rel, 1,
                    "ENVELOPE_FIELDS not found as a top-level tuple in "
                    "telemetry/events.py — the envelope-table docs guard "
                    "has lost its anchor")]
            return []
        marker_line = None
        documented: dict[str, int] = {}
        for lineno, line in enumerate(lines, 1):
            if marker_line is None:
                if line.strip().startswith(_ENVELOPE_MARKER):
                    marker_line = lineno
                continue
            stripped = line.strip()
            if not stripped.startswith("|"):
                break
            cells = stripped.split("|")
            if len(cells) > 1:
                # first column only — `t` / `mono` share a row; prose in
                # the meaning column may backtick anything
                for field in _BACKTICKED_RE.findall(cells[1]):
                    documented.setdefault(field, lineno)
        if marker_line is None:
            return [Finding(
                self.id, doc_rel, 1,
                "docs/observability.md has no envelope-field table "
                f"({_ENVELOPE_MARKER!r}) — the wire envelope must stay "
                "documented")]
        findings: list[Finding] = []
        for field in sorted(declared - set(documented)):
            findings.append(Finding(
                self.id, doc_rel, marker_line,
                f"envelope field {field!r} is in telemetry/events.py "
                "ENVELOPE_FIELDS but missing from the envelope table"))
        for field, lineno in sorted(documented.items()):
            if field not in declared and field != "---":
                findings.append(Finding(
                    self.id, doc_rel, lineno,
                    f"documented envelope field {field!r} is not in "
                    "telemetry/events.py ENVELOPE_FIELDS — the code is "
                    "the source of truth"))
        return findings

    def _check_rollup_docs(self, root: str, lines: list[str],
                           fn_name: str, marker: str) -> list[Finding]:
        """A rollup's key list in docs/observability.md must name exactly
        what the summary.py function emits (the PR 10 serving rollup grew
        faster than the docs table — this pins the two together; the
        streaming rollup rides the same rule)."""
        doc_rel = "docs/observability.md"
        summary_rel = "dib_tpu/telemetry/summary.py"
        emitted = self.rollup_keys(root, fn_name)
        if emitted is None:
            # a tree without the summary module at all (synthetic test
            # roots) has nothing to hold the docs to — but a tree that
            # HAS the module with no findable rollup fn means the
            # guard's anchor moved: that is drift, not a green pass
            if os.path.exists(os.path.join(root, summary_rel)):
                return [Finding(
                    self.id, summary_rel, 1,
                    f"{fn_name} not found as a top-level function in "
                    f"telemetry/summary.py — the {marker!r} docs guard "
                    "has lost its anchor; update "
                    "_ROLLUP_DOC_CHECKS alongside the refactor")]
            return []
        marker_line = None
        documented: dict[str, int] = {}
        for lineno, line in enumerate(lines, 1):
            if marker_line is None:
                if marker in line:
                    marker_line = lineno
                continue
            if not line.strip():
                break
            for key in _BACKTICKED_RE.findall(line):
                documented.setdefault(key, lineno)
        if marker_line is None:
            return [Finding(
                self.id, doc_rel, 1,
                f"docs/observability.md has no {marker!r} "
                "list — the rollup's keys must stay documented "
                f"(telemetry/summary.py {fn_name})")]
        findings: list[Finding] = []
        for key in sorted(emitted - set(documented)):
            findings.append(Finding(
                self.id, doc_rel, marker_line,
                f"rollup key {key!r} is emitted by "
                f"telemetry/summary.py {fn_name} but missing from "
                f"the {marker!r} list"))
        for key, lineno in sorted(documented.items()):
            if key not in emitted:
                findings.append(Finding(
                    self.id, doc_rel, lineno,
                    f"documented rollup key {key!r} is not "
                    f"emitted by telemetry/summary.py {fn_name} — "
                    "the code is the source of truth"))
        return findings

    def check_project(self, root: str) -> list[Finding]:
        """Schema ↔ docs drift: docs/observability.md's record-type list
        must contain exactly EVENT_SCHEMA's kinds (+ the span aliases),
        and its serving-rollup key list exactly what summary.py emits."""
        schema = _schema()
        doc_rel = "docs/observability.md"
        path = os.path.join(root, doc_rel)
        try:
            with open(path, encoding="utf-8") as f:
                lines = f.read().splitlines()
        except OSError:
            return [Finding(self.id, doc_rel, 1,
                            "docs/observability.md missing — the event "
                            "schema must stay documented")]
        documented: dict[str, int] = {}
        in_section = False
        for lineno, line in enumerate(lines, 1):
            if line.startswith("Record types and their payloads"):
                in_section = True
                continue
            if in_section and line.startswith("#"):
                break
            if in_section and line.lstrip().startswith("- **`"):
                for kind in _DOC_KIND_RE.findall(line):
                    documented.setdefault(kind, lineno)
        findings: list[Finding] = []
        for kind in sorted(set(schema) - set(documented)):
            findings.append(Finding(
                self.id, doc_rel, 1,
                f"EVENT_SCHEMA kind {kind!r} is not documented in the "
                "record-type list",
            ))
        for kind, lineno in sorted(documented.items()):
            if kind not in schema and kind not in _DOC_SPAN_ALIASES:
                findings.append(Finding(
                    self.id, doc_rel, lineno,
                    f"documented record type {kind!r} has no EVENT_SCHEMA "
                    "row — the registry is the source of truth",
                ))
        findings.extend(self._check_envelope_docs(root, lines))
        findings.extend(self._check_phase_docs(root, lines))
        for fn_name, marker in _ROLLUP_DOC_CHECKS:
            findings.extend(self._check_rollup_docs(root, lines,
                                                    fn_name, marker))
        return findings
