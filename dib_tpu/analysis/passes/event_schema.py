"""event-schema: every emit call site agrees with EVENT_SCHEMA, and so
do the docs.

The incident: the event stream grew to 13 kinds across six PRs with the
schema living only in prose — ``events.py``'s docstring claimed mid-chunk
heartbeats carry ``chunk_elapsed_s`` while the code emitted
``phase_elapsed_s`` (found by writing this pass), and nothing stopped a
call site from inventing a kind or misspelling a field that ``summarize``
would then silently never roll up. The registry
(``dib_tpu.telemetry.events.EVENT_SCHEMA``) is now the single source of
truth; this pass holds the other two surfaces to it:

- **call sites**: every ``<writer>.emit("<kind>", ...)`` and typed-helper
  call (``.mitigation(...)``, ``.heartbeat(...)``, …) on a recognized
  writer is checked — the kind must exist, explicit keyword fields must
  be in the kind's vocabulary, and a literal-kind ``emit`` must pass
  every required field (``**kwargs`` forwarding defers to runtime, where
  ``DIB_TELEMETRY_STRICT=1`` still gates kind membership);
- **docs**: the record-type table in docs/observability.md must list
  exactly the schema's kinds (``request``/``batch`` are documented
  aliases of ``span``).

Writers are recognized conservatively by receiver shape (``telemetry``,
``writer``, ``self.telemetry``, ``self._telemetry``, or a local assigned
from ``EventWriter(...)``/``open_writer(...)``) — a ``.save()``-shaped
heuristic that never fires is worse than one that misses an exotic
alias, and every emitting module in the tree uses these names.
"""

from __future__ import annotations

import ast
import os
import re

from dib_tpu.analysis.core import (
    Finding,
    LintPass,
    Module,
    dotted_name,
    register,
)

#: Receiver spellings recognized as an EventWriter.
_WRITER_RECEIVERS = {"telemetry", "writer", "self.telemetry",
                     "self._telemetry", "self.writer", "self._writer"}
#: Kinds documented in docs/observability.md as named span events.
_DOC_SPAN_ALIASES = {"request", "batch"}

#: Typed helpers whose parameter names differ from the wire field they
#: emit (``EventWriter.span(span_id=..., parent_id=...)`` writes
#: ``span``/``parent``); call-site kwargs are translated before the
#: vocabulary check.
_HELPER_PARAM_ALIASES = {
    "span": {"span_id": "span", "parent_id": "parent"},
}

_DOC_KIND_RE = re.compile(r"\*\*`([a-z_]+)`\*\*")


def _schema():
    from dib_tpu.telemetry.events import EVENT_SCHEMA

    return EVENT_SCHEMA


def _writer_locals(module: Module) -> set[str]:
    """Local names assigned from EventWriter(...) / open_writer(...)."""
    out: set[str] = set()
    if module.tree is None:
        return out
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)):
            continue
        callee = dotted_name(node.value.func)
        if callee and callee.split(".")[-1] in ("EventWriter",
                                                "open_writer"):
            out.add(node.targets[0].id)
    return out


@register
class EventSchemaPass(LintPass):
    id = "event-schema"
    description = ("emit/typed-helper call sites checked against the "
                   "EVENT_SCHEMA registry; docs/observability.md checked "
                   "against the same rows")
    incident = ("events.py documented a heartbeat field the code never "
                "emitted (chunk_elapsed_s vs phase_elapsed_s); a "
                "misspelled field is invisible to summarize forever")

    def check_module(self, module: Module) -> list[Finding]:
        if module.tree is None:
            return []
        if module.rel == "dib_tpu/telemetry/events.py":
            # the registry's own module: typed helpers forward to emit()
            # with a variable kind — nothing checkable at this layer
            return []
        schema = _schema()
        helper_kinds = set(schema)  # every typed helper is named its kind
        receivers = set(_WRITER_RECEIVERS) | _writer_locals(module)
        findings: list[Finding] = []
        for call in ast.walk(module.tree):
            if not (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)):
                continue
            method = call.func.attr
            if method != "emit" and method not in helper_kinds:
                continue
            recv = dotted_name(call.func.value)
            if recv not in receivers:
                continue
            if method == "emit":
                if not (call.args
                        and isinstance(call.args[0], ast.Constant)
                        and isinstance(call.args[0].value, str)):
                    continue  # variable kind: runtime strict mode owns it
                kind = call.args[0].value
                if kind not in schema:
                    findings.append(self.finding(
                        module, call.lineno,
                        f"emit of unknown event kind {kind!r} — add a row "
                        "to telemetry/events.py EVENT_SCHEMA and document "
                        "it in docs/observability.md",
                    ))
                    continue
            else:
                kind = method
            spec = schema[kind]
            vocab = set(spec.required) | set(spec.optional)
            has_splat = any(kw.arg is None for kw in call.keywords)
            aliases = _HELPER_PARAM_ALIASES.get(kind, {})
            explicit = {aliases.get(kw.arg, kw.arg)
                        for kw in call.keywords if kw.arg}
            unknown = sorted(explicit - vocab)
            if unknown:
                findings.append(self.finding(
                    module, call.lineno,
                    f"event kind {kind!r} has no field(s) {unknown} in "
                    "EVENT_SCHEMA — add them to the kind's row (and "
                    "docs/observability.md) or fix the spelling",
                ))
            if method == "emit" and not has_splat:
                missing = sorted(set(spec.required) - explicit)
                if missing:
                    findings.append(self.finding(
                        module, call.lineno,
                        f"emit of kind {kind!r} is missing required "
                        f"field(s) {missing} — or use the typed "
                        f"`.{kind}(...)` helper, whose signature binds "
                        "them",
                    ))
        return findings

    # ------------------------------------------------------ project level
    def check_project(self, root: str) -> list[Finding]:
        """Schema ↔ docs drift: docs/observability.md's record-type list
        must contain exactly EVENT_SCHEMA's kinds (+ the span aliases)."""
        schema = _schema()
        doc_rel = "docs/observability.md"
        path = os.path.join(root, doc_rel)
        try:
            with open(path, encoding="utf-8") as f:
                lines = f.read().splitlines()
        except OSError:
            return [Finding(self.id, doc_rel, 1,
                            "docs/observability.md missing — the event "
                            "schema must stay documented")]
        documented: dict[str, int] = {}
        in_section = False
        for lineno, line in enumerate(lines, 1):
            if line.startswith("Record types and their payloads"):
                in_section = True
                continue
            if in_section and line.startswith("#"):
                break
            if in_section and line.lstrip().startswith("- **`"):
                for kind in _DOC_KIND_RE.findall(line):
                    documented.setdefault(kind, lineno)
        findings: list[Finding] = []
        for kind in sorted(set(schema) - set(documented)):
            findings.append(Finding(
                self.id, doc_rel, 1,
                f"EVENT_SCHEMA kind {kind!r} is not documented in the "
                "record-type list",
            ))
        for kind, lineno in sorted(documented.items()):
            if kind not in schema and kind not in _DOC_SPAN_ALIASES:
                findings.append(Finding(
                    self.id, doc_rel, lineno,
                    f"documented record type {kind!r} has no EVENT_SCHEMA "
                    "row — the registry is the source of truth",
                ))
        return findings
