"""resource-lifecycle: every spawned process/pipe/socket/thread must have
a reachable close/join/terminate.

The incident shape (PR 8/PR 10, found by chaos drills as fd
exhaustion): the serving and scheduling planes now spawn real OS
resources — ``subprocess.Popen`` re-exec workers (``serve/prefork.py``,
the flag-stripping fork-bomb postmortem's blast radius), ``Pipe()``
request planes and spawn-context worker ``Process``\\es
(``serve/pool.py``), worker/reaper ``Thread``\\s (``sched/pool.py``),
listener sockets (``serve/server.py``). A resource created on some path
with NO reachable ``close``/``join``/``terminate``/``kill``/``wait``
leaks a process table entry or fd per respawn; the chaos suites find it
hours later as ``EMFILE``, not at the creation site.

Decidable rules (conservative in the right direction — a resource that
ESCAPES its scope is the next scope's problem, never a finding here):

- **locals**: a name bound directly from a resource constructor
  (``subprocess.Popen``, ``multiprocessing``/ctx ``Pipe``/``Process``,
  ``threading.Thread``, ``socket.socket``/``create_server``/
  ``create_connection``) — or from a project function whose summary
  says it RETURNS such a resource (interprocedural: factories like
  ``spawn(k)`` / ``reserve_port(host)`` taint their callers) — must be
  closed in the scope (a closer-method call on the name, or a ``with``
  block) unless it escapes: returned/yielded, passed as a call
  argument, stored into an attribute/subscript/container, or aliased.
- **self attributes**: ``self.x = <resource ctor>`` must have SOME
  method of the class calling a closer on ``self.x`` (or passing it
  out). The class closing its resources in ``close()`` is the contract;
  whether ``close()`` is called is the caller's lifecycle.
- ``daemon=True`` **threads** are exempt (fire-and-forget by declared
  intent; the interpreter reaps them) — daemon PROCESSES are not (a
  spawned process holds pipes and a pid either way).

Tuple-unpacked constructors (``parent, child = Pipe()``) bind every
target as a resource; factory summaries carry which tuple positions
are resources, so ``sock, port = reserve_port(host)`` taints exactly
``sock``.
"""

from __future__ import annotations

import ast

from dib_tpu.analysis.core import (
    Finding,
    LintPass,
    Module,
    call_name,
    register,
    statements_in_order,
    walk_stmt_exprs,
)

#: Closer method names: any of these called on the resource counts as a
#: reachable lifecycle end (correct USE of them is runtime's problem).
_CLOSERS = {"close", "terminate", "kill", "join", "wait", "shutdown",
            "communicate", "stop", "release", "detach", "unlink"}

#: Terminal ctor names accepted on ANY receiver (spawn contexts:
#: ``self._ctx.Pipe()``), vs those requiring their canonical module base.
#: The terminal name itself is the "kind" findings print.
_CTOR_ANY_BASE = {"Popen", "Pipe", "Process", "Thread"}
_CTOR_SOCKET = {"socket", "create_server", "create_connection"}


def _resource_ctor(call: ast.Call) -> str | None:
    """The resource kind a constructor call creates, else None."""
    name = call_name(call)
    if name is None:
        return None
    parts = name.split(".")
    terminal = parts[-1]
    if terminal in _CTOR_ANY_BASE:
        if terminal == "Thread" and _is_daemon(call):
            return None
        if terminal == "Process" and parts[0] not in (
                "multiprocessing", "mp", "self", "ctx") \
                and len(parts) == 1:
            return None   # a bare local Process() class is not stdlib's
        return terminal
    if terminal in _CTOR_SOCKET and parts[0] == "socket":
        return "socket"
    return None


def _is_daemon(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return False


@register
class ResourceLifecyclePass(LintPass):
    id = "resource-lifecycle"
    description = ("subprocess/pipe/socket/thread objects with no "
                   "reachable close/join/terminate in their owning scope "
                   "or class (escapes are the next scope's problem)")
    incident = ("the PR 10 prefork/pool planes respawn worker processes "
                "and pipes on every heal; a handle dropped on any path "
                "leaks a pid+fds per respawn — the chaos suites find it "
                "hours later as fd exhaustion (EMFILE), never at the "
                "creation site (docs/serving.md, docs/robustness.md)")

    def check_module(self, module: Module) -> list[Finding]:
        return self.check_module_with_project(module, None)

    def check_module_with_project(self, module: Module,
                                  project) -> list[Finding]:
        if module.tree is None:
            return []
        factories = (self._factory_summaries(project)
                     if project is not None else {})
        if not factories and not any(
                tok in module.source
                for tok in ("Popen", "Pipe", "Process", "Thread", "socket")):
            return []   # no ctor tokens AND no factories to flow in from
        findings: list[Finding] = []
        for fn in module.functions():
            findings.extend(self._check_scope(module, fn, project,
                                              factories))
        findings.extend(self._check_self_attrs(module))
        return findings

    # ----------------------------------------------- factory summaries
    def _factory_summaries(self, project) -> dict[str, dict]:
        """``{qualname: {position or None: kind}}`` for project functions
        returning live resources (position None = the bare return value;
        ints index a returned tuple). The shared call-graph fixpoint
        (Project.fixpoint), so factory-of-factory chains resolve."""
        return project.fixpoint(
            "_resource_factory_facts",
            lambda info, facts: self._returned_resources(
                project.modules[info.rel], info.node, project, facts))

    def _resource_locals(self, module, fn, project, facts,
                         ) -> dict[str, tuple[int, str]]:
        """name -> (creation line, kind) for locals bound from resource
        ctors or summarized factories (tuple-unpack aware)."""
        out: dict[str, tuple[int, str]] = {}
        for stmt in statements_in_order(fn):
            if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1):
                continue
            target, value = stmt.targets[0], stmt.value
            if not isinstance(value, ast.Call):
                continue
            kind = _resource_ctor(value)
            positions: dict = {}
            if kind is not None:
                positions = ({0: kind, 1: kind} if kind == "Pipe"
                             else {None: kind})
            elif project is not None:
                info = project.resolve_call(module, value, scope=fn)
                if info is not None:
                    positions = facts.get(info.qualname, {})
            if not positions:
                continue
            if isinstance(target, ast.Name):
                # bare binding: one resource (or a holder of several —
                # closing the elements needs an unpack first either way)
                out[target.id] = (stmt.lineno,
                                  next(iter(positions.values())))
            elif isinstance(target, ast.Tuple):
                for i, elt in enumerate(target.elts):
                    tkind = positions.get(i)
                    if isinstance(elt, ast.Name) and tkind is not None:
                        out[elt.id] = (stmt.lineno, tkind)
        return out

    def _returned_resources(self, module, fn, project, facts) -> dict:
        locals_ = self._resource_locals(module, fn, project, facts)
        closed = self._closed_names(fn)
        out: dict = {}
        for stmt in statements_in_order(fn):
            if not isinstance(stmt, ast.Return) or stmt.value is None:
                continue
            value = stmt.value
            if isinstance(value, ast.Call):
                kind = _resource_ctor(value)
                if kind is not None:
                    out[None] = kind
            elif isinstance(value, ast.Name) and value.id in locals_ \
                    and value.id not in closed:
                out[None] = locals_[value.id][1]
            elif isinstance(value, ast.Tuple):
                for i, elt in enumerate(value.elts):
                    if isinstance(elt, ast.Call):
                        kind = _resource_ctor(elt)
                        if kind is not None:
                            out[i] = kind
                    elif isinstance(elt, ast.Name) \
                            and elt.id in locals_ \
                            and elt.id not in closed:
                        out[i] = locals_[elt.id][1]
        return out

    # ------------------------------------------------------ scope check
    @staticmethod
    def _closed_names(fn) -> set[str]:
        """Names with a reachable closer in the scope: ``name.close()``
        etc anywhere (order-insensitive — a lint proves reachability
        exists, not that every path takes it), or managed by ``with``."""
        closed: set[str] = set()
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _CLOSERS
                    and isinstance(node.func.value, ast.Name)):
                closed.add(node.func.value.id)
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    ctx = item.context_expr
                    if isinstance(ctx, ast.Name):
                        closed.add(ctx.id)
                    if item.optional_vars is not None and isinstance(
                            item.optional_vars, ast.Name):
                        closed.add(item.optional_vars.id)
        return closed

    @staticmethod
    def _handle_names(module, root: ast.AST):
        """Bare Names in ``root`` whose VALUE (the handle itself) flows
        out — a Name that is merely the base of an attribute chain
        (``proc.pid``, ``proc.returncode``) passes an attribute, never
        the handle, and must not launder the leak."""
        for sub in ast.walk(root):
            if isinstance(sub, ast.Name) and not isinstance(
                    module.parent_of(sub), ast.Attribute):
                yield sub.id

    def _escaped_names(self, module, fn) -> set[str]:
        """Names whose value leaves the scope: returned/yielded, passed
        to any call, stored into an attribute/subscript/container, or
        aliased by a plain assignment. Receiver-position uses
        (``proc.poll()``) and attribute reads handed elsewhere
        (``log.info('%s', proc.pid)``) do NOT escape — only the bare
        handle transfers ownership."""
        escaped: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                for arg in (*node.args,
                            *(kw.value for kw in node.keywords)):
                    escaped.update(self._handle_names(module, arg))
            elif isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
                value = getattr(node, "value", None)
                if value is not None:
                    escaped.update(self._handle_names(module, value))
            elif isinstance(node, ast.Assign):
                # aliasing / storing: any bare-Name RHS element escapes
                # when the target is not a plain Name rebind of itself
                stores = any(not isinstance(t, ast.Name)
                             for t in node.targets)
                if isinstance(node.value, ast.Name):
                    escaped.add(node.value.id)      # plain alias
                elif stores or isinstance(node.value,
                                          (ast.Tuple, ast.List, ast.Dict)):
                    escaped.update(self._handle_names(module, node.value))
        return escaped

    def _check_scope(self, module, fn, project, factories) -> list[Finding]:
        findings: list[Finding] = []
        # a resource constructor whose handle is DISCARDED outright — a
        # bare `subprocess.Popen(cmd)` statement — can never be closed
        for stmt in statements_in_order(fn):
            if isinstance(stmt, ast.Expr) \
                    and isinstance(stmt.value, ast.Call):
                kind = _resource_ctor(stmt.value)
                if kind is not None:
                    findings.append(self.finding(
                        module, stmt.lineno,
                        f"{kind} handle discarded — nothing can ever "
                        "close, join, or terminate it; bind it and end "
                        "its life (or hand it to an owner that does)",
                    ))
        locals_ = self._resource_locals(module, fn, project, factories)
        if not locals_:
            return findings
        closed = self._closed_names(fn)
        escaped = self._escaped_names(module, fn)
        for name, (line, kind) in sorted(locals_.items()):
            if name in closed or name in escaped:
                continue
            findings.append(self.finding(
                module, line,
                f"`{name}` ({kind}) is created here but no path in "
                f"`{fn.name}` closes, joins, or hands it off — each "
                "leaked handle is a pid/fd the chaos drills find later "
                "as EMFILE; close it in a finally (or return it to an "
                "owner that does)",
            ))
        return findings

    # -------------------------------------------------- self attributes
    def _check_self_attrs(self, module) -> list[Finding]:
        findings: list[Finding] = []
        for cls in ast.walk(module.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            created: dict[str, tuple[int, str]] = {}
            managed: set[str] = set()
            for node in ast.walk(cls):
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Attribute)
                        and isinstance(node.targets[0].value, ast.Name)
                        and node.targets[0].value.id == "self"
                        and isinstance(node.value, ast.Call)):
                    kind = _resource_ctor(node.value)
                    if kind is not None:
                        created.setdefault(
                            node.targets[0].attr, (node.lineno, kind))
                if (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Attribute)
                        and isinstance(node.value.value, ast.Name)
                        and node.value.value.id == "self"
                        and node.attr in _CLOSERS):
                    managed.add(node.value.attr)   # self.X.close reachable
                if (isinstance(node, ast.Call)):
                    for arg in (*node.args,
                                *(kw.value for kw in node.keywords)):
                        if (isinstance(arg, ast.Attribute)
                                and isinstance(arg.value, ast.Name)
                                and arg.value.id == "self"):
                            managed.add(arg.attr)  # handed off
            for attr, (line, kind) in sorted(created.items()):
                if attr in managed:
                    continue
                findings.append(self.finding(
                    module, line,
                    f"`self.{attr}` ({kind}) is created but no method of "
                    f"`{cls.name}` ever closes/joins/terminates it — the "
                    "class cannot possibly end the resource's life; add "
                    "it to close() (the serve/pool.py WorkerReplica "
                    "contract)",
                ))
        return findings
