"""thread-shared-state: unguarded self-mutation from a thread target.

The incident (PR 6, CHANGES.md): ``EventWriter.emit`` was called from
both the fit thread and the new mid-chunk heartbeat daemon thread — the
unsynchronized ``seq`` counter produced gapped/duplicated sequence
numbers until a lock was added by hand in review. The shape is general:
a module spawns ``threading.Thread(target=...)``, the target mutates
``self.<attr>``, and the class holds no ``Lock``/``RLock`` — every such
attribute is a data race waiting for a scheduler interleaving to prove
it.

This pass finds, per module that spawns threads: every assignment (or
aug-assignment, the classic ``self.x += 1`` read-modify-write) to a
``self`` attribute inside a thread-target function — a method, a local
closure, or anything reachable as the ``target=`` argument — whose
owning class nowhere assigns a ``threading.Lock()`` / ``RLock()``.
Classes that hold a lock are trusted to use it (locking *correctness* is
beyond a linter); classes with no lock at all cannot possibly be
synchronized, which is exactly the decidable half of the bug.
"""

from __future__ import annotations

import ast

from dib_tpu.analysis.core import (
    Finding,
    LintPass,
    Module,
    call_name,
    register,
)

_LOCKISH = {"Lock", "RLock"}


def _is_thread_ctor(call: ast.Call) -> bool:
    name = call_name(call)
    return name is not None and name.split(".")[-1] == "Thread"


def _lock_classes(module: Module) -> set[ast.ClassDef]:
    """Classes that assign a threading.Lock/RLock anywhere in their body
    (``self._lock = threading.Lock()`` in __init__, or a class attr)."""
    out: set[ast.ClassDef] = set()
    if module.tree is None:
        return out
    for cls in ast.walk(module.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        for node in ast.walk(cls):
            if isinstance(node, ast.Call):
                name = call_name(node)
                if name and name.split(".")[-1] in _LOCKISH:
                    out.add(cls)
                    break
    return out


def _self_mutations(fn: ast.AST) -> list[tuple[int, str]]:
    """(line, attr) for every ``self.<attr> = ...`` / ``self.<attr> op= ...``
    inside ``fn``, nested closures included (they share the race)."""
    out: list[tuple[int, str]] = []
    for node in ast.walk(fn):
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for target in targets:
            if (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                out.append((target.lineno, target.attr))
    return out


@register
class ThreadSharedStatePass(LintPass):
    id = "thread-shared-state"
    description = ("self-attribute mutation from a threading.Thread target "
                   "in a class that holds no Lock/RLock")
    incident = ("PR 6: EventWriter.emit raced the mid-chunk heartbeat "
                "thread — gapped seq numbers until a lock was added by "
                "hand in review (CHANGES.md)")

    def _resolve_target(self, module: Module, call: ast.Call,
                        target: ast.expr):
        """The FunctionDef a ``target=`` expression names, resolved in the
        right scope: ``target=self._run`` searches the spawning class
        (NOT a module-wide name map — another class's same-named method
        must not shadow it), ``target=<name>`` searches the enclosing
        functions innermost-first, then the module top level."""
        defs = (ast.FunctionDef, ast.AsyncFunctionDef)
        if isinstance(target, ast.Name):
            for anc in module.ancestors(call):
                if isinstance(anc, defs):
                    for node in ast.walk(anc):
                        if (isinstance(node, defs) and node is not anc
                                and node.name == target.id):
                            return node, target.id
            for node in module.tree.body:
                if isinstance(node, defs) and node.name == target.id:
                    return node, target.id
        elif (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"):
            cls = module.enclosing_class(call)
            if cls is not None:
                for node in ast.walk(cls):
                    if isinstance(node, defs) and node.name == target.attr:
                        return node, f"self.{target.attr}"
        return None, None

    def check_module(self, module: Module) -> list[Finding]:
        if module.tree is None or "Thread" not in module.source:
            return []
        locked = _lock_classes(module)
        findings: list[Finding] = []
        seen: set[tuple[int, str]] = set()
        for call in ast.walk(module.tree):
            if not (isinstance(call, ast.Call) and _is_thread_ctor(call)):
                continue
            target = next((kw.value for kw in call.keywords
                           if kw.arg == "target"), None)
            if target is None:
                continue
            target_fn, target_name = self._resolve_target(
                module, call, target)
            if target_fn is None:
                continue
            # the class whose state the target can reach: the target's own
            # enclosing class, else the spawner's (closures inside methods)
            cls = (module.enclosing_class(target_fn)
                   or module.enclosing_class(call))
            if cls is None or cls in locked:
                continue
            for line, attr in _self_mutations(target_fn):
                if (line, attr) in seen:
                    continue
                seen.add((line, attr))
                findings.append(self.finding(
                    module, line,
                    f"`self.{attr}` is mutated from thread target "
                    f"`{target_name}` but class `{cls.name}` holds no "
                    "threading.Lock/RLock — the EventWriter.emit race "
                    "class; guard the shared state with a lock",
                ))
        return findings
