"""mesh-consistency: PartitionSpecs, shard_map specs, and donation must
agree with the meshes the project actually builds.

The incident class this pass exists for is the ROADMAP's next move: the
2D ``Mesh(('sweep','data'))`` pjit refactor. Sharding bugs are the worst
JAX bug shape — a ``PartitionSpec`` naming an axis the mesh doesn't
have, or a ``shard_map`` whose in_specs don't match its function's
arguments, fails deep inside XLA with an error naming neither the spec
nor the call site; and a checkpoint RESTORED under a different sharding
constraint than it was saved with doesn't fail at all — it silently
reshards, and the resumed β-sweep trains on differently-laid-out
buffers (the reshard-on-restore shape a stacked-replica restore lives
or dies on, docs/parallelism.md).

Four decidable checks, all against the project-wide mesh facts the
interprocedural engine collects (axis names from ``Mesh(...)``
constructions plus the repo's ``*_AXIS`` module constants, resolved
through imports):

1. **unknown axis**: a ``PartitionSpec``/``P`` literal naming an axis no
   project mesh defines;
2. **rank overflow**: a spec with more entries than the widest project
   mesh has axes; duplicate axis names in one ``Mesh`` construction;
3. **shard_map arity**: literal ``in_specs`` tuples vs the wrapped
   function's parameter count (when the function resolves locally);
4. **donation × sharding**: a ``jax.jit`` call carrying BOTH
   ``donate_argnums``/``argnames`` AND literal ``in_shardings``/
   ``out_shardings`` where a donated argument's input spec differs from
   the output spec at the same position — XLA cannot reuse the buffer
   in place, so the donation buys nothing while the input is still
   invalidated; and **save/restore spec drift**: inside one class, a
   ``save``-named method applying a sharding constraint ``P(a)`` to the
   tree it persists while a ``restore``-named method applies a
   different ``P(b)`` to what it loads.

Unresolvable axis expressions (computed specs, meshes built from
variables) are skipped, never guessed at.
"""

from __future__ import annotations

import ast

from dib_tpu.analysis.core import (
    Finding,
    LintPass,
    Module,
    call_name,
    register,
)

_SPEC_NAMES = {"PartitionSpec", "P"}
_CONSTRAINT_CALLS = {"with_sharding_constraint", "device_put"}


def _spec_call(call: ast.Call) -> bool:
    name = call_name(call)
    return name is not None and name.split(".")[-1] in _SPEC_NAMES


def _mesh_ctor(call: ast.Call) -> bool:
    name = call_name(call)
    return name is not None and name.split(".")[-1] == "Mesh"


def _mesh_axis_names(module: Module, call: ast.Call, project=None,
                     ) -> list[str | None] | None:
    """The resolved axis names of one ``Mesh(...)`` construction —
    positional ``args[1]`` or the ``axis_names`` keyword, each entry a
    string (or None when unresolvable) — or None when the axis tuple is
    not a literal at all. The ONE extraction both the project-wide
    MeshFacts collection and the duplicate-axis check read."""
    names_arg = call.args[1] if len(call.args) >= 2 else None
    for kw in call.keywords:
        if kw.arg == "axis_names":
            names_arg = kw.value
    if not isinstance(names_arg, (ast.Tuple, ast.List)):
        return None
    return [_const_str(module, e, project) for e in names_arg.elts]


def _const_str(module: Module, node: ast.expr,
               project=None) -> str | None:
    """A string constant, directly or through a module-level constant
    (``BETA_AXIS``), following project imports for cross-module
    constants."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return _module_const(module, node.id, project)
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        # mesh.BETA_AXIS through an imported module alias
        if project is not None:
            imported = project._imports.get(module.rel, {}).get(node.value.id)
            if imported is not None and imported[1] is None:
                target = project.modules.get(imported[0])
                if target is not None:
                    return _module_const(target, node.attr, project)
    return None


def _module_const(module: Module, name: str, project=None,
                  _depth: int = 0) -> str | None:
    if module.tree is None or _depth > 4:
        return None
    for node in module.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == name
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            return node.value.value
    if project is not None:
        imported = project._imports.get(module.rel, {}).get(name)
        if imported is not None and imported[1] is not None:
            target = project.modules.get(imported[0])
            if target is not None:
                return _module_const(target, imported[1], project,
                                     _depth + 1)
    return None


def _spec_axes(module: Module, call: ast.Call, project=None,
               ) -> list[str | None]:
    """One resolved entry per spec position: the axis name(s) as strings,
    or None for an unresolvable/None entry. Tuple entries (an axis pair
    sharding one dim over two mesh axes) contribute each name."""
    out: list[str | None] = []
    for arg in call.args:
        if isinstance(arg, ast.Constant) and arg.value is None:
            out.append(None)
            continue
        if isinstance(arg, (ast.Tuple, ast.List)):
            for elt in arg.elts:
                out.append(_const_str(module, elt, project))
            continue
        out.append(_const_str(module, arg, project))
    return out


def _spec_signature(module: Module, call: ast.Call, project=None) -> tuple:
    """A comparable signature for one literal spec (position-wise resolved
    axis names; unresolvable entries compare as the marker ``...``)."""
    sig = []
    for arg in call.args:
        if isinstance(arg, (ast.Tuple, ast.List)):
            sig.append(tuple(_const_str(module, e, project) or ...
                             for e in arg.elts))
        elif isinstance(arg, ast.Constant) and arg.value is None:
            sig.append(None)
        else:
            sig.append(_const_str(module, arg, project) or ...)
    return tuple(sig)


def mesh_facts(project) -> "MeshFacts":
    """The project's mesh facts, built once and cached on the project —
    the ONE accessor both the pass and the cache's global-facts digest
    read, so they can never compute facts from different inputs."""
    facts = getattr(project, "_mesh_facts", None)
    if facts is None:
        facts = MeshFacts(project.modules.values(), project)
        project._mesh_facts = facts
    return facts


class MeshFacts:
    """Project-wide mesh knowledge: every axis name any mesh defines and
    the widest mesh rank — collected from ``Mesh(...)`` constructions
    with literal/constant axis tuples and the ``*_AXIS`` module-constant
    convention (``parallel/mesh.py``)."""

    def __init__(self, modules, project=None):
        self.axes: set[str] = set()
        self.max_rank: int | None = None
        for module in modules:
            if module.tree is None:
                continue
            for node in module.tree.body:
                if (isinstance(node, ast.Assign) and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and node.targets[0].id.endswith("_AXIS")
                        and isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, str)):
                    self.axes.add(node.value.value)
            for call in ast.walk(module.tree):
                if not (isinstance(call, ast.Call) and _mesh_ctor(call)):
                    continue
                resolved = _mesh_axis_names(module, call, project)
                if resolved is None or any(r is None for r in resolved):
                    continue
                self.axes.update(resolved)
                rank = len(resolved)
                self.max_rank = (rank if self.max_rank is None
                                 else max(self.max_rank, rank))


@register
class MeshConsistencyPass(LintPass):
    id = "mesh-consistency"
    description = ("PartitionSpec axes vs project mesh axis names, "
                   "shard_map in_specs arity vs the wrapped function, "
                   "donation composed with mismatched pjit shardings, "
                   "save/restore sharding-constraint drift")
    incident = ("the 2D Mesh(('sweep','data')) pjit refactor's failure "
                "shapes: a spec axis the mesh lacks dies deep in XLA "
                "naming neither; a checkpoint restored under a different "
                "constraint than its save site silently RESHARDS the "
                "resumed sweep (the reshard-on-restore bug, "
                "docs/parallelism.md)")

    def check_module(self, module: Module) -> list[Finding]:
        return self.check_module_with_project(module, None)

    def check_module_with_project(self, module: Module,
                                  project) -> list[Finding]:
        if module.tree is None:
            return []
        src = module.source
        if not any(tok in src for tok in ("PartitionSpec", "Mesh",
                                          "shard_map", "P(")):
            return []
        facts = (mesh_facts(project) if project is not None
                 else MeshFacts([module]))
        findings: list[Finding] = []
        findings.extend(self._check_specs(module, facts, project))
        findings.extend(self._check_mesh_ctors(module, project))
        findings.extend(self._check_shard_maps(module, project))
        findings.extend(self._check_jit_sharding(module, project))
        findings.extend(self._check_save_restore(module, project))
        return findings

    # ------------------------------------------------------------- axes
    def _check_specs(self, module, facts: MeshFacts, project):
        findings = []
        for call in ast.walk(module.tree):
            if not (isinstance(call, ast.Call) and _spec_call(call)):
                continue
            axes = _spec_axes(module, call, project)
            if facts.axes:
                for axis in axes:
                    if axis is not None and axis not in facts.axes:
                        findings.append(self.finding(
                            module, call.lineno,
                            f"PartitionSpec axis {axis!r} is not an axis "
                            "of any mesh this project builds (known: "
                            f"{sorted(facts.axes)}) — the pjit/shard_map "
                            "using it will fail deep in XLA, or worse, "
                            "fall back to replication",
                        ))
            # spec LENGTH is the array's rank, not the mesh's — a 3D
            # array on a 2D mesh legitimately writes P('sweep','data',
            # None). What IS decidable: one axis cannot shard two
            # dimensions, and a spec cannot name more DISTINCT axes
            # than the widest mesh has.
            named = [a for a in axes if a is not None]
            dupes = sorted({a for a in named if named.count(a) > 1})
            for axis in dupes:
                findings.append(self.finding(
                    module, call.lineno,
                    f"PartitionSpec uses axis {axis!r} for two "
                    "dimensions — a mesh axis can shard at most one "
                    "array dimension",
                ))
            if facts.max_rank is not None and not dupes \
                    and len(set(named)) > facts.max_rank:
                findings.append(self.finding(
                    module, call.lineno,
                    f"PartitionSpec names {len(set(named))} distinct "
                    f"axes but the widest project mesh has "
                    f"{facts.max_rank} — no single mesh this project "
                    "builds can satisfy the spec",
                ))
        return findings

    def _check_mesh_ctors(self, module, project):
        findings = []
        for call in ast.walk(module.tree):
            if not (isinstance(call, ast.Call) and _mesh_ctor(call)):
                continue
            resolved = _mesh_axis_names(module, call, project)
            if resolved is None:
                continue
            named = [r for r in resolved if r is not None]
            if len(named) != len(set(named)):
                findings.append(self.finding(
                    module, call.lineno,
                    f"Mesh axis names {named} contain a duplicate — every "
                    "axis must be unique for PartitionSpecs to be "
                    "unambiguous",
                ))
        return findings

    # -------------------------------------------------------- shard_map
    def _check_shard_maps(self, module, project):
        findings = []
        local_defs = {}
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local_defs[node.name] = node
        for call in ast.walk(module.tree):
            if not isinstance(call, ast.Call):
                continue
            name = call_name(call)
            if name is None or name.split(".")[-1] != "shard_map":
                continue
            target = call.args[0] if call.args else None
            fn = (local_defs.get(target.id)
                  if isinstance(target, ast.Name) else None)
            if fn is None and isinstance(target, ast.Name) \
                    and project is not None:
                resolved = project.resolve_symbol(module.rel, target.id)
                if resolved is not None and resolved[0] == "func":
                    fn = resolved[1].node
            if fn is None:
                continue
            n_params = len(fn.args.posonlyargs) + len(fn.args.args)
            for kw in call.keywords:
                if kw.arg != "in_specs":
                    continue
                if isinstance(kw.value, (ast.Tuple, ast.List)):
                    n_specs = len(kw.value.elts)
                    if n_specs != n_params and not fn.args.vararg:
                        findings.append(self.finding(
                            module, call.lineno,
                            f"shard_map in_specs has {n_specs} entries "
                            f"but `{fn.name}` takes {n_params} "
                            "argument(s) — every argument needs exactly "
                            "one spec (XLA's error will not name either "
                            "side)",
                        ))
        return findings

    # ------------------------------------------------ donation × sharding
    def _check_jit_sharding(self, module, project):
        """Both jit spellings the repo uses: direct ``jax.jit(fn, ...)``
        rebindings AND the dominant decorator forms
        (``@partial(jax.jit, ...)`` / ``@jax.jit(...)``) — the 2D-mesh
        refactor will write the decorator shape, so skipping it would
        skip the check entirely."""
        findings = []
        for call in ast.walk(module.tree):
            if not isinstance(call, ast.Call):
                continue
            name = call_name(call)
            if name not in ("jax.jit", "jit", "pjit", "jax.pjit"):
                continue
            parent = module.parent_of(call)
            if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and call in parent.decorator_list:
                continue   # `@jax.jit(...)`: the decorator walk owns it
            wrapped = call.args[0] if call.args else None
            fn = None
            if isinstance(wrapped, ast.Name):
                fn = self._local_def(module, wrapped.id)
            findings.extend(self._jit_sharding_site(
                module, project, call, fn))
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for deco in node.decorator_list:
                if not isinstance(deco, ast.Call):
                    continue
                from dib_tpu.analysis.jaxutil import _jit_decoration

                if _jit_decoration(deco) is None:
                    continue
                findings.extend(self._jit_sharding_site(
                    module, project, deco, node))
        return findings

    @staticmethod
    def _local_def(module, name: str):
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == name:
                return node
        return None

    def _jit_sharding_site(self, module, project, call: ast.Call,
                           fn) -> list[Finding]:
        """One jit application (call or decorator): donated positions
        whose literal in/out sharding specs differ."""
        from dib_tpu.analysis.jaxutil import _int_elts, _string_elts

        kws = {kw.arg: kw.value for kw in call.keywords if kw.arg}
        donate_nums = _int_elts(kws.get("donate_argnums",
                                        ast.Tuple(elts=[])))
        donate_names = _string_elts(kws.get("donate_argnames",
                                            ast.Tuple(elts=[])))
        in_sh = kws.get("in_shardings")
        out_sh = kws.get("out_shardings")
        if not (donate_nums or donate_names) or in_sh is None \
                or out_sh is None:
            return []
        if not isinstance(in_sh, (ast.Tuple, ast.List)):
            return []
        positions = set(donate_nums)
        if donate_names and fn is not None:
            params = [a.arg for a in (*fn.args.posonlyargs,
                                      *fn.args.args)]
            positions.update(params.index(p) for p in donate_names
                             if p in params)
        out_elts = (out_sh.elts
                    if isinstance(out_sh, (ast.Tuple, ast.List))
                    else [out_sh])
        findings = []
        for pos in sorted(positions):
            if pos >= len(in_sh.elts) or pos >= len(out_elts):
                continue
            in_spec, out_spec = in_sh.elts[pos], out_elts[pos]
            if not (isinstance(in_spec, ast.Call)
                    and _spec_call(in_spec)
                    and isinstance(out_spec, ast.Call)
                    and _spec_call(out_spec)):
                continue
            if _spec_signature(module, in_spec, project) != \
                    _spec_signature(module, out_spec, project):
                findings.append(self.finding(
                    module, call.lineno,
                    f"argument {pos} is donated but its in_sharding "
                    "and out_sharding specs differ — XLA cannot "
                    "reuse a donated buffer across a reshard, so "
                    "the donation saves nothing while the input is "
                    "still invalidated; align the specs or drop the "
                    "donation",
                ))
        return findings

    # -------------------------------------------------- save vs restore
    def _constraints_in(self, module, fn, project) -> list[tuple]:
        """Literal spec signatures applied via with_sharding_constraint /
        device_put(..., NamedSharding(mesh, P(...))) inside one function."""
        out = []
        for call in ast.walk(fn):
            if not isinstance(call, ast.Call):
                continue
            name = call_name(call)
            terminal = name.split(".")[-1] if name else None
            if terminal not in _CONSTRAINT_CALLS:
                continue
            for node in ast.walk(call):
                if node is call:
                    continue
                if isinstance(node, ast.Call) and _spec_call(node):
                    out.append(_spec_signature(module, node, project))
        # repr key: signatures mix None/str/tuple/Ellipsis, which do not
        # order under < — a bare sorted() would crash the whole run
        return sorted(out, key=repr)

    def _check_save_restore(self, module, project):
        findings = []
        for cls in ast.walk(module.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            save_specs: list[tuple] = []
            restore_specs: list[tuple] = []
            restore_line = None
            for item in cls.body:
                if not isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                specs = self._constraints_in(module, item, project)
                if not specs:
                    continue
                if "save" in item.name:
                    save_specs.extend(specs)
                elif "restore" in item.name or "load" in item.name:
                    restore_specs.extend(specs)
                    restore_line = restore_line or item.lineno
            if save_specs and restore_specs \
                    and sorted(save_specs, key=repr) \
                    != sorted(restore_specs, key=repr):
                findings.append(self.finding(
                    module, restore_line,
                    f"`{cls.name}` restores under sharding constraint(s) "
                    f"{restore_specs} but saves under {save_specs} — a "
                    "restore whose constraint differs from the save site "
                    "silently RESHARDS the checkpoint (the "
                    "reshard-on-restore bug the 2D mesh refactor must "
                    "not ship with); make both sites read one spec",
                ))
        return findings
