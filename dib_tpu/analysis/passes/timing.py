"""timing-hygiene: no bare wall-clock deltas around jitted work.

The incident (PR 2, docs/observability.md "async-dispatch pitfall"): JAX
dispatch is asynchronous, so ``t0 = time.time(); f(x); dt = time.time()
- t0`` measures only the DISPATCH — a phantom speedup that burned real
measurement rounds before the blocking timers existed. The package's
honest primitives are ``utils.profiling.PhaseTimer`` / ``timed_blocked``
and ``telemetry.trace.span`` (both block on registered outputs before
closing the interval).

Migrated from ``scripts/check_timing_hygiene.py`` (which now delegates
here): flags every ``time.time()`` / ``time.perf_counter()`` in package
code outside the allowlisted host-only modules. The legacy
``# timing-ok: <reason>`` pragma still works (the framework maps it onto
this pass); new code should prefer ``# lint-ok(timing-hygiene): <reason>``.
Scope is the package only — ``scripts/`` are host-side drivers whose
wall clocks time subprocesses and I/O, not jitted dispatch.
"""

from __future__ import annotations

import re

from dib_tpu.analysis.core import Finding, LintPass, Module, register

_PATTERN = re.compile(r"\btime\.(?:time|perf_counter)\(\)")


@register
class TimingHygienePass(LintPass):
    id = "timing-hygiene"
    description = ("bare time.time()/perf_counter() in package code — "
                   "async dispatch makes the interval a lie")
    incident = ("PR 2: wall-clock deltas around jitted calls measured "
                "only the dispatch; the phantom speedups burned "
                "measurement rounds (docs/observability.md)")
    scope = "package"
    # Module-level exemptions, each with the reason it may read a wall
    # clock directly. Everything else times through PhaseTimer/trace.span
    # or carries a per-line pragma.
    allowlist = {
        "dib_tpu/utils/profiling.py":
            "the blocking-timer implementation itself",
        "dib_tpu/telemetry/trace.py": "the span implementation itself",
        "dib_tpu/telemetry/events.py":
            "event-envelope timestamps, not intervals",
        "dib_tpu/telemetry/xla_stats.py":
            "times host-side lower/compile, no dispatch",
        "dib_tpu/telemetry/hooks.py":
            "PhaseTimer feeder: hook-boundary adds after an explicit "
            "block_until_ready",
        "dib_tpu/train/hooks.py":
            "TimedHook measures host hooks, which fetch their device "
            "results internally",
        "dib_tpu/train/watchdog.py":
            "supervisor process: times subprocess beats, never "
            "dispatches jitted work",
        "dib_tpu/telemetry/live.py":
            "host-side stream follower/dashboard: staleness vs event "
            "wall-clock stamps, no jitted work",
        "dib_tpu/telemetry/registry.py":
            "host-side registry timestamps, no intervals",
        "dib_tpu/analysis/passes/timing.py":
            "this pass: its docstring, pattern, and messages spell the "
            "forbidden calls",
    }

    def check_module(self, module: Module) -> list[Finding]:
        findings: list[Finding] = []
        for lineno, line in enumerate(module.lines, 1):
            if _PATTERN.search(line):
                findings.append(self.finding(
                    module, lineno,
                    "bare wall-clock call: JAX dispatch is async, so "
                    "time.time()/perf_counter() around a jitted call "
                    "measures only the dispatch — use "
                    "utils.profiling.PhaseTimer/timed_blocked or "
                    "telemetry.trace.span (docs/observability.md)",
                ))
        return findings
