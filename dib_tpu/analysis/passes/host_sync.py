"""host-sync: implicit device→host coercions stall the dispatch pipeline.

The incident (PR 2, docs/observability.md "async-dispatch pitfall" and
the MFU work in BENCH_r05): a bare ``float(loss)`` / ``.item()`` /
``np.asarray(...)`` on a jitted call's result is a BLOCKING device fetch
— it parks the host until the whole dispatched program finishes, breaks
chunk-to-chunk pipelining, and (when it sneaks into a loop) turns an
async training loop into a synchronous one. The repo's idiom is ONE
explicit ``jax.device_get`` of a small dict per chunk boundary (see
``train/loop.py``'s boundary-row fetch), after the boundary's
``block_on`` has already paid for the sync.

This pass guards the chunk-loop modules (``target_modules``): inside
them, applying ``float()`` / ``int()`` / ``bool()`` / ``.item()`` /
``np.asarray()`` to a value that came from a locally-jitted call is
flagged. Fetching through ``jax.device_get`` first — or rebinding the
result at all — clears the taint, so the blocking-fetch idiom passes
clean. A deliberate coercion (e.g. a one-off pre-loop fetch) carries a
``# lint-ok(host-sync): <reason>`` pragma.
"""

from __future__ import annotations

import ast

from dib_tpu.analysis.core import (
    Finding,
    LintPass,
    Module,
    assigned_names,
    call_name,
    register,
    statements_in_order,
    walk_stmt_exprs,
)
from dib_tpu.analysis.jaxutil import jitted_callables, match_callable

_COERCIONS = {"float", "int", "bool"}
_ARRAY_COERCIONS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array"}


def _base_name(node: ast.expr) -> str | None:
    """The root Name of a Name/Attribute/Subscript chain."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


@register
class HostSyncPass(LintPass):
    id = "host-sync"
    description = ("implicit device→host coercions (float()/int()/bool()/"
                   ".item()/np.asarray) on jitted results in the "
                   "chunk-loop modules")
    incident = ("PR 2 / BENCH_r05: hidden blocking fetches serialized the "
                "chunk pipeline — the MFU work exists because the host "
                "kept parking on implicit syncs (docs/observability.md, "
                "async-dispatch pitfall)")
    # The modules whose inner loops are the product's hot path. Everything
    # else may fetch freely — drivers and hooks run between chunks. The
    # sched modules are included from day one: the scheduler's worker
    # pool runs MANY units' chunk loops concurrently, so a hidden
    # blocking fetch there serializes the whole pool, not one run. The
    # overlap/prefetch modules and the measurement trainer joined with the
    # raw-speed PR: an implicit sync in the overlap plumbing would
    # silently re-serialize exactly the boundary the overlap exists to
    # hide. The async serving modules joined with ISSUE 10: the serving
    # hot path handles thousands of requests/s on one event loop plus the
    # batcher threads, so an implicit device fetch there stalls EVERY
    # in-flight request, not one chunk.
    target_modules = (
        "dib_tpu/train/loop.py",
        "dib_tpu/train/measurement.py",
        "dib_tpu/train/overlap.py",
        "dib_tpu/train/prefetch.py",
        "dib_tpu/parallel/sweep.py",
        "dib_tpu/workloads/boolean.py",
        "dib_tpu/sched/runner.py",
        "dib_tpu/sched/pool.py",
        "dib_tpu/sched/scheduler.py",
        "dib_tpu/serve/engine.py",
        "dib_tpu/serve/batcher.py",
        "dib_tpu/serve/server.py",
        "dib_tpu/serve/pool.py",
        "dib_tpu/serve/zoo.py",
        # the streaming control plane joined with ISSUE 12: the online
        # loop IS a chunk loop (an implicit fetch serializes every
        # round), and the deployer restores/probes checkpoints while the
        # fleet serves — a hidden sync there stalls promotion under load
        "dib_tpu/stream/online.py",
        "dib_tpu/stream/deployer.py",
        # the integrity plane joined with ISSUE 14: the anomaly detector
        # runs INSIDE the chunk loop on every boundary (it must consume
        # only the row fetch the boundary already pays for — an implicit
        # sync there re-serializes training), and the digest/scrub layer
        # walks restored payloads (explicit device_get only)
        "dib_tpu/train/anomaly.py",
        "dib_tpu/train/scrub.py",
        "dib_tpu/train/checkpoint.py",
        # the study controller joined with ISSUE 15: it drives the
        # scheduler pool whose workers run MANY units' chunk loops —
        # the decision core must stay on the unit histories' saved
        # arrays, never on an implicit fetch that would serialize the
        # round it is trying to steer
        "dib_tpu/study/controller.py",
        # the fleet aggregator joined with ISSUE 16: `fleet tail` follows
        # MANY runs' planes from one poll loop — an implicit device fetch
        # (e.g. coercing a metrics payload that arrived as a jitted
        # result in-process) would stall the merge for every source at
        # once, exactly the cross-run serialization the sched pool entry
        # guards against
        "dib_tpu/telemetry/fleet.py",
        # the drift autopilot joined with ISSUE 19: its supervise loop
        # tails a LIVE trainer's stream and drives mini-studies through
        # the same worker pool — an implicit fetch in the loop (e.g.
        # coercing a harvested estimate that arrived as a jitted result
        # in-process) would park the supervisor mid-drift and stretch
        # the drift→apply window the SLO gates
        "dib_tpu/autopilot/loop.py",
    )

    def check_module(self, module: Module) -> list[Finding]:
        registry = jitted_callables(module)
        if not registry:
            return []
        findings: list[Finding] = []
        for fn in module.functions():
            findings.extend(self._check_scope(module, fn, registry))
        return findings

    def _check_scope(self, module, fn, registry) -> list[Finding]:
        findings: list[Finding] = []
        device: dict[str, int] = {}   # name -> line it became device-fresh
        for stmt in statements_in_order(fn):
            for call in (n for n in walk_stmt_exprs(stmt)
                         if isinstance(n, ast.Call)):
                name = call_name(call)
                coerced: ast.expr | None = None
                kind = None
                if name in _COERCIONS and len(call.args) == 1:
                    coerced, kind = call.args[0], f"{name}()"
                elif name in _ARRAY_COERCIONS and call.args:
                    coerced, kind = call.args[0], f"{name}()"
                elif (isinstance(call.func, ast.Attribute)
                        and call.func.attr == "item" and not call.args):
                    coerced, kind = call.func.value, ".item()"
                if coerced is None:
                    continue
                base = _base_name(coerced)
                if base is not None and base in device:
                    findings.append(self.finding(
                        module, call.lineno,
                        f"{kind} on `{base}` (device-fresh since line "
                        f"{device[base]}) is an implicit blocking "
                        "device→host fetch in a chunk-loop module — batch "
                        "it into the boundary's single `jax.device_get` "
                        "fetch (the blocking-fetch idiom, "
                        "docs/observability.md)",
                    ))
            assigned = assigned_names(stmt)
            if assigned:
                value = getattr(stmt, "value", None)
                value_jit = (match_callable(value, registry)
                             if isinstance(value, ast.Call) else None)
                for name in assigned:
                    if value_jit is not None:
                        device[name] = stmt.lineno
                    else:
                        # jax.device_get / any other rebind clears it
                        device.pop(name, None)
        return findings
