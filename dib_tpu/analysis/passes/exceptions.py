"""exception-hygiene: no silently-swallowed broad exceptions.

The incident (PR 4, docs/robustness.md): a robustness subsystem is only
as honest as its error handling — an ``except Exception: pass`` turns a
real fault into nothing (no re-raise, no error result, no telemetry
event), which is exactly how a recovery path rots until a drill or
production finds it.

Migrated from ``scripts/check_exception_hygiene.py`` (which now
delegates here), and widened from the package to the whole tree —
``scripts/`` drive the committed benchmarks and drills, where a
swallowed exception corrupts the measured history instead of a serving
path. Flags any handler that catches a BROAD type (bare ``except:``,
``Exception``, ``BaseException`` — alone or in a tuple) with a body that
does NOTHING (only ``pass``/``...``). Narrow handlers, re-raises,
logging, and error results all pass. The legacy ``# fault-ok: <reason>``
pragma still works; new code should prefer
``# lint-ok(exception-hygiene): <reason>``.
"""

from __future__ import annotations

import ast

from dib_tpu.analysis.core import Finding, LintPass, Module, register

_BROAD = {"Exception", "BaseException"}


def _broad_names(handler: ast.ExceptHandler) -> bool:
    """True when the handler catches Exception/BaseException or is bare."""
    node = handler.type
    if node is None:
        return True
    elts = node.elts if isinstance(node, ast.Tuple) else [node]
    for elt in elts:
        name = elt.id if isinstance(elt, ast.Name) else (
            elt.attr if isinstance(elt, ast.Attribute) else None)
        if name in _BROAD:
            return True
    return False


def _body_is_silent(handler: ast.ExceptHandler) -> bool:
    """True when the body does nothing: only pass / bare ellipsis."""
    for stmt in handler.body:
        if isinstance(stmt, ast.Pass):
            continue
        if (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Constant)
                and stmt.value.value is Ellipsis):
            continue
        return False
    return True


@register
class ExceptionHygienePass(LintPass):
    id = "exception-hygiene"
    description = ("broad exception handlers (bare/Exception/"
                   "BaseException) whose body does nothing")
    incident = ("PR 4: `except Exception: pass` hides exactly the faults "
                "the recovery paths exist for; the drills only prove "
                "paths that are allowed to fail loudly "
                "(docs/robustness.md)")

    def check_module(self, module: Module) -> list[Finding]:
        if module.tree is None:
            return []
        findings: list[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if _broad_names(node) and _body_is_silent(node):
                findings.append(self.finding(
                    module, node.lineno,
                    "silent broad exception handler: re-raise, return an "
                    "error result, or emit a telemetry event — or narrow "
                    "the type (docs/robustness.md)",
                ))
        return findings
