"""async-blocking: nothing reachable from a coroutine may block the loop.

The incident shape (docs/serving.md): ``serve/server.py`` runs the WHOLE
HTTP surface on one asyncio event loop — every connection is a
coroutine. One synchronous ``time.sleep``, one blocking socket read,
one ``subprocess.run``, one implicit device fetch executed ON the loop
stalls every in-flight request at once: the continuous batcher keeps
dispatching, but nothing can be parsed, queued, or answered until the
blocking call returns. At 1.6k req/s (BENCH_SERVE_ASYNC_CPU.json) a
10 ms block is sixteen requests' worth of added latency — and the bug
is invisible in single-request tests.

The rule, interprocedural (analysis/project.py): inside any ``async
def``, a call that is NOT awaited and either (a) matches a known
blocking primitive — ``time.sleep``, ``subprocess.run``/``check_*``,
synchronous socket/urllib connects, ``Future.result()``,
``jax.device_get`` / ``.block_until_ready()`` (an implicit device sync
parks the host exactly like a sleep), ``asyncio.run`` (nested loops
deadlock) — or (b) resolves to a project function whose summary says it
(transitively) makes such a call, is flagged with the chain named.
Additionally, a call that resolves to a project COROUTINE but is not
awaited is flagged (`never awaited` — the coroutine silently never
runs).

The blessed escapes are what the serving code actually uses: park the
blocking callable on an executor (``loop.run_in_executor(None, fn)`` /
``asyncio.to_thread(fn)`` — the callable is passed, not called, so
this pass never sees a call), or ``await asyncio.sleep`` instead of
``time.sleep``.
"""

from __future__ import annotations

import ast

from dib_tpu.analysis.core import (
    Finding,
    LintPass,
    Module,
    call_name,
    register,
    statements_in_order,
    walk_stmt_exprs,
)

#: Dotted call names that block the calling thread.
_BLOCKING_DOTTED = {
    "time.sleep": "time.sleep blocks the loop thread; await "
                  "asyncio.sleep instead",
    "subprocess.run": "subprocess.run blocks until the child exits; use "
                      "asyncio.create_subprocess_exec",
    "subprocess.call": "subprocess.call blocks until the child exits",
    "subprocess.check_call": "subprocess.check_call blocks until the "
                             "child exits",
    "subprocess.check_output": "subprocess.check_output blocks until the "
                               "child exits",
    "socket.create_connection": "a synchronous socket connect blocks the "
                                "loop; use loop.sock_connect / "
                                "asyncio.open_connection",
    "urllib.request.urlopen": "a synchronous HTTP fetch blocks the loop",
    "os.system": "os.system blocks until the shell exits",
    "asyncio.run": "asyncio.run inside a running loop raises (and a "
                   "fresh loop would block this one)",
    "jax.device_get": "an implicit device sync parks the loop thread "
                      "until the dispatched program finishes — every "
                      "in-flight request stalls behind it",
    "jax.block_until_ready": "an explicit device sync parks the loop "
                             "thread until the dispatched program "
                             "finishes",
}

#: Terminal attribute names (any receiver) that block.
_BLOCKING_ATTRS = {
    "block_until_ready": "an explicit device sync parks the loop thread",
    "result": "Future.result() blocks the loop (and deadlocks when the "
              "future completes on this same loop); await it instead",
}


def _blocking_primitive(call: ast.Call) -> str | None:
    """The reason string when a call is a known blocking primitive."""
    name = call_name(call)
    if name is not None and name in _BLOCKING_DOTTED:
        return _BLOCKING_DOTTED[name]
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        if attr in _BLOCKING_ATTRS:
            # `.result()` in the Future shapes: bare or with a timeout
            # (positional or keyword) — result(timeout) parks the loop
            # for up to the timeout, same stall. More arguments is some
            # other API's `.result`.
            if attr == "result" and len(call.args) > 1:
                return None
            return _BLOCKING_ATTRS[attr]
    return None


@register
class AsyncBlockingPass(LintPass):
    id = "async-blocking"
    description = ("blocking calls (sleep/subprocess/sync socket/"
                   "Future.result/implicit device sync) reachable from a "
                   "coroutine without an executor hop; project coroutines "
                   "called but never awaited")
    incident = ("serve/server.py's event loop serves every connection as "
                "a coroutine — ONE synchronous sleep/socket/device fetch "
                "on the loop stalls every in-flight request at once "
                "(invisible in single-request tests, catastrophic at the "
                "measured 1.6k req/s)")

    def check_module(self, module: Module) -> list[Finding]:
        return self.check_module_with_project(module, None)

    def check_module_with_project(self, module: Module,
                                  project) -> list[Finding]:
        if module.tree is None or "async def" not in module.source:
            return []
        summaries = (self._blocking_summaries(project)
                     if project is not None else {})
        findings: list[Finding] = []
        for fn in module.functions():
            if isinstance(fn, ast.AsyncFunctionDef):
                findings.extend(self._check_coroutine(
                    module, fn, project, summaries))
        return findings

    # ----------------------------------------------- blocking summaries
    def _blocking_summaries(self, project) -> dict[str, tuple[int, str]]:
        """``{qualname: (lineno, reason)}`` for every SYNC project
        function that (transitively) makes a blocking call — the shared
        call-graph fixpoint (Project.fixpoint), cached on the project."""
        def transfer(info, facts):
            if info.is_async:
                return None
            return self._first_blocking_call(
                project.modules[info.rel], info.node, project, facts)

        return project.fixpoint("_async_blocking_facts", transfer)

    def _first_blocking_call(self, module, fn, project, facts,
                             ) -> tuple[int, str] | None:
        for stmt in statements_in_order(fn):
            for call in (n for n in walk_stmt_exprs(stmt)
                         if isinstance(n, ast.Call)):
                reason = _blocking_primitive(call)
                if reason is not None:
                    return call.lineno, reason
                if project is None:
                    continue
                info = project.resolve_call(module, call, scope=fn)
                if info is not None and not info.is_async \
                        and info.qualname in facts:
                    # embed only the callee's NAME and LINE, never its
                    # reason string: a reason embedding another fact's
                    # reason grows without bound through recursion
                    # cycles (engine._dispatch calls itself) and the
                    # fixpoint would never converge
                    callee_line, _reason = facts[info.qualname]
                    return call.lineno, (
                        f"calls `{info.name}` → blocking at "
                        f"{info.rel}:{callee_line}")
        return None

    # ------------------------------------------------- coroutine checks
    def _check_coroutine(self, module, fn, project, summaries,
                         ) -> list[Finding]:
        findings: list[Finding] = []
        for stmt in statements_in_order(fn):
            for call in (n for n in walk_stmt_exprs(stmt)
                         if isinstance(n, ast.Call)):
                if isinstance(module.parent_of(call), ast.Await):
                    continue
                reason = _blocking_primitive(call)
                if reason is not None:
                    findings.append(self.finding(
                        module, call.lineno,
                        f"blocking call on the event loop in coroutine "
                        f"`{fn.name}`: {reason} (one blocked loop stalls "
                        "every in-flight request — run it in an executor "
                        "or use the async equivalent)",
                    ))
                    continue
                if project is None:
                    continue
                info = project.resolve_call(module, call, scope=fn)
                if info is None:
                    continue
                if info.is_async:
                    # only the unambiguous shape: a bare coroutine call
                    # as a statement (passing the coroutine object into
                    # create_task/gather — or binding it for a later
                    # await — is legitimate and common)
                    if isinstance(module.parent_of(call), ast.Expr):
                        findings.append(self.finding(
                            module, call.lineno,
                            f"coroutine `{info.name}` is called but its "
                            f"coroutine object is discarded in "
                            f"`{fn.name}` — it will never run; `await` "
                            "it (or wrap it in asyncio.create_task)",
                        ))
                    continue
                hit = summaries.get(info.qualname)
                if hit is not None:
                    line, reason = hit
                    findings.append(self.finding(
                        module, call.lineno,
                        f"`{info.name}` blocks the event loop (via its "
                        f"line {line}: {reason}) and is called from "
                        f"coroutine `{fn.name}` — park it on an executor "
                        "(loop.run_in_executor) or make the chain async",
                    ))
        return findings
