"""prng-reuse: one PRNG key, one consumer.

The incident class: reusing a key across two consuming calls silently
correlates the "random" draws — batches sampled identically to the noise,
val splits identical across replicas, sweeps whose members share
trajectories. Nothing crashes; the statistics are just wrong, which is
the worst way for a training run to fail (the reference codebase's own
key-handling was one of the bug classes PARITY.md had to characterize).

The rule: a key variable — one assigned from ``jax.random.PRNGKey`` /
``split`` / ``fold_in`` / ``wrap_key_data`` (including tuple-unpack from
``split``) or a parameter named like a key (``key``, ``rng``, ``k_*``) —
may be passed to at most ONE consuming call before being rebound through
``jax.random.split`` / ``fold_in``. Passing a key to ``split``/``fold_in``
derives fresh keys and is sanctioned; anything else (a ``jax.random.*``
sampler, a model ``init``/``apply``, a fit) consumes it. A second
consumption without an intervening rebind is flagged, as is a consumption
inside a loop whose body never rebinds the key (every iteration reuses
the same key — the classic copy-paste bug).

Interprocedural (analysis/project.py): a call resolved to a project
function consumes a key argument only when that function's summary says
the bound parameter is consumed (transitively) — so a helper that only
``split``\\s its key no longer burns the caller's one allowed
consumption, while a helper that samples with it counts exactly like a
direct ``jax.random.normal``. Unresolvable calls keep the conservative
rule (they consume).
"""

from __future__ import annotations

import ast
import re

from dib_tpu.analysis.core import (
    Finding,
    LintPass,
    Module,
    assigned_names,
    call_name,
    register,
    statements_in_order,
    walk_stmt_exprs,
)

#: jax.random calls that derive fresh keys or only inspect one — passing
#: a key to these never consumes its entropy.
_DERIVING = {"split", "fold_in", "wrap_key_data", "PRNGKey", "key", "clone",
             "key_data", "key_impl"}

#: Parameter names treated as keys on sight (locals are tracked by
#: provenance instead — anything assigned from a deriving call).
_KEY_PARAM = re.compile(r"^(key|rng|prng_key|k_[a-z0-9_]+)$")


def _is_deriving_call(call: ast.Call) -> bool:
    name = call_name(call)
    if name is None:
        return False
    parts = name.split(".")
    if len(parts) == 1:
        # bare `split(key)` via `from jax.random import split`; the other
        # deriving names are too generic to trust unqualified
        return parts[0] in ("split", "fold_in", "PRNGKey")
    return parts[-1] in _DERIVING and parts[0] in ("jax", "random", "jr")


def _is_key_producing(value: ast.expr) -> bool:
    return isinstance(value, ast.Call) and _is_deriving_call(value)


@register
class PrngReusePass(LintPass):
    id = "prng-reuse"
    description = ("a PRNG key passed to two consuming calls without an "
                   "intervening jax.random.split/fold_in rebind")
    incident = ("reused keys correlate 'independent' draws — batches "
                "sampled identically to the reparameterization noise, "
                "replicas sharing trajectories; wrong statistics, no "
                "crash")

    def check_module(self, module: Module) -> list[Finding]:
        return self.check_module_with_project(module, None)

    def check_module_with_project(self, module: Module,
                                  project) -> list[Finding]:
        findings: list[Finding] = []
        # Key-shaped PARAMETER names only mean "PRNG key" in modules that
        # actually touch jax.random — elsewhere `key` is a dict key
        # (telemetry/report.py's chunk_series) and tracking it would be
        # all noise. Locals are tracked by provenance regardless.
        params_are_keys = "jax.random" in module.source
        for fn in module.functions():
            findings.extend(
                self._check_scope(module, fn, params_are_keys, project))
        return findings

    def _consumes(self, module: Module, call: ast.Call, argname: str,
                  fn, project) -> bool:
        """Does this (non-deriving) call consume the key ``argname``?
        Project-resolved callees answer from their interprocedural
        summary; everything else conservatively consumes."""
        if project is None:
            return True
        return project.call_consumes_key(module, call, argname, scope=fn)

    def _check_scope(self, module: Module, fn,
                     params_are_keys: bool, project=None) -> list[Finding]:
        findings: list[Finding] = []
        stmts = statements_in_order(fn)
        # name -> line of the assignment that made it a key (or 0 = param)
        keys: dict[str, int] = {}
        if params_are_keys:
            for arg in (*fn.args.posonlyargs, *fn.args.args,
                        *fn.args.kwonlyargs):
                if _KEY_PARAM.match(arg.arg):
                    keys[arg.arg] = 0
        # name -> line of its one allowed consumption
        consumed: dict[str, int] = {}
        loop_rebinds = self._loop_rebinds(fn)
        for stmt in stmts:
            for call in (n for n in walk_stmt_exprs(stmt)
                         if isinstance(n, ast.Call)):
                deriving = _is_deriving_call(call)
                for arg in (*call.args,
                            *(kw.value for kw in call.keywords)):
                    if not (isinstance(arg, ast.Name) and arg.id in keys):
                        continue
                    if deriving:
                        continue  # split/fold_in derive, never consume
                    if not self._consumes(module, call, arg.id, fn,
                                          project):
                        continue  # resolved helper only derives/ignores it
                    prior = consumed.get(arg.id)
                    if prior is not None:
                        findings.append(self.finding(
                            module, arg.lineno,
                            f"key `{arg.id}` already consumed at line "
                            f"{prior} — split it first "
                            "(`k1, k2 = jax.random.split(...)`) so the "
                            "two consumers draw independent randomness",
                        ))
                        continue
                    consumed[arg.id] = arg.lineno
                    loop = self._stale_loop(module, stmt, arg.id,
                                            keys[arg.id], loop_rebinds)
                    if loop is not None:
                        findings.append(self.finding(
                            module, arg.lineno,
                            f"key `{arg.id}` (bound at line "
                            f"{keys[arg.id] or 'parameter'}) is consumed "
                            f"inside the loop at line {loop.lineno} but "
                            "never rebound per iteration — every "
                            "iteration reuses the same randomness; "
                            "`jax.random.split`/`fold_in` it inside the "
                            "loop",
                        ))
            assigned = assigned_names(stmt)
            for name in assigned:
                consumed.pop(name, None)
                if _is_key_producing(getattr(stmt, "value", None)):
                    keys[name] = stmt.lineno
                else:
                    keys.pop(name, None)
        return findings

    def _loop_rebinds(self, fn) -> dict[ast.stmt, set[str]]:
        """For each loop statement in the scope: the names its body (or
        iteration header) rebinds on every pass."""
        out: dict[ast.stmt, set[str]] = {}
        for stmt in statements_in_order(fn):
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                rebinds: set[str] = set()
                for inner in statements_in_order(stmt):
                    rebinds |= assigned_names(inner)
                rebinds |= assigned_names(stmt)  # for-target itself
                out[stmt] = rebinds
        return out

    def _stale_loop(self, module: Module, stmt: ast.stmt, name: str,
                    bound_line: int, loop_rebinds) -> ast.stmt | None:
        """The innermost enclosing loop that consumes ``name`` without a
        per-iteration rebind, when the key was bound OUTSIDE that loop."""
        for anc in module.ancestors(stmt):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return None
            rebinds = loop_rebinds.get(anc)
            if rebinds is None:
                continue
            if name in rebinds:
                return None
            if bound_line and anc.lineno <= bound_line <= (
                    getattr(anc, "end_lineno", 0) or 0):
                return None  # bound inside the loop after all
            return anc
        return None
