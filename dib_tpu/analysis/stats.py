"""Suppression-budget accounting: ``lint --stats`` vs ``LINT_BUDGET.json``.

A pragma is a debt note: a place the suite was told to look away, with
a reason. Debts are fine — uncounted debts rot. ``lint --stats`` counts
every suppression per pass across the tree (legacy ``timing-ok``/
``fault-ok`` spellings count under the pass they map to) and gates the
counts against the committed budget, ``telemetry check``-style (exit 1
on violation):

- a pass OVER its budget fails — new suppressions need the budget row
  raised in the same commit, which is what code review sees;
- a pass UNDER its budget fails too, unless the budget row carries a
  justification: un-justified slack means pragmas were removed without
  ratcheting the budget down, and un-ratcheted budgets are how the
  count silently creeps back up. The budget can therefore only SHRINK
  without paperwork; holding it above the current count requires a
  ``justifications`` row saying why the headroom exists.

``LINT_BUDGET.json``::

    {
      "version": 1,
      "budget": {"timing-hygiene": 33, ...},
      "justifications": {"<pass>": "why this row may exceed the count"}
    }
"""

from __future__ import annotations

import json
import os
from typing import Iterable

from dib_tpu.analysis import core
from dib_tpu.analysis.core import Module

BUDGET_VERSION = 1
BUDGET_FILENAME = "LINT_BUDGET.json"


def load_budget(root: str) -> dict | None:
    """The committed budget, or None when the repo has none (counting
    still works; gating is skipped). Raises ValueError on a malformed
    budget — a broken committed gate must fail loudly, not skip."""
    path = os.path.join(root, BUDGET_FILENAME)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        budget = json.load(f)
    problems = validate_budget(budget)
    if problems:
        raise ValueError(f"{BUDGET_FILENAME}: " + "; ".join(problems))
    return budget


def validate_budget(budget) -> list[str]:
    problems: list[str] = []
    if not isinstance(budget, dict):
        return ["must be a JSON object"]
    if budget.get("version") != BUDGET_VERSION:
        problems.append(f"version must be {BUDGET_VERSION}")
    rows = budget.get("budget")
    if not isinstance(rows, dict):
        problems.append("'budget' must map pass ids to integer counts")
        rows = {}
    for pass_id, count in rows.items():
        if not isinstance(count, int) or count < 0:
            problems.append(f"budget[{pass_id!r}] must be a non-negative "
                            "integer")
        if pass_id not in core.REGISTRY \
                and pass_id != core.PRAGMA_PASS_ID:
            problems.append(f"budget names unknown pass {pass_id!r}")
    just = budget.get("justifications", {})
    if not isinstance(just, dict) or not all(
            isinstance(v, str) and v.strip() for v in just.values()):
        problems.append("'justifications' must map pass ids to non-empty "
                        "reasons")
    return problems


def suppression_stats(modules: Iterable[Module]) -> dict[str, int]:
    """Per-pass pragma counts over the parsed tree (sorted)."""
    return core.pragma_counts(modules)


def budget_violations(stats: dict[str, int], budget: dict) -> list[str]:
    """The gate: over-budget passes, and un-justified slack (the
    shrink-only ratchet — see the module docstring)."""
    rows: dict[str, int] = budget.get("budget", {})
    just: dict[str, str] = budget.get("justifications", {})
    problems: list[str] = []
    for pass_id, count in sorted(stats.items()):
        allowed = rows.get(pass_id, 0)
        if count > allowed:
            problems.append(
                f"{pass_id}: {count} suppression(s), budget {allowed} — "
                "either remove the new pragma or raise the budget row "
                "(and let review see it)")
    for pass_id, allowed in sorted(rows.items()):
        count = stats.get(pass_id, 0)
        if allowed > count and pass_id not in just:
            problems.append(
                f"{pass_id}: budget {allowed} exceeds the actual count "
                f"{count} with no justification row — ratchet the budget "
                "down to the count (the budget only shrinks for free)")
    return problems


def stats_report(stats: dict[str, int], budget: dict | None,
                 violations: list[str]) -> dict:
    """The machine-readable ``--stats --json`` payload."""
    return {
        "version": BUDGET_VERSION,
        "suppressions": stats,
        "total": sum(stats.values()),
        "budget": None if budget is None else budget.get("budget", {}),
        "violations": violations,
    }


def format_stats(stats: dict[str, int], budget: dict | None,
                 violations: list[str]) -> str:
    lines = ["suppressions per pass (lint --stats):"]
    rows = budget.get("budget", {}) if budget else {}
    for pass_id in sorted(set(stats) | set(rows)):
        count = stats.get(pass_id, 0)
        allowed = rows.get(pass_id)
        budget_txt = f" / budget {allowed}" if allowed is not None else ""
        lines.append(f"  {pass_id}: {count}{budget_txt}")
    lines.append(f"  total: {sum(stats.values())}")
    if budget is None:
        lines.append(f"no {BUDGET_FILENAME} committed — counts reported, "
                     "nothing gated")
    for problem in violations:
        lines.append(f"BUDGET VIOLATION: {problem}")
    if budget is not None and not violations:
        lines.append("suppression budget: ok")
    return "\n".join(lines)
