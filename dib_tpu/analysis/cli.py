"""``python -m dib_tpu lint`` — the one CLI over every pass.

Exit codes follow the repo's gate convention (``telemetry check``,
``compare``): 0 clean, 1 findings, 2 bad usage. ``--json`` emits a
stable machine-readable report (the shape tests/test_lint/test_cli.py
pins); the default output is one ``path:line: [pass] message`` per
finding, clickable in a terminal.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Sequence

from dib_tpu.analysis import core

JSON_VERSION = 1


def _resolve_paths(paths: Sequence[str], root: str):
    """Explicit CLI paths -> (abs, repo-relative) file pairs."""
    pairs: list[tuple[str, str]] = []
    for p in paths:
        ap = os.path.abspath(p)
        if not os.path.exists(ap):
            raise FileNotFoundError(p)
        if os.path.isdir(ap):
            rel_root = os.path.relpath(ap, root).replace(os.sep, "/")
            pairs.extend(core.iter_source_files(root, roots=(rel_root,)))
        else:
            pairs.append((ap, os.path.relpath(ap, root).replace(os.sep, "/")))
    return pairs


def lint_main(argv: Sequence[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="dib_tpu lint",
        description="JAX-correctness static analysis over dib_tpu/ and "
                    "scripts/ (docs/static-analysis.md). Exit 0 clean, "
                    "1 findings, 2 bad usage.",
    )
    parser.add_argument("paths", nargs="*",
                        help="Files or directories to lint (default: the "
                             "whole tree — dib_tpu/ and scripts/).")
    parser.add_argument("--select", default=None,
                        help="Comma-separated pass ids to run (default: "
                             "all). Pragma-grammar findings always "
                             "report.")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="Machine-readable report on stdout.")
    parser.add_argument("--list", action="store_true", dest="list_passes",
                        help="Print the pass catalog and exit 0.")
    parser.add_argument("--root", default=core.REPO,
                        help=argparse.SUPPRESS)  # tests point at fixtures
    try:
        args = parser.parse_args(list(argv) if argv is not None else None)
    except SystemExit as exc:
        # argparse exits 2 on usage errors already; normalize --help to 0
        return int(exc.code or 0)

    passes = core.all_passes()
    if args.list_passes:
        for lint in passes:
            print(f"{lint.id}: {lint.description}")
            print(f"    prevents: {lint.incident}")
        return 0

    select = None
    if args.select is not None:
        select = [s.strip() for s in args.select.split(",") if s.strip()]
        if not select:
            print("dib_tpu lint: --select needs at least one pass id",
                  file=sys.stderr)
            return 2

    files = None
    if args.paths:
        try:
            files = _resolve_paths(args.paths, args.root)
        except FileNotFoundError as exc:
            print(f"dib_tpu lint: no such path: {exc}", file=sys.stderr)
            return 2
    try:
        findings = core.run_passes(root=args.root, select=select,
                                   files=files)
    except KeyError as exc:
        print(f"dib_tpu lint: {exc.args[0]}", file=sys.stderr)
        return 2

    if args.as_json:
        selected = (passes if select is None
                    else [core.get_pass(s) for s in sorted(set(select))])
        print(json.dumps({
            "version": JSON_VERSION,
            "passes": [
                {"id": p.id, "description": p.description,
                 "incident": p.incident, "scope": p.scope}
                for p in selected
            ],
            "findings": [
                {"pass": f.pass_id, "path": f.path, "line": f.line,
                 "message": f.message}
                for f in findings
            ],
            "summary": {"findings": len(findings)},
        }, indent=1, sort_keys=True))
    else:
        for f in findings:
            print(f.format())
        n = len(findings)
        scope_desc = ("selected passes" if select is not None
                      else f"{len(passes)} passes")
        where = "given paths" if files is not None else "dib_tpu/ + scripts/"
        if n:
            print(f"\ndib-lint: {n} finding(s) from {scope_desc} over "
                  f"{where}. Suppress a reviewed exception with "
                  "`# lint-ok(<pass>): <reason>` (docs/static-analysis.md).")
        else:
            print(f"dib-lint: ok ({scope_desc} over {where})")
    return 1 if findings else 0
