"""``python -m dib_tpu lint`` — the one CLI over every pass.

Exit codes follow the repo's gate convention (``telemetry check``,
``compare``): 0 clean, 1 findings (or a suppression-budget violation
under ``--stats``), 2 bad usage. Output modes:

- default: one ``path:line: [pass] message`` per finding, clickable;
- ``--json``: the stable machine-readable report
  (tests/test_lint/test_cli.py pins the shape);
- ``--sarif``: SARIF 2.1.0 for code-scanning consumers
  (tests/test_lint/test_tooling.py validates the required properties);
- ``--stats``: the suppression-budget report gated against the
  committed ``LINT_BUDGET.json`` (docs/static-analysis.md).

``--changed`` replays the content-hash cache under ``.dib_lint_cache/``
and re-analyzes only dirty files plus their reverse-dependency closure
— bit-identical findings to a cold run (pinned by test), one cheap
parse pass over everything else.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Sequence

from dib_tpu.analysis import core

JSON_VERSION = 1


def _resolve_paths(paths: Sequence[str], root: str):
    """Explicit CLI paths -> (abs, repo-relative) file pairs."""
    pairs: list[tuple[str, str]] = []
    for p in paths:
        ap = os.path.abspath(p)
        if not os.path.exists(ap):
            raise FileNotFoundError(p)
        if os.path.isdir(ap):
            rel_root = os.path.relpath(ap, root).replace(os.sep, "/")
            pairs.extend(core.iter_source_files(root, roots=(rel_root,)))
        else:
            pairs.append((ap, os.path.relpath(ap, root).replace(os.sep, "/")))
    return pairs


def _build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="dib_tpu lint",
        description="JAX-correctness static analysis over dib_tpu/ and "
                    "scripts/ (docs/static-analysis.md). Exit 0 clean, "
                    "1 findings, 2 bad usage.",
    )
    parser.add_argument("paths", nargs="*",
                        help="Files or directories to lint (default: the "
                             "whole tree — dib_tpu/ and scripts/).")
    parser.add_argument("--select", default=None,
                        help="Comma-separated pass ids to run (default: "
                             "all). Pragma-grammar findings always "
                             "report.")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="Machine-readable report on stdout.")
    parser.add_argument("--sarif", action="store_true",
                        help="SARIF 2.1.0 report on stdout (code-scanning "
                             "consumers).")
    parser.add_argument("--changed", action="store_true",
                        help="Incremental full-tree run: re-analyze only "
                             "files whose content hash changed since the "
                             "last run, plus their reverse-dependency "
                             "closure (.dib_lint_cache/). Findings are "
                             "bit-identical to a cold run.")
    parser.add_argument("--no-cache", action="store_true",
                        help="Do not read or write .dib_lint_cache/.")
    parser.add_argument("--stats", action="store_true",
                        help="Suppression-budget report: per-pass pragma "
                             "counts gated against LINT_BUDGET.json "
                             "(exit 1 on violation).")
    parser.add_argument("--list", action="store_true", dest="list_passes",
                        help="Print the pass catalog and exit 0.")
    parser.add_argument("--root", default=core.REPO,
                        help=argparse.SUPPRESS)  # tests point at fixtures
    return parser


def lint_main(argv: Sequence[str] | None = None) -> int:
    parser = _build_parser()
    try:
        args = parser.parse_args(list(argv) if argv is not None else None)
    except SystemExit as exc:
        # argparse exits 2 on usage errors already; normalize --help to 0
        return int(exc.code or 0)

    passes = core.all_passes()
    if args.list_passes:
        for lint in passes:
            print(f"{lint.id}: {lint.description}")
            print(f"    prevents: {lint.incident}")
        return 0

    def usage_error(message: str) -> int:
        print(f"dib_tpu lint: {message}", file=sys.stderr)
        return 2

    if args.as_json and args.sarif:
        return usage_error("--json and --sarif are exclusive output modes")
    if args.stats and (args.sarif or args.changed or args.paths
                       or args.select):
        return usage_error("--stats is its own mode (combine only with "
                           "--json)")
    if args.changed and args.paths:
        return usage_error("--changed is a full-tree mode; drop the "
                           "explicit paths")
    if args.changed and args.select:
        return usage_error("--changed caches full-pass results only; "
                           "drop --select")

    select = None
    if args.select is not None:
        select = [s.strip() for s in args.select.split(",") if s.strip()]
        if not select:
            return usage_error("--select needs at least one pass id")

    if args.stats:
        return _stats_main(args)

    from dib_tpu.analysis import cache as cache_mod

    analyzed = cached = None
    if args.paths:
        try:
            files = _resolve_paths(args.paths, args.root)
        except FileNotFoundError as exc:
            return usage_error(f"no such path: {exc}")
        try:
            findings = core.run_passes(root=args.root, select=select,
                                       files=files)
        except KeyError as exc:
            return usage_error(str(exc.args[0]))
    else:
        try:
            result = cache_mod.run_tree(
                root=args.root, select=select, changed=args.changed,
                write_cache=False if args.no_cache else None,
                read_cache=not args.no_cache)
        except KeyError as exc:
            return usage_error(str(exc.args[0]))
        findings = result.findings
        analyzed, cached = result.analyzed_count, len(result.cached)

    if args.sarif:
        from dib_tpu.analysis.sarif import sarif_report

        selected = (passes if select is None
                    else [core.get_pass(s) for s in sorted(set(select))])
        print(json.dumps(sarif_report(findings, selected), indent=1,
                         sort_keys=True))
        return 1 if findings else 0

    if args.as_json:
        selected = (passes if select is None
                    else [core.get_pass(s) for s in sorted(set(select))])
        summary: dict = {"findings": len(findings)}
        if analyzed is not None:
            summary["analyzed_files"] = analyzed
            summary["cached_files"] = cached
        print(json.dumps({
            "version": JSON_VERSION,
            "passes": [
                {"id": p.id, "description": p.description,
                 "incident": p.incident, "scope": p.scope}
                for p in selected
            ],
            "findings": [
                {"pass": f.pass_id, "path": f.path, "line": f.line,
                 "message": f.message}
                for f in findings
            ],
            "summary": summary,
        }, indent=1, sort_keys=True))
    else:
        for f in findings:
            print(f.format())
        n = len(findings)
        scope_desc = ("selected passes" if select is not None
                      else f"{len(passes)} passes")
        where = ("given paths" if args.paths else "dib_tpu/ + scripts/")
        if analyzed is not None and args.changed:
            where += (f" ({analyzed} analyzed, {cached} replayed from "
                      "cache)")
        if n:
            print(f"\ndib-lint: {n} finding(s) from {scope_desc} over "
                  f"{where}. Suppress a reviewed exception with "
                  "`# lint-ok(<pass>): <reason>` (docs/static-analysis.md).")
        else:
            print(f"dib-lint: ok ({scope_desc} over {where})")
    return 1 if findings else 0


def _stats_main(args) -> int:
    from dib_tpu.analysis import stats as stats_mod

    modules = core.load_tree(args.root)
    counts = stats_mod.suppression_stats(modules.values())
    try:
        budget = stats_mod.load_budget(args.root)
    except ValueError as exc:
        print(f"dib_tpu lint: {exc}", file=sys.stderr)
        return 2
    violations = ([] if budget is None
                  else stats_mod.budget_violations(counts, budget))
    if args.as_json:
        print(json.dumps(stats_mod.stats_report(counts, budget, violations),
                         indent=1, sort_keys=True))
    else:
        print(stats_mod.format_stats(counts, budget, violations))
    return 1 if violations else 0
