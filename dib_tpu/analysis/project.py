"""Interprocedural dataflow engine: one parse pass, project-wide facts.

PR 7's passes stop at function boundaries — ``helper(state)`` hides a
donation from ``donation-safety`` exactly the way the PR 4 incident hid
from review. This module is the second layer: a project-wide symbol
table built in the same single parse pass the framework already does
(module → class → function defs, import resolution inside the lint
roots), call-graph edges with bound/unbound-method argument mapping
(the ``donated_args`` machinery, generalized), and fixpoint taint
propagation so facts like "donated tree", "consumed PRNG key",
"returns an un-copied device buffer", "blocks the calling thread", and
"returns a live OS resource" flow THROUGH helper-function boundaries
instead of stopping at them.

What crosses a function boundary (docs/static-analysis.md spells the
same contract for users):

- **bare-name calls** to functions defined in the same module or
  imported by name (``from dib_tpu.train.overlap import snapshot_params``),
  re-export chains followed through package ``__init__`` modules;
- **module-attribute calls** through an imported module alias
  (``overlap.snapshot_params(...)``);
- **``self.method(...)``** calls, resolved in the enclosing class;
- **bound-instance calls** on locals with a locally decidable type
  (``trainer = DIBTrainer(...); trainer.fit(...)``) — a name assigned
  from exactly one project-class constructor and never rebound.

What deliberately does NOT cross: dynamic dispatch (``for hook in
hooks: hook(...)``), attributes of attributes (``self.zoo.resolve``),
inherited methods, and anything a conditional rebinds — an
interprocedural lint must stay decidable, so the unresolvable stays
with the intraprocedural rules (conservative for PRNG consumption,
silent for donation).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable

from dib_tpu.analysis.core import (
    Module,
    assigned_names,
    call_name,
    dotted_name,
    statements_in_order,
    walk_stmt_exprs,
)
from dib_tpu.analysis.jaxutil import JittedFn, bind_call_args, jitted_callables


@dataclasses.dataclass(frozen=True)
class FunctionInfo:
    """One project function/method, addressable across modules."""

    rel: str                      # owning module, repo-relative
    name: str                     # bare name
    qualname: str                 # "<rel>::<Class.>name"
    cls: str | None               # enclosing class name, if a method
    params: tuple[str, ...]       # positional-or-keyword params in order
    is_async: bool
    lineno: int
    node: ast.FunctionDef | ast.AsyncFunctionDef

    @property
    def is_method(self) -> bool:
        return self.cls is not None


def _module_name(rel: str) -> str | None:
    """Dotted import name for a repo-relative path (``dib_tpu/train/
    overlap.py`` → ``dib_tpu.train.overlap``; package ``__init__`` maps to
    the package itself)."""
    if not rel.endswith(".py"):
        return None
    parts = rel[:-3].split("/")
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) if parts else None


class Project:
    """The project-wide symbol table + call-graph resolution + summaries.

    Built once per lint run from the already-parsed :class:`Module`\\s;
    summaries are computed lazily (a run selecting only intraprocedural
    passes never pays for the fixpoints) and cached.
    """

    def __init__(self, modules: Iterable[Module]):
        self.modules: dict[str, Module] = {m.rel: m for m in modules}
        # dotted module name -> rel ("dib_tpu.train.overlap" -> ".../overlap.py")
        self._by_name: dict[str, str] = {}
        # bare script name -> rel (scripts import each other unqualified)
        self._script_names: dict[str, str] = {}
        for rel in self.modules:
            name = _module_name(rel)
            if name is not None:
                self._by_name[name] = rel
                if rel.startswith("scripts/") and "." not in name.partition(
                        "scripts.")[2]:
                    self._script_names[rel[len("scripts/"):-3]] = rel
        # per-module tables (built eagerly: one cheap AST walk per module)
        self._functions: dict[str, dict[str, FunctionInfo]] = {}
        self._classes: dict[str, dict[str, ast.ClassDef]] = {}
        self._methods: dict[str, dict[str, dict[str, FunctionInfo]]] = {}
        self._imports: dict[str, dict[str, tuple[str, str | None]]] = {}
        # dep edges with no name binding (`import a.b` binds `a`, but the
        # file's analysis still depends on a/b.py's content)
        self._extra_deps: dict[str, set[str]] = {}
        for rel, module in self.modules.items():
            self._index_module(rel, module)
        self.module_deps: dict[str, set[str]] = {
            rel: ({target for target, _sym in self._imports[rel].values()}
                  | self._extra_deps.get(rel, set()))
            for rel in self.modules
        }
        self.reverse_deps: dict[str, set[str]] = {r: set() for r in self.modules}
        for rel, deps in self.module_deps.items():
            for dep in deps:
                self.reverse_deps.setdefault(dep, set()).add(rel)
        # caches (summaries land lazily via :meth:`fixpoint`)
        self._jitted: dict[str, dict[str, JittedFn]] = {}
        self._instance_types: dict[str, dict[str, tuple[str, str]]] = {}

    # ------------------------------------------------------------ indexing
    def _index_module(self, rel: str, module: Module) -> None:
        funcs: dict[str, FunctionInfo] = {}
        classes: dict[str, ast.ClassDef] = {}
        methods: dict[str, dict[str, FunctionInfo]] = {}
        imports: dict[str, tuple[str, str | None]] = {}
        self._functions[rel] = funcs
        self._classes[rel] = classes
        self._methods[rel] = methods
        self._imports[rel] = imports
        if module.tree is None:
            return
        for node in module.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                funcs[node.name] = self._info(rel, node, cls=None)
            elif isinstance(node, ast.ClassDef):
                classes[node.name] = node
                methods[node.name] = {
                    item.name: self._info(rel, item, cls=node.name)
                    for item in node.body
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))
                }
        # imports anywhere in the file — this repo imports inside functions
        # heavily (lazy jax), and a linter's name resolution does not need
        # scope sensitivity to be right about which module a name means
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    target = self._resolve_module(node, alias.name, rel)
                    if alias.asname:
                        if target is not None:
                            imports[alias.asname] = (target, None)
                        continue
                    # Python binds the ROOT package name: `import a.b`
                    # puts `a` (not a.b) in the namespace — resolve the
                    # bound name against the root, and keep the dep edge
                    # to the actually-imported submodule
                    root_name = alias.name.split(".")[0]
                    root_target = (target if root_name == alias.name
                                   else self._resolve_module(
                                       node, root_name, rel))
                    if root_target is not None:
                        imports[root_name] = (root_target, None)
                    if target is not None and target != root_target:
                        self._extra_deps.setdefault(rel, set()).add(target)
            elif isinstance(node, ast.ImportFrom):
                base = self._import_from_base(node, rel)
                if base is None:
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    # `from pkg import sub` may name a submodule rather
                    # than a symbol — prefer the submodule when it exists
                    sub = self._by_name.get(
                        f"{_module_name(base)}.{alias.name}"
                        if _module_name(base) else "")
                    if sub is not None:
                        imports[alias.asname or alias.name] = (sub, None)
                    else:
                        imports[alias.asname or alias.name] = (
                            base, alias.name)

    def _info(self, rel: str, node, cls: str | None) -> FunctionInfo:
        args = node.args
        params = tuple(a.arg for a in (*args.posonlyargs, *args.args))
        qual = f"{rel}::{cls + '.' if cls else ''}{node.name}"
        return FunctionInfo(
            rel=rel, name=node.name, qualname=qual, cls=cls, params=params,
            is_async=isinstance(node, ast.AsyncFunctionDef),
            lineno=node.lineno, node=node,
        )

    def _resolve_module(self, node: ast.Import, dotted: str,
                        rel: str) -> str | None:
        if dotted in self._by_name:
            return self._by_name[dotted]
        return self._script_names.get(dotted) \
            if rel.startswith("scripts/") else None

    def _import_from_base(self, node: ast.ImportFrom,
                          rel: str) -> str | None:
        """The rel of the module a ``from X import ...`` reads from."""
        if node.level == 0:
            if node.module is None:
                return None
            if node.module in self._by_name:
                return self._by_name[node.module]
            if rel.startswith("scripts/"):
                return self._script_names.get(node.module)
            return None
        # relative import: walk up from the importing module's package.
        # The strip is unconditional — a plain module drops its own file
        # name, a package __init__ drops the "__init__" segment: both
        # land on the containing package (keeping "__init__" would build
        # lookups like "pkg.__init__.x" that match nothing, silently
        # dropping every fact and dep edge of a package's re-exports)
        parts = rel[:-3].split("/")[:-1]
        parts = parts[: len(parts) - (node.level - 1)]
        if node.module:
            parts = parts + node.module.split(".")
        return self._by_name.get(".".join(parts))

    # ---------------------------------------------------------- resolution
    def jitted(self, rel: str) -> dict[str, JittedFn]:
        if rel not in self._jitted:
            module = self.modules.get(rel)
            self._jitted[rel] = (jitted_callables(module)
                                 if module is not None else {})
        return self._jitted[rel]

    def function(self, rel: str, name: str) -> FunctionInfo | None:
        return self._functions.get(rel, {}).get(name)

    def method(self, rel: str, cls: str, name: str) -> FunctionInfo | None:
        return self._methods.get(rel, {}).get(cls, {}).get(name)

    def resolve_symbol(self, rel: str, name: str, _depth: int = 0):
        """A top-level name in ``rel`` → ``("func", FunctionInfo)``,
        ``("class", rel, ClassDef)``, or None — following re-export
        chains through package ``__init__`` modules (bounded)."""
        if _depth > 8:
            return None
        info = self.function(rel, name)
        if info is not None:
            return ("func", info)
        cls = self._classes.get(rel, {}).get(name)
        if cls is not None:
            return ("class", rel, cls)
        imported = self._imports.get(rel, {}).get(name)
        if imported is None:
            return None
        target, symbol = imported
        if symbol is None:
            return None                   # a module alias is not a callable
        return self.resolve_symbol(target, symbol, _depth + 1)

    def instance_types(self, module: Module, fn) -> dict[str, tuple[str, str]]:
        """Locals of ``fn`` with a decidable project-class type: assigned
        from exactly one ``Cls(...)`` constructor (Cls a project class
        visible in the module) and never reassigned anything else.
        Returns ``{name: (rel, class name)}``."""
        key = f"{module.rel}:{getattr(fn, 'lineno', 0)}"
        cached = self._instance_types.get(key)
        if cached is not None:
            return cached
        counts: dict[str, int] = {}
        typed: dict[str, tuple[str, str]] = {}
        for stmt in statements_in_order(fn):
            for name in assigned_names(stmt):
                counts[name] = counts.get(name, 0) + 1
            value = getattr(stmt, "value", None)
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(value, ast.Call)
                    and isinstance(value.func, ast.Name)):
                resolved = self.resolve_symbol(module.rel, value.func.id)
                if resolved is not None and resolved[0] == "class":
                    typed[stmt.targets[0].id] = (resolved[1], resolved[2].name)
        out = {n: t for n, t in typed.items() if counts.get(n, 0) == 1}
        self._instance_types[key] = out
        return out

    def resolve_call(self, module: Module, call: ast.Call,
                     scope=None) -> FunctionInfo | None:
        """The project function a call site resolves to, or None.

        ``scope`` is the enclosing function node (for bound-instance
        locals); the enclosing class for ``self.m(...)`` comes from the
        module's parent links.
        """
        func = call.func
        if isinstance(func, ast.Name):
            resolved = self.resolve_symbol(module.rel, func.id)
            return resolved[1] if resolved and resolved[0] == "func" else None
        if not isinstance(func, ast.Attribute):
            return None
        base = func.value
        if isinstance(base, ast.Name):
            if base.id == "self":
                cls = module.enclosing_class(call)
                if cls is not None:
                    return self.method(module.rel, cls.name, func.attr)
                return None
            imported = self._imports.get(module.rel, {}).get(base.id)
            if imported is not None and imported[1] is None:
                return self.function(imported[0], func.attr)
            if scope is not None:
                typed = self.instance_types(module, scope).get(base.id)
                if typed is not None:
                    return self.method(typed[0], typed[1], func.attr)
        return None

    def all_functions(self):
        for rel in sorted(self._functions):
            yield from self._functions[rel].values()
            for cls in sorted(self._methods.get(rel, {})):
                yield from self._methods[rel][cls].values()

    def fixpoint(self, cache_attr: str, transfer) -> dict:
        """Generic MONOTONE call-graph fixpoint, cached on the project.

        ``transfer(info, facts) -> fact`` recomputes one function's fact
        from the current facts map; a falsy fact is "nothing" and is
        never stored (absent ≡ empty). Facts must only grow under
        iteration — every summary here does (donation/consumption/
        blocking/resource sets), which is what guarantees termination.
        All four pass summaries share this loop so the next summary is
        one transfer function, not a copied driver.
        """
        cached = getattr(self, cache_attr, None)
        if cached is not None:
            return cached
        facts: dict = {}
        changed = True
        while changed:
            changed = False
            for info in self.all_functions():
                fact = transfer(info, facts)
                if fact and fact != facts.get(info.qualname):
                    facts[info.qualname] = fact
                    changed = True
        setattr(self, cache_attr, facts)
        return facts

    # ------------------------------------------------- donation summaries
    def donation_summaries(self) -> dict[str, dict[str, str]]:
        """``{qualname: {param: via-chain}}`` for every project function
        that passes one of ITS OWN parameters (before any rebind) into a
        call that donates it — a jitted ``donate_argnames`` callee, or
        (transitively) another summarized function. The caller's
        parameter is dead after such a call exactly as if the caller were
        jitted with the donation itself."""
        facts = self.fixpoint("_donation_facts", self._donation_fact)
        return {q: fact["donated"] for q, fact in facts.items()
                if fact.get("donated")}

    def fresh_returners(self) -> set[str]:
        """Qualnames of functions whose return value is (or contains) the
        un-copied result of a jitted call — the device buffers the PR 4
        async-save incident raced. A host copy (``jax.device_get`` /
        ``np.array``) inside the function clears it."""
        facts = self.fixpoint("_donation_facts", self._donation_fact)
        return {q for q, fact in facts.items() if fact.get("fresh")}

    def _donation_fact(self, info: FunctionInfo, facts) -> dict:
        """One combined donation fact: ``{"donated": {param: chain},
        "fresh": bool}`` — the two taints share one statement walk."""
        donated, returns_fresh = self._donation_transfer(info, facts)
        fact: dict = {}
        if donated:
            fact["donated"] = donated
        if returns_fresh:
            fact["fresh"] = True
        return fact

    def _donation_target(self, module: Module, call: ast.Call, scope,
                         facts) -> tuple[tuple[str, ...], frozenset | dict,
                                         bool, str] | None:
        """(params, donated, is_method, name) for a call that donates —
        via local jit facts or a project summary."""
        local = self.jitted(module.rel)
        func = call.func
        jit = None
        if isinstance(func, ast.Name):
            jit = local.get(func.id)
        elif isinstance(func, ast.Attribute):
            jit = local.get(func.attr)
        if jit is not None and jit.donated:
            return jit.params, jit.donated, jit.is_method, jit.name
        info = self.resolve_call(module, call, scope=scope)
        if info is not None:
            target_jit = self.jitted(info.rel).get(info.name)
            if (target_jit is not None and target_jit.donated
                    and target_jit.lineno == info.lineno):
                return (target_jit.params, target_jit.donated,
                        target_jit.is_method, target_jit.name)
            summary = facts.get(info.qualname, {}).get("donated")
            if summary:
                return info.params, summary, info.is_method, info.name
        return None

    def _is_jitted_call(self, module: Module, call: ast.Call, scope) -> bool:
        local = self.jitted(module.rel)
        func = call.func
        if isinstance(func, ast.Name) and func.id in local:
            return True
        if isinstance(func, ast.Attribute) and func.attr in local:
            return True
        info = self.resolve_call(module, call, scope=scope)
        return (info is not None
                and info.name in self.jitted(info.rel)
                and self.jitted(info.rel)[info.name].lineno == info.lineno)

    def _donation_transfer(self, info: FunctionInfo, facts,
                           ) -> tuple[dict[str, str], bool]:
        module = self.modules[info.rel]
        donated: dict[str, str] = {}
        rebound: set[str] = set()
        fresh_names: set[str] = set()
        returns_fresh = False
        for stmt in statements_in_order(info.node):
            for call in (n for n in walk_stmt_exprs(stmt)
                         if isinstance(n, ast.Call)):
                target = self._donation_target(module, call, info.node, facts)
                if target is None:
                    continue
                params, tdonated, is_method, tname = target
                for param, arg in bind_call_args(
                        call, params, is_method).items():
                    if param in tdonated and isinstance(arg, ast.Name) \
                            and arg.id in info.params \
                            and arg.id not in rebound \
                            and arg.id not in donated:
                        chain = (f"{tname} → {tdonated[param]}"
                                 if isinstance(tdonated, dict) else tname)
                        # cap the chain: through a recursion cycle the
                        # embedded callee chain would otherwise grow on
                        # every fixpoint sweep and never converge — the
                        # first four hops identify the path, "…" says
                        # there is more
                        hops = chain.split(" → ")
                        if len(hops) > 4:
                            chain = " → ".join(hops[:4]) + " → …"
                        donated[arg.id] = chain
            value = getattr(stmt, "value", None)
            assigned = assigned_names(stmt)
            if assigned and isinstance(value, ast.Call):
                if self._is_jitted_call(module, value, info.node):
                    fresh_names.update(assigned)
                else:
                    resolved = self.resolve_call(module, value,
                                                 scope=info.node)
                    if resolved is not None and facts.get(
                            resolved.qualname, {}).get("fresh"):
                        fresh_names.update(assigned)
                    else:
                        fresh_names.difference_update(assigned)
            else:
                fresh_names.difference_update(assigned)
            rebound.update(assigned)
            if isinstance(stmt, ast.Return) and stmt.value is not None:
                for node in ast.walk(stmt.value):
                    if isinstance(node, ast.Call):
                        if self._is_jitted_call(module, node, info.node):
                            returns_fresh = True
                        else:
                            resolved = self.resolve_call(
                                module, node, scope=info.node)
                            if resolved is not None and facts.get(
                                    resolved.qualname, {}).get("fresh"):
                                returns_fresh = True
                    elif isinstance(node, ast.Name) \
                            and node.id in fresh_names:
                        returns_fresh = True
        return donated, returns_fresh

    def donation_registry(self, module: Module) -> dict[str, JittedFn]:
        """Donating callables VISIBLE in ``module`` beyond its own jit
        facts: imported jitted functions, plus local/imported/project
        functions whose summary says they donate a parameter. Keyed by
        the name a call site would use, as :class:`JittedFn` rows the
        donation pass's machinery consumes unchanged."""
        out: dict[str, JittedFn] = {}
        summaries = self.donation_summaries()

        def add(name: str, info: FunctionInfo) -> None:
            target_jit = self.jitted(info.rel).get(info.name)
            if (target_jit is not None and target_jit.donated
                    and target_jit.lineno == info.lineno):
                out[name] = dataclasses.replace(target_jit, name=name)
                return
            summary = summaries.get(info.qualname)
            if summary:
                out[name] = JittedFn(
                    name=name, params=info.params,
                    donated=frozenset(summary),
                    is_method=info.is_method, lineno=info.lineno,
                    via=", ".join(f"{p} → {chain}"
                                  for p, chain in sorted(summary.items())),
                )

        for name, info in self._functions.get(module.rel, {}).items():
            add(name, info)
        for cls, methods in self._methods.get(module.rel, {}).items():
            for name, info in methods.items():
                add(name, info)
        for name, (target, symbol) in self._imports.get(
                module.rel, {}).items():
            if symbol is None:
                continue
            resolved = self.resolve_symbol(module.rel, name)
            if resolved is not None and resolved[0] == "func":
                add(name, resolved[1])
        return out

    # ----------------------------------------------------- PRNG summaries
    def key_consumers(self) -> dict[str, set[str]]:
        """``{qualname: {param}}``: parameters a function passes (before
        any rebind) into a call that CONSUMES key entropy — an unresolved
        non-deriving call (conservative, the intraprocedural rule), a
        jitted callee, or transitively another summarized consumer. A
        helper that only ``split``\\s its key never lands here, which is
        what lets call sites pass one key to a deriving helper and then
        legitimately consume it once themselves."""
        return self.fixpoint("_key_consumer_facts", self._consumer_transfer)

    def _consumer_transfer(self, info: FunctionInfo, facts) -> set[str]:
        from dib_tpu.analysis.passes.prng import _is_deriving_call as \
            is_deriving

        module = self.modules[info.rel]
        consumed: set[str] = set()
        rebound: set[str] = set()
        for stmt in statements_in_order(info.node):
            direct_args: set[int] = set()
            for call in (n for n in walk_stmt_exprs(stmt)
                         if isinstance(n, ast.Call)):
                for arg in (*call.args, *(kw.value for kw in call.keywords)):
                    if not (isinstance(arg, ast.Name)
                            and arg.id in info.params
                            and arg.id not in rebound):
                        continue
                    direct_args.add(id(arg))
                    if is_deriving(call):
                        continue
                    if self.call_consumes_key(module, call, arg.id,
                                              scope=info.node, facts=facts):
                        consumed.add(arg.id)
            # conservative escape hatch: a param key read in ANY context
            # other than a direct call argument — a bare alias
            # (`k = key`), a container literal, a subscript — may be
            # consumed through the alias, which this summary does not
            # track; mark it consumed so callers keep the conservative
            # intraprocedural behavior instead of a silent pass
            for node in walk_stmt_exprs(stmt):
                if isinstance(node, ast.Name) \
                        and isinstance(node.ctx, ast.Load) \
                        and node.id in info.params \
                        and node.id not in rebound \
                        and id(node) not in direct_args:
                    consumed.add(node.id)
            rebound.update(assigned_names(stmt))
        # the same escape hatch for CLOSURE capture: statements_in_order/
        # walk_stmt_exprs prune nested def/lambda bodies, but a nested
        # function reading the param consumes through the closure —
        # untrackable here, so conservatively consuming (unless the
        # nested scope shadows the name with its own binding)
        for nested in ast.walk(info.node):
            if nested is info.node or not isinstance(
                    nested, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
                continue
            own = {a.arg for a in (*nested.args.posonlyargs,
                                   *nested.args.args,
                                   *nested.args.kwonlyargs)}
            for node in ast.walk(nested):
                if isinstance(node, ast.Name) \
                        and isinstance(node.ctx, ast.Load) \
                        and node.id in info.params \
                        and node.id not in own:
                    consumed.add(node.id)
        return consumed

    def call_consumes_key(self, module: Module, call: ast.Call,
                          argname: str, scope=None, facts=None) -> bool:
        """Does passing ``argname`` to this (non-deriving) call consume
        its entropy? Resolved project functions answer from their
        summary; jitted callees and everything unresolvable answer yes
        (the conservative intraprocedural rule)."""
        if facts is None:
            facts = self.key_consumers()
        info = self.resolve_call(module, call, scope=scope)
        if info is None:
            return True
        target_jit = self.jitted(info.rel).get(info.name)
        if target_jit is not None and target_jit.lineno == info.lineno:
            return True                   # jitted leaves use their keys
        bound = bind_call_args(call, info.params, info.is_method)
        params = {p for p, arg in bound.items()
                  if isinstance(arg, ast.Name) and arg.id == argname}
        if not params:
            return True                   # *args/**kwargs: can't map — be safe
        return bool(params & facts.get(info.qualname, set()))
