"""Content-hash incremental cache: ``lint --changed`` re-analyzes only
dirty files plus their reverse-dependency closure.

The suite is CI-grade only if running it on every commit is cheap. The
parse pass is cheap by construction (one ``ast.parse`` per file); the
expensive part is the passes themselves — so the cache stores each
file's PER-MODULE findings keyed by its content hash, and an
incremental run replays cached findings for every file whose analysis
provably cannot have changed.

The correctness argument (the ``--changed`` ≡ cold-run bit-identity the
tier-1 test pins): a file's per-module findings depend on (a) its own
content and (b) the content of the modules it transitively imports
inside the lint roots — that is exactly what the interprocedural
summaries read (analysis/project.py resolves nothing outside the
project). So the re-analysis set is the dirty files plus the REVERSE
closure of the fresh import graph over them; everything outside that
set replays byte-identically from the cache. Facts still come from the
FULL fresh project (every file is re-parsed every run), so a dirty
helper's new summary is visible to every re-analyzed caller.

Invalidation is total when the analyzer itself changes: the cache key
includes a fingerprint of every ``dib_tpu/analysis/`` source file plus
the registered pass ids, so editing a pass (or this module) discards
the whole cache instead of replaying findings a different analyzer
produced. The same treatment covers the two PROJECT-GLOBAL fact sets
that deliberately escape the import graph — the mesh axis facts the
``mesh-consistency`` pass collects from every module, and the runtime
``EVENT_SCHEMA`` rows the ``event-schema`` pass checks call sites
against: their digest rides the cache, and a change discards the whole
cache rather than letting a module outside the closure replay findings
computed against old global facts. Project-level checks (docs drift)
are re-run every time — they are cheap and depend on files outside the
roots.

Cache location: ``<root>/.dib_lint_cache/cache.json`` (gitignored).
A missing/corrupt/stale-versioned cache degrades to a cold run, never
an error.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Iterable

from dib_tpu.analysis import core
from dib_tpu.analysis.core import Finding, Module

CACHE_VERSION = 1
CACHE_DIRNAME = ".dib_lint_cache"


def cache_path(root: str) -> str:
    return os.path.join(root, CACHE_DIRNAME, "cache.json")


def _content_hash(source: str) -> str:
    return hashlib.sha256(source.encode("utf-8")).hexdigest()


def analyzer_fingerprint() -> str:
    """Hash of the analyzer's own sources + registered pass ids — a pass
    edit must invalidate every cached finding."""
    here = os.path.dirname(os.path.abspath(__file__))
    digest = hashlib.sha256()
    for dirpath, dirnames, filenames in os.walk(here):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                with open(os.path.join(dirpath, fname), "rb") as f:
                    digest.update(f.read())
    digest.update(",".join(sorted(core.REGISTRY)).encode())
    return digest.hexdigest()


def global_facts_digest(project) -> str:
    """Digest of the project-global facts that per-module findings may
    read WITHOUT an import edge: the mesh axis facts (collected from
    every module) and the runtime EVENT_SCHEMA rows. A change in either
    invalidates the whole cache — the reverse-dep closure cannot bound
    their blast radius."""
    from dib_tpu.analysis.passes.mesh import mesh_facts

    facts = mesh_facts(project)
    digest = hashlib.sha256()
    digest.update(repr((sorted(facts.axes), facts.max_rank)).encode())
    try:
        from dib_tpu.telemetry.events import EVENT_SCHEMA

        digest.update(repr(sorted(
            (kind, tuple(spec.required), tuple(spec.optional))
            for kind, spec in EVENT_SCHEMA.items())).encode())
    except Exception:   # a tree without the runtime package still lints
        digest.update(b"no-event-schema")
    return digest.hexdigest()


@dataclasses.dataclass
class TreeResult:
    """One full-tree lint outcome with incrementality accounting."""

    findings: list[Finding]
    analyzed: list[str]          # rels whose passes actually ran
    cached: list[str]            # rels replayed from the cache
    total_files: int
    modules: dict[str, Module]   # the parsed tree (stats/budget reuse it)

    @property
    def analyzed_count(self) -> int:
        return len(self.analyzed)


def _serialize(findings: Iterable[Finding]) -> list[list]:
    return [[f.pass_id, f.path, f.line, f.message] for f in findings]


def _deserialize(rows) -> list[Finding]:
    return [Finding(str(p), str(path), int(line), str(msg))
            for p, path, line, msg in rows]


def load_cache(root: str) -> dict | None:
    try:
        with open(cache_path(root), encoding="utf-8") as f:
            cache = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(cache, dict) or cache.get("version") != CACHE_VERSION:
        return None
    if cache.get("analyzer") != analyzer_fingerprint():
        return None
    # the files payload must hold the shape run_tree indexes into — a
    # hand-mangled (but JSON-valid) cache degrades to a cold run like
    # every other corruption, never a traceback
    files = cache.get("files")
    if not isinstance(files, dict) or not all(
            isinstance(entry, dict)
            and isinstance(entry.get("hash"), str)
            and isinstance(entry.get("deps"), list)
            and isinstance(entry.get("findings"), list)
            for entry in files.values()):
        return None
    return cache


def save_cache(root: str, modules: dict[str, Module],
               per_module: dict[str, list[Finding]],
               deps: dict[str, set[str]], global_facts: str) -> None:
    payload = {
        "version": CACHE_VERSION,
        "analyzer": analyzer_fingerprint(),
        "global_facts": global_facts,
        "files": {
            rel: {
                "hash": _content_hash(modules[rel].source),
                "deps": sorted(deps.get(rel, ())),
                "findings": _serialize(per_module.get(rel, ())),
            }
            for rel in modules
        },
    }
    path = cache_path(root)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
    except OSError:
        pass   # an unwritable cache degrades to cold runs, never an error


def _reverse_closure(seeds: set[str], reverse_deps: dict[str, set[str]],
                     ) -> set[str]:
    out = set(seeds)
    frontier = list(seeds)
    while frontier:
        rel = frontier.pop()
        for dependent in reverse_deps.get(rel, ()):
            if dependent not in out:
                out.add(dependent)
                frontier.append(dependent)
    return out


def run_tree(root: str = core.REPO,
             roots: Iterable[str] = core.DEFAULT_ROOTS,
             select: Iterable[str] | None = None,
             changed: bool = False,
             write_cache: bool | None = None,
             read_cache: bool = True) -> TreeResult:
    """Full-tree lint with optional incrementality.

    ``changed=False`` is a cold run over every file (and — unless
    ``write_cache=False`` — primes the cache for the next ``--changed``
    run). ``changed=True`` replays cached findings for every file
    outside the dirty set's reverse-dependency closure; with no usable
    cache it degrades to a cold run. ``select`` forces a cold,
    cache-less run (a partial pass set must never poison the full-run
    cache). ``read_cache=False`` (the CLI's ``--no-cache``) ignores an
    existing cache entirely — the stale/corrupt-cache escape hatch.
    """
    passes = core.selected_passes(select)
    known_ids = set(core.REGISTRY)
    modules = core.load_tree(root, roots)
    project = core.build_project(modules.values())
    use_cache = select is None
    if write_cache is None:
        write_cache = use_cache
    facts_digest = global_facts_digest(project) if use_cache else ""

    cache = (load_cache(root)
             if (changed and use_cache and read_cache) else None)
    if cache is not None and cache.get("global_facts") != facts_digest:
        cache = None   # global facts escape the import graph: full cold run
    to_analyze = set(modules)
    if cache is not None:
        files = cache.get("files", {})
        dirty = {rel for rel, module in modules.items()
                 if rel not in files
                 or files[rel].get("hash") != _content_hash(module.source)}
        removed = set(files) - set(modules)
        # a deleted module changes its importers' resolution: their
        # cached deps say who they were
        removed_dependents = {
            rel for rel, entry in files.items()
            if any(dep in removed for dep in entry.get("deps", ()))
        }
        seeds = dirty | (removed_dependents & set(modules))
        to_analyze = _reverse_closure(seeds, project.reverse_deps) \
            & set(modules)

    per_module: dict[str, list[Finding]] = {}
    for rel in sorted(modules):
        if rel not in to_analyze:
            try:
                per_module[rel] = _deserialize(
                    cache["files"][rel]["findings"])
                continue
            except (KeyError, TypeError, ValueError):
                # a mangled row degrades THIS file to a fresh analysis,
                # never the whole run to a traceback (the corrupt-cache
                # contract load_cache covers for the other shapes)
                to_analyze.add(rel)
        per_module[rel] = core.check_one_module(
            modules[rel], passes, project=project, known_ids=known_ids)

    findings: list[Finding] = []
    for rel in sorted(per_module):
        findings.extend(per_module[rel])
    for lint in passes:
        findings.extend(lint.check_project(root))
    findings.sort(key=lambda f: (f.path, f.line, f.pass_id, f.message))

    if write_cache and use_cache:
        save_cache(root, modules, per_module, project.module_deps,
                   facts_digest)
    return TreeResult(
        findings=findings,
        analyzed=sorted(to_analyze),
        cached=sorted(set(modules) - to_analyze),
        total_files=len(modules),
        modules=modules,
    )
