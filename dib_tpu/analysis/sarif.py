"""SARIF 2.1.0 rendering: ``lint --sarif`` for code-scanning consumers.

SARIF (Static Analysis Results Interchange Format) is the interchange
shape CI code-scanning UIs ingest (GitHub code scanning, VS Code SARIF
viewers). One run, one tool (``dib-lint``), one rule per registered
pass (the reserved ``pragma`` id included — suppression-grammar
problems must surface in the same UI), one result per finding with a
physical location. ``tests/test_lint/test_tooling.py`` validates the
required-property subset of the 2.1.0 schema.
"""

from __future__ import annotations

from typing import Iterable

from dib_tpu.analysis.core import PRAGMA_PASS_ID, Finding, LintPass

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def sarif_report(findings: Iterable[Finding],
                 passes: Iterable[LintPass]) -> dict:
    """The complete SARIF log object for one lint run."""
    rules = [
        {
            "id": lint.id,
            "shortDescription": {"text": lint.description},
            "fullDescription": {"text": f"Prevents: {lint.incident}"},
            "helpUri": "docs/static-analysis.md",
        }
        for lint in passes
    ]
    rules.append({
        "id": PRAGMA_PASS_ID,
        "shortDescription": {
            "text": "suppression-grammar problems (reasonless, malformed, "
                    "or unknown-pass lint-ok pragmas; unparseable files)"},
        "fullDescription": {
            "text": "Prevents: a suppression that does not parse silently "
                    "changes what the suite checks"},
        "helpUri": "docs/static-analysis.md",
    })
    results = [
        {
            "ruleId": finding.pass_id,
            "level": "error",
            "message": {"text": finding.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": finding.path},
                    "region": {"startLine": finding.line},
                },
            }],
        }
        for finding in findings
    ]
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "dib-lint",
                    "informationUri": "docs/static-analysis.md",
                    "rules": rules,
                },
            },
            "results": results,
        }],
    }
