"""JAX-aware AST helpers shared by the analysis passes.

The donation, host-sync, and PRNG passes all need the same facts about a
module: which locally-defined callables are jitted, which of those donate
which parameters, and how a call site's arguments map onto those
parameters. This module derives them once per :class:`~.core.Module`.
"""

from __future__ import annotations

import ast
import dataclasses

from dib_tpu.analysis.core import Module, call_name, dotted_name


def bind_call_args(call: ast.Call, params: tuple[str, ...],
                   is_method: bool) -> dict[str, ast.expr]:
    """``{parameter name: argument expression}`` for one call site — the
    bound/unbound-method argument mapping every interprocedural fact
    flows through. A bound-method call (``self.run_chunk(state, ...)``)
    maps positionals one parameter later than an unbound call — and an
    unbound call through an attribute (``type(self).run_chunk(self,
    state, ...)``, ``Trainer.run_chunk(self, ...)``) is recognized by
    its explicit leading ``self`` argument, which a bound call never
    passes. Keyword arguments map by name; ``*args``/``**kwargs`` at the
    call site are left unmapped (callers treat unmapped as unknown)."""
    offset = 0
    if is_method and isinstance(call.func, ast.Attribute):
        first = call.args[0] if call.args else None
        explicit_self = (params
                         and isinstance(first, ast.Name)
                         and first.id == params[0])
        offset = 0 if explicit_self else 1
    out: dict[str, ast.expr] = {}
    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Starred):
            # positions after a *args splat depend on its runtime length
            # — leave them (and the splat itself) unmapped, never
            # mis-mapped to the wrong parameter
            break
        idx = i + offset
        if idx < len(params):
            out[params[idx]] = arg
    for kw in call.keywords:
        if kw.arg is not None and kw.arg in params:
            out[kw.arg] = kw.value
    return out


@dataclasses.dataclass(frozen=True)
class JittedFn:
    """One jitted (or donation-summarized) callable."""

    name: str
    params: tuple[str, ...]      # positional-or-keyword params, in order
    donated: frozenset[str]      # subset of params donated to XLA
    is_method: bool              # defined inside a class (self-first)
    lineno: int
    #: For interprocedural summaries (analysis/project.py): the helper
    #: chain through which the donation actually happens ("fit →
    #: run_chunk"). Empty for directly-jitted callables.
    via: str = ""

    def donated_args(self, call: ast.Call) -> dict[str, int]:
        """``{variable name: lineno}`` for every bare-Name argument the
        call binds to a donated parameter (see :func:`bind_call_args`
        for the bound/unbound mapping rules)."""
        return {arg.id: arg.lineno
                for param, arg in bind_call_args(
                    call, self.params, self.is_method).items()
                if param in self.donated and isinstance(arg, ast.Name)}


def _jit_decoration(node: ast.expr) -> dict | None:
    """Inspect one decorator (or an assigned value): returns
    ``{"donate_argnames": [...], "donate_argnums": [...]}`` (either may be
    empty) when the expression is a ``jax.jit``/``partial(jax.jit, ...)``
    application, else None."""
    if not isinstance(node, ast.Call):
        return None
    callee = call_name(node)
    inner_is_jit = False
    if callee in ("partial", "functools.partial") and node.args:
        inner = dotted_name(node.args[0])
        inner_is_jit = inner in ("jax.jit", "jit", "pjit", "jax.pjit")
    is_jit = callee in ("jax.jit", "jit", "pjit", "jax.pjit") or inner_is_jit
    if not is_jit:
        return None
    spec: dict = {"donate_argnames": [], "donate_argnums": []}
    for kw in node.keywords:
        if kw.arg == "donate_argnames":
            spec["donate_argnames"] = _string_elts(kw.value)
        elif kw.arg == "donate_argnums":
            spec["donate_argnums"] = _int_elts(kw.value)
    return spec


def _string_elts(node: ast.expr) -> list[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


def _int_elts(node: ast.expr) -> list[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)]
    return []


def _params(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> tuple[str, ...]:
    args = fn.args
    return tuple(a.arg for a in (*args.posonlyargs, *args.args))


def jitted_callables(module: Module) -> dict[str, JittedFn]:
    """Every locally-defined jitted callable in the module, by name —
    ``@partial(jax.jit, ...)`` / ``@jax.jit`` decorated defs plus
    ``name = jax.jit(fn, ...)`` rebindings of a local def. ``donated``
    resolves ``donate_argnames`` directly and ``donate_argnums`` through
    the wrapped function's parameter list."""
    if module.tree is None:
        return {}
    defs: dict[str, ast.FunctionDef | ast.AsyncFunctionDef] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
    out: dict[str, JittedFn] = {}
    for name, fn in defs.items():
        for deco in fn.decorator_list:
            spec = _jit_decoration(deco)
            if spec is None:
                continue
            params = _params(fn)
            donated = set(spec["donate_argnames"])
            donated.update(params[i] for i in spec["donate_argnums"]
                           if i < len(params))
            out[name] = JittedFn(
                name, params, frozenset(donated),
                is_method=module.enclosing_class(fn) is not None,
                lineno=fn.lineno,
            )
            break
    # name = jax.jit(local_fn, donate_argnums=...) rebindings
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)):
            continue
        spec = _jit_decoration(node.value)
        if spec is None:
            continue
        bound = node.targets[0].id
        wrapped = (node.value.args[0] if node.value.args else None)
        wrapped_def = (defs.get(wrapped.id)
                       if isinstance(wrapped, ast.Name) else None)
        params = _params(wrapped_def) if wrapped_def is not None else ()
        donated = set(spec["donate_argnames"])
        donated.update(params[i] for i in spec["donate_argnums"]
                       if i < len(params))
        out[bound] = JittedFn(
            bound, params, frozenset(donated),
            is_method=False, lineno=node.lineno,
        )
    return out


def match_callable(call: ast.Call, registry: dict[str, JittedFn]
                   ) -> JittedFn | None:
    """The registry entry a call site resolves to: a bare-name call
    (``run_chunk(...)``) or any attribute call with a matching terminal
    name (``self.run_chunk(...)``, ``trainer.run_chunk(...)``)."""
    func = call.func
    if isinstance(func, ast.Name):
        return registry.get(func.id)
    if isinstance(func, ast.Attribute):
        return registry.get(func.attr)
    return None
