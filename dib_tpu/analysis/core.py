"""Framework shared by every lint pass: walker, pragmas, allowlists, registry.

One engine, one pragma grammar. A pass sees a :class:`Module` — parsed
source with AST parent links, per-line pragma table, and scope helpers —
and returns :class:`Finding`\\s. The framework owns everything a pass
should not re-implement:

  - **walking** the tree (``dib_tpu/`` + ``scripts/`` by default, one
    parse per file shared by every pass);
  - **suppression**: a finding on a line carrying
    ``# lint-ok(<pass>): <reason>`` is dropped — the reason is MANDATORY
    (a reasonless pragma is itself a finding, pass id ``pragma``), and so
    is naming a real pass (typos surface instead of silently
    suppressing nothing). Legacy spellings ``# timing-ok: <reason>`` and
    ``# fault-ok: <reason>`` map to the migrated ``timing-hygiene`` /
    ``exception-hygiene`` passes so the pre-framework pragmas keep
    working;
  - **allowlists**: each pass may exempt whole modules, every entry
    carrying the justification that would otherwise live in a review
    thread (enforced non-empty at registration);
  - **scoping**: a pass declares where it applies — the package, the
    scripts tree, both, or an explicit module list (``target_modules``).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Iterable, Iterator

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: Default lint roots, relative to the repo checkout.
DEFAULT_ROOTS = ("dib_tpu", "scripts")

#: The reserved pass id for pragma-grammar findings (always reported,
#: never selectable away — a suppression that doesn't parse must not
#: silently suppress, and must not silently NOT suppress either).
PRAGMA_PASS_ID = "pragma"

_PRAGMA_RE = re.compile(r"#\s*lint-ok\s*\(([^)]*)\)\s*(?::\s*(.*))?")
#: Legacy per-check pragmas (pre-framework), mapped onto their passes.
LEGACY_PRAGMAS = {
    "timing-ok": "timing-hygiene",
    "fault-ok": "exception-hygiene",
}
_LEGACY_RES = {
    word: re.compile(r"#\s*" + re.escape(word) + r"\b\s*(?::\s*(.*))?")
    for word in LEGACY_PRAGMAS
}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding, anchored to a repo-relative path and 1-based line."""

    pass_id: str
    path: str          # repo-relative, forward slashes
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_id}] {self.message}"


@dataclasses.dataclass(frozen=True)
class Pragma:
    """A parsed suppression on one physical line."""

    passes: tuple[str, ...]
    reason: str


class Module:
    """One parsed source file, shared by every pass that looks at it.

    ``tree`` is the parsed AST with parent links (``parent_of``) or
    ``None`` when the file does not parse (``parse_error`` carries the
    SyntaxError; the framework reports unparseable files itself).
    ``pragmas`` maps 1-based line numbers to :class:`Pragma`.
    """

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.pragma_findings: list[Finding] = []
        self.pragmas: dict[int, Pragma] = {}
        self._parse_pragmas()
        self.parse_error: SyntaxError | None = None
        self._parents: dict[ast.AST, ast.AST] = {}
        try:
            self.tree: ast.Module | None = ast.parse(source, filename=rel)
        except SyntaxError as exc:
            self.tree = None
            self.parse_error = exc
        else:
            for parent in ast.walk(self.tree):
                for child in ast.iter_child_nodes(parent):
                    self._parents[child] = parent

    # ------------------------------------------------------------- pragmas
    def _comments(self) -> Iterator[tuple[int, int, str]]:
        """(lineno, col, text) for every real COMMENT token — pragmas live
        in comments only, so a docstring *describing* the grammar is never
        mistaken for a suppression."""
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(self.source).readline)
            for tok in tokens:
                if tok.type == tokenize.COMMENT:
                    yield tok.start[0], tok.start[1], tok.string
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return  # unparseable files are reported by the framework

    def _anchor(self, lineno: int, col: int) -> int:
        """The line a pragma suppresses: its own when it trails code, else
        (comment-only line, where long reasons live) the next code line."""
        if self.lines[lineno - 1][:col].strip():
            return lineno
        n = lineno + 1
        while n <= len(self.lines):
            text = self.lines[n - 1].strip()
            if text and not text.startswith("#"):
                return n
            n += 1
        return lineno

    def _parse_pragmas(self) -> None:
        for lineno, col, line in self._comments():
            m = _PRAGMA_RE.search(line)
            if m:
                ids = tuple(p.strip() for p in m.group(1).split(",") if p.strip())
                reason = (m.group(2) or "").strip()
                if not ids or not reason:
                    self.pragma_findings.append(Finding(
                        PRAGMA_PASS_ID, self.rel, lineno,
                        "suppression must name a pass and carry a reason: "
                        "`# lint-ok(<pass>): <reason>`",
                    ))
                    continue
                self._add_pragma(self._anchor(lineno, col), ids, reason)
                continue
            if "lint-ok" in line:
                self.pragma_findings.append(Finding(
                    PRAGMA_PASS_ID, self.rel, lineno,
                    "malformed lint-ok pragma (expected "
                    "`# lint-ok(<pass>): <reason>`)",
                ))
                continue
            for word, regex in _LEGACY_RES.items():
                m = regex.search(line)
                if m is None:
                    continue
                reason = (m.group(1) or "").strip()
                if not reason:
                    self.pragma_findings.append(Finding(
                        PRAGMA_PASS_ID, self.rel, lineno,
                        f"legacy `# {word}:` pragma needs a reason",
                    ))
                else:
                    self._add_pragma(self._anchor(lineno, col),
                                     (LEGACY_PRAGMAS[word],), reason)

    def _add_pragma(self, anchor: int, ids, reason: str) -> None:
        """Record one suppression; stacked comment-only pragma lines that
        anchor to the same code line MERGE their pass ids instead of the
        later one silently dropping the earlier."""
        prev = self.pragmas.get(anchor)
        if prev is not None:
            ids = (*prev.passes, *ids)
        self.pragmas[anchor] = Pragma(tuple(ids), reason)

    def suppressed(self, pass_id: str, line: int) -> bool:
        pragma = self.pragmas.get(line)
        return pragma is not None and pass_id in pragma.passes

    # --------------------------------------------------------- AST helpers
    def parent_of(self, node: ast.AST) -> ast.AST | None:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def enclosing_class(self, node: ast.AST) -> ast.ClassDef | None:
        for anc in self.ancestors(node):
            if isinstance(anc, ast.ClassDef):
                return anc
        return None

    def functions(self) -> Iterator[ast.FunctionDef | ast.AsyncFunctionDef]:
        """Every function/method in the file, outermost first."""
        if self.tree is None:
            return
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


def statements_in_order(fn: ast.AST) -> list[ast.stmt]:
    """Every statement lexically inside ``fn`` (excluding nested function/
    class bodies), in source order — the linearization the scope-local
    passes (donation, PRNG) reason over. Branches of an ``if``/``try``
    appear in source order; that is deliberate for a lint: a read that is
    lexically after a donating call is worth a look even when one branch
    can't reach it."""
    out: list[ast.stmt] = []

    def visit(body: Iterable[ast.stmt]) -> None:
        for stmt in body:
            out.append(stmt)
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested scopes analyze separately
            for field in ("body", "orelse", "finalbody"):
                inner = getattr(stmt, field, None)
                if inner:
                    visit(inner)
            for handler in getattr(stmt, "handlers", ()) or ():
                visit(handler.body)
            for case in getattr(stmt, "cases", ()) or ():
                visit(case.body)

    visit(getattr(fn, "body", ()))
    return out


def stmt_expr_roots(stmt: ast.stmt) -> list[ast.AST]:
    """The expression nodes that belong to ONE statement in the
    :func:`statements_in_order` linearization. For a simple statement
    that is the statement itself; for a compound statement it is only
    the header (an ``if``/``while`` test, a ``for`` iterable, ``with``
    context expressions) — the nested statements appear later in the
    linearization in their own right, so walking the whole subtree here
    would double-count every read and call inside the body."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in stmt.items]
    if isinstance(stmt, ast.Try):
        return []
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return list(stmt.decorator_list)
    if isinstance(stmt, ast.Match):
        return [stmt.subject]
    return [stmt]


def walk_stmt_exprs(stmt: ast.stmt) -> Iterator[ast.AST]:
    """Walk exactly the nodes :func:`stmt_expr_roots` owns, pruning
    nested function/class/lambda subtrees (separate scopes — analyzed,
    if at all, on their own)."""
    stack = list(stmt_expr_roots(stmt))
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            stack.append(child)


def assigned_names(stmt: ast.stmt) -> set[str]:
    """Bare names (re)bound by one statement: assignment targets including
    tuple unpacking, aug-assign, ``for`` targets, and ``with ... as``."""
    names: set[str] = set()

    def collect(target: ast.AST) -> None:
        if isinstance(target, ast.Name):
            names.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                collect(elt)
        elif isinstance(target, ast.Starred):
            collect(target.value)

    if isinstance(stmt, ast.Assign):
        for target in stmt.targets:
            collect(target)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        collect(stmt.target)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        collect(stmt.target)
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                collect(item.optional_vars)
    return names


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> str | None:
    """Dotted name of a call's callee (``jax.random.split`` etc.), or None
    for computed callees."""
    return dotted_name(call.func)


# ------------------------------------------------------------------ passes
class LintPass:
    """Base class for one lint pass.

    Subclasses set:

    - ``id``: the pass id used in ``--select`` and pragmas (kebab-case);
    - ``description``: one line, shown by ``lint --list``;
    - ``incident``: the runtime incident this pass prevents (shown in the
      pass catalog — every pass exists because something burned time);
    - ``scope``: ``"all"`` (default), ``"package"`` (``dib_tpu/`` only),
      or ``"scripts"``;
    - ``target_modules``: optional explicit repo-relative module list —
      when set, the pass runs ONLY on those modules (e.g. host-sync
      hygiene applies to the chunk-loop modules);
    - ``allowlist``: ``{repo-relative path: justification}`` module
      exemptions.

    and implement :meth:`check_module`; :meth:`check_project` optionally
    adds whole-project checks (e.g. schema-vs-docs drift).
    """

    id: str = ""
    description: str = ""
    incident: str = ""
    scope: str = "all"
    target_modules: tuple[str, ...] | None = None
    allowlist: dict[str, str] = {}

    def applies_to(self, rel: str) -> bool:
        if self.target_modules is not None:
            return rel in self.target_modules
        if self.scope == "package":
            return rel.startswith("dib_tpu/")
        if self.scope == "scripts":
            return rel.startswith("scripts/")
        return True

    def check_module(self, module: Module) -> list[Finding]:
        return []

    def check_module_with_project(self, module: Module,
                                  project) -> list[Finding]:
        """Hook for interprocedural passes: ``project`` is the
        :class:`~dib_tpu.analysis.project.Project` built over the whole
        lint tree (None when a caller runs a pass standalone). The
        default delegates to the intraprocedural :meth:`check_module`."""
        return self.check_module(module)

    def check_project(self, root: str) -> list[Finding]:
        return []

    def finding(self, module: Module, line: int, message: str) -> Finding:
        return Finding(self.id, module.rel, line, message)


REGISTRY: dict[str, LintPass] = {}


def register(cls: type[LintPass]) -> type[LintPass]:
    """Class decorator: instantiate and register one pass."""
    inst = cls()
    if not inst.id or not inst.description or not inst.incident:
        raise ValueError(
            f"{cls.__name__}: a pass must declare id, description, and the "
            "runtime incident it prevents")
    if inst.id == PRAGMA_PASS_ID:
        raise ValueError(f"pass id {PRAGMA_PASS_ID!r} is reserved")
    if inst.id in REGISTRY:
        raise ValueError(f"duplicate pass id {inst.id!r}")
    for rel, why in inst.allowlist.items():
        if not why or not why.strip():
            raise ValueError(
                f"{inst.id}: allowlist entry {rel!r} needs a justification")
    REGISTRY[inst.id] = inst
    return cls


def all_passes() -> list[LintPass]:
    return [REGISTRY[k] for k in sorted(REGISTRY)]


def get_pass(pass_id: str) -> LintPass:
    return REGISTRY[pass_id]


# ------------------------------------------------------------------ runner
def iter_source_files(root: str, roots: Iterable[str] = DEFAULT_ROOTS,
                      ) -> Iterator[tuple[str, str]]:
    """Yield ``(abs_path, repo_relative)`` for every ``.py`` under the lint
    roots, sorted, ``__pycache__`` pruned."""
    for sub in roots:
        base = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
            for fname in sorted(filenames):
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fname)
                yield path, os.path.relpath(path, root).replace(os.sep, "/")


def load_module(path: str, rel: str) -> Module:
    with open(path, encoding="utf-8") as f:
        return Module(path, rel, f.read())


def load_tree(root: str, roots: Iterable[str] = DEFAULT_ROOTS,
              ) -> dict[str, Module]:
    """Parse every source file under the lint roots once — the shared
    parse pass both the per-module passes and the interprocedural
    project index reason over."""
    return {rel: load_module(path, rel)
            for path, rel in iter_source_files(root, roots)}


def build_project(modules: Iterable[Module]):
    from dib_tpu.analysis.project import Project

    return Project(modules)


def check_one_module(module: Module, passes: list[LintPass],
                     project=None, known_ids: set[str] | None = None,
                     ) -> list[Finding]:
    """Every per-module finding for one file: pragma-grammar problems,
    unknown-pass pragmas, parse errors, and the (selected) passes with
    suppression + allowlists applied. This is the unit the incremental
    cache stores and replays — it must depend only on the module's
    content and (through ``project``) its transitive imports."""
    known_ids = known_ids if known_ids is not None else set(REGISTRY)
    findings: list[Finding] = list(module.pragma_findings)
    for lineno, pragma in module.pragmas.items():
        for pid in pragma.passes:
            if pid not in known_ids:
                findings.append(Finding(
                    PRAGMA_PASS_ID, module.rel, lineno,
                    f"pragma suppresses unknown pass {pid!r} "
                    f"(available: {sorted(known_ids)})"))
    if module.parse_error is not None:
        findings.append(Finding(
            PRAGMA_PASS_ID, module.rel, module.parse_error.lineno or 1,
            f"file does not parse: {module.parse_error.msg}"))
        return findings
    for lint in passes:
        if not lint.applies_to(module.rel):
            continue
        if module.rel in lint.allowlist:
            continue
        for finding in lint.check_module_with_project(module, project):
            if not module.suppressed(lint.id, finding.line):
                findings.append(finding)
    return findings


def selected_passes(select: Iterable[str] | None) -> list[LintPass]:
    if select is None:
        return all_passes()
    select = sorted(set(select))
    unknown = [s for s in select if s not in REGISTRY]
    if unknown:
        raise KeyError(
            f"unknown pass id(s) {unknown}; available: {sorted(REGISTRY)}")
    return [REGISTRY[s] for s in select]


def run_passes(
    root: str = REPO,
    roots: Iterable[str] = DEFAULT_ROOTS,
    select: Iterable[str] | None = None,
    files: Iterable[tuple[str, str]] | None = None,
) -> list[Finding]:
    """Run the (selected) passes over the tree; returns surviving findings.

    Pragma suppression and allowlists are applied here — a pass never
    sees its own suppressions. Pragma-grammar problems (reasonless or
    malformed suppressions, pragmas naming unknown passes) are reported
    under the reserved ``pragma`` id regardless of ``select``: a
    suppression that doesn't parse silently changes what the suite
    checks, so it can never be filtered out.

    Interprocedural facts always come from the WHOLE tree under
    ``root``/``roots`` (plus any explicit ``files`` outside it): linting
    one file still sees project-wide donation/key/blocking summaries,
    so a helper boundary never truncates a fact. With explicit
    ``files``, per-module findings are reported only for those files and
    project-level checks are skipped (unchanged CLI semantics).
    """
    passes = selected_passes(select)
    known_ids = set(REGISTRY)
    tree = load_tree(root, roots)
    explicit: list[Module] = []
    if files is not None:
        for path, rel in files:
            explicit.append(tree[rel] if rel in tree
                            else load_module(path, rel))
    project = build_project(
        list(tree.values())
        + [m for m in explicit if m.rel not in tree])

    findings: list[Finding] = []
    targets = explicit if files is not None else list(tree.values())
    for module in targets:
        findings.extend(check_one_module(module, passes, project=project,
                                         known_ids=known_ids))
    if files is None:  # project-level checks run only on full-tree runs
        for lint in passes:
            findings.extend(lint.check_project(root))
    findings.sort(key=lambda f: (f.path, f.line, f.pass_id, f.message))
    return findings


def pragma_counts(modules: Iterable[Module]) -> dict[str, int]:
    """Per-pass suppression counts over a set of parsed modules — the
    raw material of the ``lint --stats`` suppression-budget report. Each
    (anchor line, pass id) pair counts once; legacy pragmas count under
    the pass they map to."""
    counts: dict[str, int] = {}
    for module in modules:
        for pragma in module.pragmas.values():
            for pid in pragma.passes:
                counts[pid] = counts.get(pid, 0) + 1
    return dict(sorted(counts.items()))
