"""dib-lint: a pass-based JAX-correctness static-analysis suite.

PR 4's fault drills found two latent buffer-donation bugs at *runtime*
(async checkpoint saves reading buffers ``run_chunk``'s donation had
already reused) — a defect class that is decidable from the AST. This
package is the static-analysis layer that catches those bug classes
before a drill (or production) has to: one shared AST walker with
parent/scope tracking (``core.py``), a pass registry, one pragma
grammar (``# lint-ok(<pass>): <reason>`` — reasons mandatory), per-pass
module allowlists with justifications, and one CLI::

    python -m dib_tpu lint [paths...] [--select pass,...] [--json]

Exit codes: 0 clean, 1 findings, 2 bad usage. See docs/static-analysis.md
for the pass catalog (each pass names the runtime incident it prevents)
and how to add a pass.
"""

from dib_tpu.analysis.core import (
    Finding,
    LintPass,
    Module,
    all_passes,
    get_pass,
    register,
    run_passes,
)
from dib_tpu.analysis.cli import lint_main

# Importing the pass modules registers them (each module calls @register
# at import time). Keep this list in sync with docs/static-analysis.md.
from dib_tpu.analysis.passes import (  # noqa: F401
    donation,
    event_schema,
    exceptions,
    host_sync,
    prng,
    thread_state,
    timing,
)

__all__ = [
    "Finding",
    "LintPass",
    "Module",
    "all_passes",
    "get_pass",
    "lint_main",
    "register",
    "run_passes",
]
