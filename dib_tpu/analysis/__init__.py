"""dib-lint: a pass-based JAX-correctness static-analysis suite.

PR 4's fault drills found two latent buffer-donation bugs at *runtime*
(async checkpoint saves reading buffers ``run_chunk``'s donation had
already reused) — a defect class that is decidable from the AST. This
package is the static-analysis layer that catches those bug classes
before a drill (or production) has to: one shared AST walker with
parent/scope tracking (``core.py``), an INTERPROCEDURAL dataflow engine
(``project.py``: project-wide symbol table, call-graph edges with
bound/unbound argument mapping, fixpoint taint summaries so donation /
key-consumption / loop-blocking / resource facts cross helper
boundaries), a pass registry, one pragma grammar
(``# lint-ok(<pass>): <reason>`` — reasons mandatory), per-pass module
allowlists with justifications, and one CLI::

    python -m dib_tpu lint [paths...] [--select pass,...]
                           [--json | --sarif] [--changed] [--stats]

Exit codes: 0 clean, 1 findings (or budget violation under ``--stats``),
2 bad usage. ``--changed`` is the incremental mode (content-hash cache
under ``.dib_lint_cache/``, bit-identical to a cold run); ``--stats``
gates the per-pass suppression counts against the committed
``LINT_BUDGET.json``. See docs/static-analysis.md for the pass catalog
(each pass names the runtime incident it prevents) and how to add a
pass.
"""

from dib_tpu.analysis.core import (
    Finding,
    LintPass,
    Module,
    all_passes,
    get_pass,
    register,
    run_passes,
)
from dib_tpu.analysis.cli import lint_main
from dib_tpu.analysis.cache import run_tree
from dib_tpu.analysis.project import Project

# Importing the pass modules registers them (each module calls @register
# at import time). Keep this list in sync with docs/static-analysis.md.
from dib_tpu.analysis.passes import (  # noqa: F401
    async_blocking,
    donation,
    event_schema,
    exceptions,
    host_sync,
    mesh,
    prng,
    resource_lifecycle,
    thread_state,
    timing,
)

__all__ = [
    "Finding",
    "LintPass",
    "Module",
    "Project",
    "all_passes",
    "get_pass",
    "lint_main",
    "register",
    "run_passes",
    "run_tree",
]
