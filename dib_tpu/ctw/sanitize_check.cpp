// Sanitizer self-test for the CTW native component (SURVEY.md section 5:
// the framework's answer to "race detection / sanitizers" — the reference
// has none; here the C++ core is exercised under ASan/UBSan in the test
// suite, which compiles this file together with ctw.cpp using
// -fsanitize=address,undefined and asserts a clean exit).
//
// Exercises every extern "C" entry point across the regimes that stress the
// allocator and tree logic: random sequences (deep unique contexts),
// periodic sequences (path compression / tail splitting), incremental
// appends in odd-sized chunks, small depth caps, and multiple alphabets.

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <vector>

extern "C" {
double dib_ctw_entropy(const int32_t* seq, int64_t n, int32_t alphabet_size,
                       int32_t max_depth);
void* dib_ctw_new(int32_t alphabet_size, int32_t max_depth);
void dib_ctw_free(void* handle);
void dib_ctw_append(void* handle, const int32_t* seq, int64_t n);
double dib_ctw_code_length(void* handle);
int64_t dib_ctw_length(void* handle);
int64_t dib_ctw_num_nodes(void* handle);
}

static uint64_t rng_state = 0x9E3779B97F4A7C15ull;
static uint32_t next_u32() {
  rng_state ^= rng_state << 13;
  rng_state ^= rng_state >> 7;
  rng_state ^= rng_state << 17;
  return static_cast<uint32_t>(rng_state >> 32);
}

static int check(bool ok, const char* what) {
  if (!ok) {
    std::fprintf(stderr, "FAIL: %s\n", what);
    return 1;
  }
  return 0;
}

int main() {
  int failures = 0;

  for (int32_t alphabet = 2; alphabet <= 5; ++alphabet) {
    for (int32_t depth : {1, 4, 64, 512}) {
      // random sequence: one-shot API
      std::vector<int32_t> random_seq(4096);
      for (auto& s : random_seq) s = static_cast<int32_t>(next_u32() % alphabet);
      double h_rand = dib_ctw_entropy(random_seq.data(),
                                      static_cast<int64_t>(random_seq.size()),
                                      alphabet, depth);
      failures += check(std::isfinite(h_rand) && h_rand >= 0.0,
                        "random entropy finite/nonnegative");
      failures += check(h_rand <= std::log2(static_cast<double>(alphabet)) + 0.2,
                        "random entropy <= log2(alphabet) + slack");

      // periodic sequence: stresses path compression / tail splitting
      std::vector<int32_t> periodic(8192);
      for (size_t i = 0; i < periodic.size(); ++i)
        periodic[i] = static_cast<int32_t>((i % 3) % alphabet);
      double h_per = dib_ctw_entropy(periodic.data(),
                                     static_cast<int64_t>(periodic.size()),
                                     alphabet, depth);
      failures += check(std::isfinite(h_per) && h_per >= 0.0,
                        "periodic entropy finite");
      // a period-3 pattern is deterministic given >= 2 context symbols;
      // at depth 1 the binary-alphabet case is genuinely ambiguous (~0.67)
      if (depth >= 2) {
        failures += check(h_per < 0.3, "periodic sequence compresses");
      }

      // incremental API in odd-sized chunks, including empty appends
      void* handle = dib_ctw_new(alphabet, depth);
      failures += check(handle != nullptr, "handle allocated");
      dib_ctw_append(handle, random_seq.data(), 0);   // empty append is a no-op
      int64_t offset = 0;
      const int64_t chunks[] = {1, 7, 128, 1000, 2960};
      for (int64_t c : chunks) {
        dib_ctw_append(handle, random_seq.data() + offset, c);
        offset += c;
      }
      failures += check(dib_ctw_length(handle) == offset, "incremental length");
      failures += check(dib_ctw_num_nodes(handle) > 0, "nodes allocated");
      double cl = dib_ctw_code_length(handle);
      double h_inc = cl / static_cast<double>(offset);
      // incremental on the full prefix == one-shot on the same prefix
      double h_ref = dib_ctw_entropy(random_seq.data(), offset, alphabet, depth);
      failures += check(std::fabs(h_inc - h_ref) < 1e-9,
                        "incremental matches one-shot");
      dib_ctw_free(handle);
    }
  }

  // single-symbol and tiny sequences (boundary conditions)
  int32_t one[] = {0};
  double h1 = dib_ctw_entropy(one, 1, 2, 512);
  failures += check(std::isfinite(h1), "single symbol finite");
  int32_t tiny[] = {1, 0, 0, 1};
  double h4 = dib_ctw_entropy(tiny, 4, 2, 512);
  failures += check(std::isfinite(h4) && h4 > 0.0, "tiny sequence finite");

  if (failures) {
    std::fprintf(stderr, "%d check(s) failed\n", failures);
    return 1;
  }
  std::printf("sanitize_check OK\n");
  return 0;
}
