"""Context Tree Weighting entropy-rate estimation (native C++ component).

Host-side counterpart of the TPU workloads: the chaos
measurement-optimization pipeline symbolizes long trajectories on device,
then scores the symbol sequences' entropy rate here (reference call stack:
chaos notebook cell 10 -> ctw.estimate_entropy, chaos/ctw.pyx:2 ->
chaos/cppctw.cpp:163). CTW is inherently sequential pointer-chasing, so it
stays native/CPU by design.

The C++ core (``ctw.cpp``) is compiled on first use into a shared library
and bound through ``ctypes`` (no Cython/pybind build dependency). Beyond
the reference's one-shot ``estimate_entropy``, this module exposes
:class:`CTWEstimator`, an incremental estimator whose tree grows across
``append`` calls — entropy-rate-vs-length scaling curves (the
Schürmann–Grassberger extrapolation workload) reuse one tree instead of
rebuilding per length.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Sequence

import numpy as np

__all__ = ["estimate_entropy", "CTWEstimator", "DEFAULT_MAX_DEPTH"]

# Same default context-depth cap as the reference (chaos/cppctw.cpp:13).
DEFAULT_MAX_DEPTH = 512

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "ctw.cpp")
_LIB_PATH = os.path.join(_HERE, "libdibctw.so")

_lib = None
_lib_lock = threading.Lock()


def _build_library() -> None:
    # Compile to a temp name and rename into place: concurrent processes
    # (pytest workers, sweep shards on shared FS) may race import-time
    # builds, and POSIX rename keeps dlopen from ever seeing a partial ELF.
    tmp_path = f"{_LIB_PATH}.{os.getpid()}.tmp"
    cmd = [
        "g++",
        "-O3",
        "-std=c++17",
        "-shared",
        "-fPIC",
        "-o",
        tmp_path,
        _SRC,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except subprocess.CalledProcessError as e:
        raise RuntimeError(f"CTW native build failed:\n{e.stderr}") from e
    os.replace(tmp_path, _LIB_PATH)


def _load() -> ctypes.CDLL:
    """Compile (if stale) and load the shared library, configuring signatures."""
    global _lib
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if (not os.path.exists(_LIB_PATH)) or (
            os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)
        ):
            _build_library()
        lib = ctypes.CDLL(_LIB_PATH)
        lib.dib_ctw_entropy.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.c_int32,
        ]
        lib.dib_ctw_entropy.restype = ctypes.c_double
        lib.dib_ctw_new.argtypes = [ctypes.c_int32, ctypes.c_int32]
        lib.dib_ctw_new.restype = ctypes.c_void_p
        lib.dib_ctw_free.argtypes = [ctypes.c_void_p]
        lib.dib_ctw_free.restype = None
        lib.dib_ctw_append.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
        ]
        lib.dib_ctw_append.restype = None
        lib.dib_ctw_code_length.argtypes = [ctypes.c_void_p]
        lib.dib_ctw_code_length.restype = ctypes.c_double
        lib.dib_ctw_length.argtypes = [ctypes.c_void_p]
        lib.dib_ctw_length.restype = ctypes.c_int64
        lib.dib_ctw_num_nodes.argtypes = [ctypes.c_void_p]
        lib.dib_ctw_num_nodes.restype = ctypes.c_int64
        _lib = lib
        return _lib


def _as_symbols(sequence: Sequence[int] | np.ndarray, alphabet_size: int) -> np.ndarray:
    seq = np.ascontiguousarray(sequence, dtype=np.int32)
    if seq.ndim != 1:
        raise ValueError(f"sequence must be 1-D, got shape {seq.shape}")
    if seq.size and (seq.min() < 0 or seq.max() >= alphabet_size):
        raise ValueError(
            f"symbols must lie in [0, {alphabet_size}); "
            f"got range [{seq.min()}, {seq.max()}]"
        )
    return seq


def estimate_entropy(
    sequence: Sequence[int] | np.ndarray,
    alphabet_size: int,
    max_depth: int = DEFAULT_MAX_DEPTH,
) -> float:
    """CTW entropy-rate estimate of ``sequence`` in bits/symbol.

    API parity with the reference binding (chaos/ctw.pyx:2-3), with the
    depth cap exposed instead of hardcoded.
    """
    if alphabet_size < 2:
        raise ValueError("alphabet_size must be >= 2")
    seq = _as_symbols(sequence, alphabet_size)
    if seq.size == 0:
        return 0.0
    lib = _load()
    ptr = seq.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
    return float(lib.dib_ctw_entropy(ptr, seq.size, alphabet_size, max_depth))


class CTWEstimator:
    """Incremental CTW estimator: append symbols, query entropy at any point.

    The underlying context tree persists across ``append`` calls, so scoring
    a sequence at many prefix lengths costs one tree build instead of one
    per length (the reference rebuilds from scratch per length,
    chaos notebook cell 10 post-training loop).
    """

    def __init__(self, alphabet_size: int, max_depth: int = DEFAULT_MAX_DEPTH):
        if alphabet_size < 2:
            raise ValueError("alphabet_size must be >= 2")
        self.alphabet_size = int(alphabet_size)
        self.max_depth = int(max_depth)
        self._lib = _load()
        self._handle = self._lib.dib_ctw_new(self.alphabet_size, self.max_depth)
        if not self._handle:
            raise RuntimeError("failed to allocate CTW context tree")

    def append(self, sequence: Sequence[int] | np.ndarray) -> "CTWEstimator":
        seq = _as_symbols(sequence, self.alphabet_size)
        if seq.size:
            ptr = seq.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
            self._lib.dib_ctw_append(self._handle, ptr, seq.size)
        return self

    @property
    def length(self) -> int:
        return int(self._lib.dib_ctw_length(self._handle))

    @property
    def num_nodes(self) -> int:
        return int(self._lib.dib_ctw_num_nodes(self._handle))

    def code_length_bits(self) -> float:
        """Total CTW weighted code length of everything appended, in bits."""
        return float(self._lib.dib_ctw_code_length(self._handle))

    def entropy_rate(self) -> float:
        """Current entropy-rate estimate in bits/symbol."""
        n = self.length
        return self.code_length_bits() / n if n else 0.0

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.dib_ctw_free(self._handle)
            self._handle = None

    def __enter__(self) -> "CTWEstimator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # best-effort; prefer close()/context manager
        try:
            self.close()
        except Exception:  # fault-ok: __del__ during interpreter shutdown must never raise; ctypes/lib state may already be torn down
            pass
