"""dib_tpu.ctw (populated incrementally)."""
