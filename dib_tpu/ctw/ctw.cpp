// Context Tree Weighting entropy-rate estimator (host-side native component).
//
// Capability parity with the reference's infinite-depth CTW estimator
// (reference chaos/cppctw.cpp: KT estimator, weighted context mixing,
// path-compressed lazy tails, depth cap), re-architected for this framework:
//
//   * flat arena storage (index-based nodes in contiguous vectors) instead of
//     per-node heap allocations and recursive destructors — cache-friendly,
//     O(1) teardown, and immune to destructor stack overflow on deep chains;
//   * iterative explicit-stack post-order pass for the code-length mixing
//     recursion;
//   * an incremental API: symbols can be appended across calls and the code
//     length re-queried, so entropy-rate-vs-length scaling curves reuse one
//     growing tree instead of rebuilding from scratch at every length;
//   * int32 symbols (alphabets beyond char), int64 counts/positions, and a
//     configurable max context depth;
//   * a plain C ABI for ctypes binding (no Cython/pybind dependency).
//
// Algorithm (identical math to the reference, Willems et al. 1995):
//   - every context node holds symbol counts; the Krichevsky–Trofimov local
//     code length with Dirichlet parameter b = 1/K is
//         L_E = [lgamma(S + K b) - lgamma(K b) - sum_i(lgamma(c_i + b)
//                - lgamma(b))] / ln 2   (bits)
//   - the CTW weighted length mixes the local estimate with the children's:
//         L_w = -log2( (2^{-L_E} + 2^{-L_C}) / 2 )
//             = 1 + min(L_E, L_C) - log2(1 + 2^{-|L_E - L_C|})
//     applied when the node has expanded children and more than one count;
//   - entropy-rate estimate = root weighted code length / sequence length.
//
// Path compression: a chain of contexts visited exactly once is stored as a
// single "tail" node remembering (position in the sequence, the one counted
// symbol); the chain is expanded one link at a time only when revisited.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <vector>

namespace {

constexpr int32_t kNoChild = -1;
constexpr int64_t kNoTail = -1;

class ContextTree {
 public:
  ContextTree(int32_t alphabet_size, int32_t max_depth)
      : k_(alphabet_size),
        max_depth_(max_depth),
        kt_b_(1.0 / static_cast<double>(alphabet_size)) {
    // node 0 is the root (empty context)
    new_node(kNoTail, -1);
  }

  // Append symbols, updating counts along each suffix-context path.
  // Single pass; safe to call repeatedly (incremental growth).
  void append(const int32_t* symbols, int64_t n) {
    seq_.reserve(seq_.size() + static_cast<size_t>(n));
    for (int64_t i = 0; i < n; ++i) {
      const int64_t pos = static_cast<int64_t>(seq_.size());
      const int32_t sym = symbols[i];
      seq_.push_back(sym);
      count_at(0, sym)++;  // root sees every symbol
      if (pos == 0) continue;

      int32_t node = 0;
      // Walk contexts backwards: symbol at pos-1 selects the depth-1 child...
      for (int64_t ctx = pos - 1; ctx >= 0; --ctx) {
        // Depth cap binds the whole walk — creation, tail expansion, and
        // descent alike — so context statistics are exactly those of a
        // depth-limited tree. (The reference checks only at node creation,
        // letting tail expansion drift past the cap.)
        if (pos - ctx > max_depth_) break;
        // Expand a compressed tail chain by one link before descending.
        if (tail_pos_[node] > 0) {
          const int64_t tpos = tail_pos_[node];
          const int32_t tsym = tail_sym_[node];
          const int32_t branch = seq_[static_cast<size_t>(tpos - 1)];
          const int32_t child = new_node(tpos - 1, tsym);
          child_at(node, branch) = child;
          count_at(child, tsym)++;
          tail_pos_[node] = kNoTail;
          tail_sym_[node] = -1;
        }
        const int32_t ctx_sym = seq_[static_cast<size_t>(ctx)];
        int32_t next = child_at(node, ctx_sym);
        if (next == kNoChild) {
          // Unseen context: park the rest of the chain as a tail.
          const int64_t tpos = (ctx > 0) ? ctx : kNoTail;
          next = new_node(tpos, (ctx > 0) ? sym : -1);
          child_at(node, ctx_sym) = next;
          count_at(next, sym)++;
          break;
        }
        node = next;
        count_at(node, sym)++;
      }
    }
  }

  // Total CTW weighted code length of everything appended so far, in bits.
  // Iterative post-order over the explicit child arrays.
  double weighted_code_length() const {
    const size_t n_nodes = tail_pos_.size();
    std::vector<double> weighted(n_nodes, 0.0);
    // frame: (node, child cursor). Children are scanned in symbol order.
    std::vector<std::pair<int32_t, int32_t>> stack;
    stack.reserve(64);
    stack.emplace_back(0, 0);
    while (!stack.empty()) {
      auto& frame = stack.back();
      const int32_t node = frame.first;
      bool descended = false;
      while (frame.second < k_) {
        const int32_t child = child_at(node, frame.second++);
        if (child != kNoChild) {
          stack.emplace_back(child, 0);
          descended = true;
          break;
        }
      }
      if (descended) continue;
      // All children done: combine.
      double le = local_code_length(node);
      double lc = 0.0;
      bool has_child = false;
      int64_t total = 0;
      for (int32_t s = 0; s < k_; ++s) {
        total += count_at(node, s);
        const int32_t child = child_at(node, s);
        if (child != kNoChild) {
          has_child = true;
          lc += weighted[static_cast<size_t>(child)];
        }
      }
      double w;
      if (has_child && total > 1) {
        w = 1.0 + std::min(le, lc) - std::log2(1.0 + std::exp2(-std::abs(le - lc)));
      } else {
        w = le;
      }
      weighted[static_cast<size_t>(node)] = w;
      stack.pop_back();
    }
    return weighted[0];
  }

  int64_t length() const { return static_cast<int64_t>(seq_.size()); }
  int64_t num_nodes() const { return static_cast<int64_t>(tail_pos_.size()); }

 private:
  int32_t new_node(int64_t tpos, int32_t tsym) {
    const int32_t id = static_cast<int32_t>(tail_pos_.size());
    tail_pos_.push_back(tpos);
    tail_sym_.push_back(tsym);
    counts_.resize(counts_.size() + static_cast<size_t>(k_), 0);
    children_.resize(children_.size() + static_cast<size_t>(k_), kNoChild);
    return id;
  }

  int64_t& count_at(int32_t node, int32_t sym) {
    return counts_[static_cast<size_t>(node) * k_ + sym];
  }
  int64_t count_at(int32_t node, int32_t sym) const {
    return counts_[static_cast<size_t>(node) * k_ + sym];
  }
  int32_t& child_at(int32_t node, int32_t sym) {
    return children_[static_cast<size_t>(node) * k_ + sym];
  }
  int32_t child_at(int32_t node, int32_t sym) const {
    return children_[static_cast<size_t>(node) * k_ + sym];
  }

  // KT local code length in bits.
  double local_code_length(int32_t node) const {
    int64_t total = 0;
    for (int32_t s = 0; s < k_; ++s) total += count_at(node, s);
    double le = std::lgamma(static_cast<double>(total) + k_ * kt_b_) -
                std::lgamma(k_ * kt_b_);
    for (int32_t s = 0; s < k_; ++s) {
      le -= std::lgamma(static_cast<double>(count_at(node, s)) + kt_b_) -
            std::lgamma(kt_b_);
    }
    return le / M_LN2;
  }

  const int32_t k_;
  const int32_t max_depth_;
  const double kt_b_;
  std::vector<int32_t> seq_;
  std::vector<int64_t> tail_pos_;
  std::vector<int32_t> tail_sym_;
  std::vector<int64_t> counts_;    // flat [node][symbol]
  std::vector<int32_t> children_;  // flat [node][symbol]
};

}  // namespace

extern "C" {

// One-shot: entropy-rate estimate (bits/symbol) of a whole sequence.
double dib_ctw_entropy(const int32_t* seq, int64_t n, int32_t alphabet_size,
                       int32_t max_depth) {
  if (n <= 0 || alphabet_size < 2) return 0.0;
  ContextTree tree(alphabet_size, max_depth);
  tree.append(seq, n);
  return tree.weighted_code_length() / static_cast<double>(n);
}

// Streaming handle API (incremental growth across calls).
void* dib_ctw_new(int32_t alphabet_size, int32_t max_depth) {
  if (alphabet_size < 2) return nullptr;
  return new ContextTree(alphabet_size, max_depth);
}

void dib_ctw_free(void* handle) { delete static_cast<ContextTree*>(handle); }

void dib_ctw_append(void* handle, const int32_t* seq, int64_t n) {
  static_cast<ContextTree*>(handle)->append(seq, n);
}

double dib_ctw_code_length(void* handle) {
  return static_cast<ContextTree*>(handle)->weighted_code_length();
}

int64_t dib_ctw_length(void* handle) {
  return static_cast<ContextTree*>(handle)->length();
}

int64_t dib_ctw_num_nodes(void* handle) {
  return static_cast<ContextTree*>(handle)->num_nodes();
}

}  // extern "C"
