"""Always-on DIB: the streaming train-to-serve control plane.

Composes the pieces the earlier PRs proved separately — chunk-aligned
resumable training (PR 8's scheduler idioms), a model zoo with
reload-exact cache invalidation (PR 10), journal-style durability
(``dib_tpu/sched/journal.py``) — into one live loop: a trainer that
learns continuously from a stream and publishes checkpoints atomically,
and a deployer that tails the publish journal and hot-swaps the serving
fleet under live traffic. See docs/streaming.md.
"""

from dib_tpu.stream.deployer import (
    DEPLOYS_FILENAME,
    CanaryFailure,
    Deployer,
    read_deploys,
    stream_status,
)
from dib_tpu.stream.online import (
    PUBLISHES_FILENAME,
    OnlineConfig,
    OnlineDIBTrainer,
    publishes_path,
    read_publishes,
)
from dib_tpu.stream.source import (
    DriftSpec,
    ReservoirSource,
    RowStream,
    SlidingWindowSource,
    make_source,
    parse_drift_specs,
)

__all__ = [
    "CanaryFailure",
    "DEPLOYS_FILENAME",
    "Deployer",
    "DriftSpec",
    "OnlineConfig",
    "OnlineDIBTrainer",
    "PUBLISHES_FILENAME",
    "ReservoirSource",
    "RowStream",
    "SlidingWindowSource",
    "make_source",
    "parse_drift_specs",
    "publishes_path",
    "read_deploys",
    "read_publishes",
    "stream_status",
]
