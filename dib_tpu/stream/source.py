"""Streaming batch sources over the dataset loaders.

An always-on DIB deployment (docs/streaming.md) trains on a *stream*,
not a fixed array: rows arrive forever, the trainer sees a bounded
working set, and a preempted trainer must resume the EXACT stream
position it died at. This module turns any ``DatasetBundle``'s arrays
into that stream:

  - :class:`RowStream` — a deterministic infinite row sequence over the
    bundle's ``(x_train, y_train)``: global row index ``i`` maps to a
    PRNG-permuted pass over the data (a fresh permutation per epoch-sized
    block, derived from ``(seed, block)`` — no mutable RNG state to
    snapshot), with a scripted :class:`DriftSpec` schedule applied as a
    pure function of the index. Same ``(seed, drift, i)`` → same row,
    always — the property every resumability claim below reduces to.
  - :class:`SlidingWindowSource` — the trainer's working set is the last
    ``window`` rows; ``advance()`` slides it by ``stride``. State is ONE
    integer (the stream offset).
  - :class:`ReservoirSource` — classic reservoir sampling (capacity-sized
    uniform sample over everything seen so far); per-row accept/replace
    decisions derive from ``(seed, index)``, so state is the count plus
    the reservoir's row INDICES — snapshot/restore is exact, and a
    resumed source is bit-identical to one that never stopped
    (``tests/test_stream.py``).
  - :class:`DriftSpec` — the scripted drift injector the chaos suite and
    the drift-detection tests drive: from global row ``at`` onward the
    feature distribution shifts (``mean_shift``) or stretches
    (``scale``). Scripted means deterministic: replaying the stream
    replays the drift.

Sources expose the same surface: ``window() -> (x, y)``, ``advance()``,
``snapshot() -> dict`` / ``restore(state)``, so ``stream/online.py``
treats them interchangeably.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = ["DriftSpec", "ReservoirSource", "RowStream",
           "SlidingWindowSource", "make_source", "parse_drift_specs"]


@dataclass(frozen=True)
class DriftSpec:
    """One scripted distribution shift: rows with global index >= ``at``
    are transformed. ``mean_shift`` adds ``magnitude`` to every feature;
    ``scale`` multiplies features by ``1 + magnitude``. Specs stack (a
    second spec compounds on the first)."""

    at: int
    kind: str = "mean_shift"
    magnitude: float = 1.0

    def __post_init__(self):
        if self.kind not in ("mean_shift", "scale"):
            raise ValueError(
                f"unknown drift kind {self.kind!r} "
                "(expected 'mean_shift' or 'scale')")
        if self.at < 0:
            raise ValueError(f"drift 'at' must be >= 0, got {self.at}")


def parse_drift_specs(pairs) -> tuple[DriftSpec, ...]:
    """CLI spelling ``AT[:KIND[:MAGNITUDE]]`` (repeatable) → specs."""
    specs = []
    for pair in pairs or ():
        parts = str(pair).split(":")
        at = int(parts[0])
        kind = parts[1] if len(parts) > 1 and parts[1] else "mean_shift"
        magnitude = float(parts[2]) if len(parts) > 2 else 1.0
        specs.append(DriftSpec(at=at, kind=kind, magnitude=magnitude))
    return tuple(sorted(specs, key=lambda s: s.at))


class RowStream:
    """Deterministic infinite row stream over fixed ``(x, y)`` arrays.

    Global index ``i`` lives in pass (block) ``i // n`` at position
    ``i % n``; each block's permutation derives from ``(seed, block)``
    via a fresh ``np.random.default_rng`` — stateless, so arbitrary
    index sets (:meth:`take`) are as cheap as sequential reads and a
    resumed consumer needs no RNG snapshot. ``shuffle=False`` streams
    the data in storage order (time-ordered datasets)."""

    def __init__(self, x, y, seed: int = 0, drift=(), shuffle: bool = True):
        self.x = np.asarray(x)
        self.y = np.asarray(y)
        if self.x.shape[0] != self.y.shape[0]:
            raise ValueError(
                f"x has {self.x.shape[0]} rows but y has {self.y.shape[0]}")
        if self.x.shape[0] == 0:
            raise ValueError("cannot stream an empty dataset")
        self.n = self.x.shape[0]
        self.seed = int(seed)
        self.shuffle = bool(shuffle)
        self.drift = tuple(sorted(drift, key=lambda s: s.at))
        self._perm_cache: dict[int, np.ndarray] = {}

    def _perm(self, block: int) -> np.ndarray:
        perm = self._perm_cache.get(block)
        if perm is None:
            if self.shuffle:
                perm = np.random.default_rng(
                    [self.seed, int(block)]).permutation(self.n)
            else:
                perm = np.arange(self.n)
            # keep only a handful of passes hot, evicting ONE oldest
            # entry (insertion order) — clearing the whole cache would
            # make a reservoir window spanning >4 blocks rebuild every
            # block's permutation on every take()
            if len(self._perm_cache) > 4:
                self._perm_cache.pop(next(iter(self._perm_cache)))
            self._perm_cache[block] = perm
        return perm

    def take(self, indices) -> tuple[np.ndarray, np.ndarray]:
        """Rows for arbitrary GLOBAL indices (drift applied per row at its
        own index — a reservoir holding pre-drift rows keeps them
        pre-drift)."""
        gidx = np.asarray(list(indices), dtype=np.int64)
        rows = np.empty(gidx.shape[0], dtype=np.int64)
        # one _perm lookup per DISTINCT block, not per row: reservoir
        # windows interleave blocks, and per-row lookups would turn each
        # cache miss into a full permutation rebuild
        blocks = gidx // self.n
        offsets = gidx % self.n
        for block in np.unique(blocks):
            sel = blocks == block
            rows[sel] = self._perm(int(block))[offsets[sel]]
        x = np.array(self.x[rows], copy=True)
        y = np.array(self.y[rows], copy=True)
        for spec in self.drift:
            mask = gidx >= spec.at
            if not mask.any():
                continue
            if spec.kind == "mean_shift":
                x[mask] = x[mask] + spec.magnitude
            else:   # scale
                x[mask] = x[mask] * (1.0 + spec.magnitude)
        return x, y

    def rows(self, start: int, count: int) -> tuple[np.ndarray, np.ndarray]:
        """``count`` consecutive rows starting at global index ``start``."""
        return self.take(range(start, start + count))


class SlidingWindowSource:
    """Working set = the most recent ``window`` rows of the stream.

    ``advance()`` slides by ``stride`` rows. The whole state is one
    integer offset, so ``snapshot()``/``restore()`` are trivially exact
    and the restored window is bit-identical (the stream itself is a
    pure function of the index)."""

    kind = "sliding"

    def __init__(self, stream: RowStream, window: int, stride: int | None = None):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.stream = stream
        self.window_size = int(window)
        self.stride = int(stride) if stride else max(window // 2, 1)
        if self.stride < 1:
            raise ValueError(f"stride must be >= 1, got {stride}")
        self.offset = self.window_size   # prefilled: rows [0, window)

    def window(self) -> tuple[np.ndarray, np.ndarray]:
        return self.stream.rows(self.offset - self.window_size,
                                self.window_size)

    def advance(self) -> None:
        self.offset += self.stride

    @property
    def rows_consumed(self) -> int:
        return self.offset

    def snapshot(self) -> dict:
        return {"kind": self.kind, "offset": int(self.offset)}

    def restore(self, state: dict) -> None:
        if state.get("kind") != self.kind:
            raise ValueError(
                f"source state kind {state.get('kind')!r} does not match "
                f"this {self.kind!r} source — the resumed run was "
                "configured with a different --stream-source")
        self.offset = int(state["offset"])


class ReservoirSource:
    """Capacity-bounded uniform sample over everything seen so far
    (Vitter's algorithm R). Each arriving row ``i >= capacity`` replaces
    slot ``j ~ U[0, i]`` when ``j < capacity``; ``j`` derives from
    ``(seed, i)``, so the decision sequence is a pure function of the
    stream position and the snapshot is just ``(count, indices)``."""

    kind = "reservoir"

    def __init__(self, stream: RowStream, window: int, stride: int | None = None):
        if window < 1:
            raise ValueError(f"window (capacity) must be >= 1, got {window}")
        self.stream = stream
        self.window_size = int(window)
        self.stride = int(stride) if stride else max(window // 2, 1)
        if self.stride < 1:
            raise ValueError(f"stride must be >= 1, got {stride}")
        # prefill: the first `capacity` rows fill the reservoir directly
        self.count = self.window_size
        self.indices = list(range(self.window_size))

    def window(self) -> tuple[np.ndarray, np.ndarray]:
        return self.stream.take(self.indices)

    def advance(self) -> None:
        for i in range(self.count, self.count + self.stride):
            j = int(np.random.default_rng(
                [self.stream.seed, 7919, i]).integers(0, i + 1))
            if j < self.window_size:
                self.indices[j] = i
        self.count += self.stride

    @property
    def rows_consumed(self) -> int:
        return self.count

    def snapshot(self) -> dict:
        return {"kind": self.kind, "count": int(self.count),
                "indices": [int(i) for i in self.indices]}

    def restore(self, state: dict) -> None:
        if state.get("kind") != self.kind:
            raise ValueError(
                f"source state kind {state.get('kind')!r} does not match "
                f"this {self.kind!r} source — the resumed run was "
                "configured with a different --stream-source")
        self.count = int(state["count"])
        self.indices = [int(i) for i in state["indices"]]
        if len(self.indices) != self.window_size:
            raise ValueError(
                f"restored reservoir holds {len(self.indices)} indices "
                f"but this source's capacity is {self.window_size} — the "
                "resumed run was configured with a different --window")


def make_source(kind: str, stream: RowStream, window: int,
                stride: int | None = None):
    """Factory for the CLI's ``--stream-source`` flag."""
    if kind == "sliding":
        return SlidingWindowSource(stream, window, stride)
    if kind == "reservoir":
        return ReservoirSource(stream, window, stride)
    raise ValueError(
        f"unknown source kind {kind!r} (expected 'sliding' or 'reservoir')")
