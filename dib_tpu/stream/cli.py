"""``python -m dib_tpu stream run|deploy|autopilot|status`` — the
always-on loop.

``run`` trains continuously on a stream over the named dataset and
publishes chunk-aligned checkpoints through the atomic publish protocol
(``stream/online.py``); ``deploy`` serves the fleet and hot-swaps each
published checkpoint in via canary-gated ``ModelZoo.reload``
(``stream/deployer.py``); ``status`` replays both journals into a
snapshot. Trainer and deployer run as SEPARATE processes sharing only
``<stream-dir>/publishes.jsonl`` — each side optionally supervised
(``--watchdog``) with journal-record progress gating its budget-free
preemption relaunches, exactly like the PR 8 scheduler pool
(docs/streaming.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Sequence

__all__ = ["stream_main"]


def _add_stream_dir(parser) -> None:
    parser.add_argument("--stream-dir", "--stream_dir", dest="stream_dir",
                        required=True,
                        help="Stream directory: publishes.jsonl plus the "
                             "staging/ and checkpoints/ trees the publish "
                             "protocol writes.")


def _add_watchdog(parser, what: str) -> None:
    parser.add_argument("--watchdog", action="store_true",
                        help=f"Supervise this {what} (train/watchdog.py "
                             "supervise_pool): crashes relaunch with "
                             "backoff against a restart budget; rc-75 "
                             "preemptions relaunch immediately and "
                             "budget-free while journal records keep "
                             "landing.")
    parser.add_argument("--max-restarts", type=int, default=3,
                        dest="max_restarts")


def _add_trace_id(parser) -> None:
    parser.add_argument("--trace-id", "--trace_id", dest="trace_id",
                        default=None,
                        help="Cross-plane trace id this run's records "
                             "carry (docs/observability.md 'Fleet "
                             "causality'; default: inherit DIB_TRACE_ID "
                             "or mint a fresh one).")


def build_stream_parser() -> argparse.ArgumentParser:
    from dib_tpu.cli import _add_model_flags, _add_telemetry_dir_flag

    parser = argparse.ArgumentParser(
        prog="dib_tpu stream",
        description="Always-on DIB: streaming train-to-serve control "
                    "plane (docs/streaming.md).",
    )
    sub = parser.add_subparsers(dest="action", required=True)

    p_run = sub.add_parser(
        "run", help="Train continuously on a stream; publish chunk-"
                    "aligned checkpoints on a cadence.")
    _add_stream_dir(p_run)
    _add_model_flags(p_run)
    p_run.add_argument("--window", type=int, default=256,
                       help="Working-set rows per round (>= batch_size).")
    p_run.add_argument("--stride", type=int, default=0,
                       help="Fresh rows consumed per round "
                            "(default: window // 2).")
    p_run.add_argument("--chunk-epochs", type=int, default=2,
                       dest="chunk_epochs",
                       help="Epochs per jitted chunk (= one round; the "
                            "checkpoint chunk-size contract).")
    p_run.add_argument("--publish-every", type=int, default=1,
                       dest="publish_every",
                       help="Publish a checkpoint every N rounds.")
    p_run.add_argument("--keep-publishes", type=int, default=0,
                       dest="keep_publishes",
                       help="Retain only the newest N published checkpoint "
                            "dirs on disk (0 = keep all). The journals "
                            "always keep every record; set this on "
                            "always-on streams so the disk stays bounded.")
    p_run.add_argument("--rounds", type=int, default=8,
                       help="Rounds this invocation runs (resume "
                            "continues the count from the journal).")
    p_run.add_argument("--stream-source", default="sliding",
                       dest="stream_source",
                       choices=["sliding", "reservoir"],
                       help="Working-set policy over the stream.")
    p_run.add_argument("--drift", action="append", default=[],
                       metavar="AT[:KIND[:MAGNITUDE]]",
                       help="Scripted drift injection (repeatable), e.g. "
                            "--drift 512:mean_shift:2.0 (tests/chaos).")
    p_run.add_argument("--drift-threshold", type=float, default=1.0,
                       dest="drift_threshold",
                       help="Window-mean shift (baseline-σ units) that "
                            "counts as drift.")
    p_run.add_argument("--reanneal", action=argparse.BooleanOptionalAction,
                       default=True,
                       help="Re-anneal β from the anneal start on "
                            "detected drift (--no-reanneal holds β).")
    p_run.add_argument("--learning_rate", type=float, default=3e-4)
    p_run.add_argument("--batch_size", type=int, default=64)
    p_run.add_argument("--beta_start", type=float, default=1e-4)
    p_run.add_argument("--beta_end", type=float, default=3e0)
    p_run.add_argument("--number_pretraining_epochs", type=int, default=4)
    p_run.add_argument("--number_annealing_epochs", type=int, default=12)
    p_run.add_argument("--optimizer", type=str, default="adam")
    p_run.add_argument("--preempt_grace_s", type=float, default=30.0,
                       help="SIGTERM/SIGINT grace: the round finishes, a "
                            "final checkpoint publishes, and the process "
                            "exits with the preemption code (75). "
                            "0 disables.")
    _add_watchdog(p_run, "trainer")
    _add_trace_id(p_run)
    _add_telemetry_dir_flag(p_run, "--stream-dir")

    p_dep = sub.add_parser(
        "deploy", help="Serve the fleet; tail the publish journal and "
                       "hot-swap each new checkpoint in (canary-gated).")
    _add_stream_dir(p_dep)
    _add_model_flags(p_dep)
    p_dep.add_argument("--deploy-dir", "--deploy_dir", dest="deploy_dir",
                       required=True,
                       help="Deployer run directory: deploys.jsonl, the "
                            "serving event stream.")
    p_dep.add_argument("--model_name", type=str, default="stream",
                       help="Zoo name the published checkpoints serve "
                            "under.")
    p_dep.add_argument("--batch_size", type=int, default=64)
    p_dep.add_argument("--beta_start", type=float, default=1e-4)
    p_dep.add_argument("--beta_end", type=float, default=3e0)
    p_dep.add_argument("--number_pretraining_epochs", type=int, default=4)
    p_dep.add_argument("--number_annealing_epochs", type=int, default=12)
    p_dep.add_argument("--optimizer", type=str, default="adam")
    p_dep.add_argument("--host", type=str, default="127.0.0.1")
    p_dep.add_argument("--port", type=int, default=0,
                       help="0 binds an ephemeral port (printed on "
                            "stdout).")
    p_dep.add_argument("--buckets", type=int, nargs="+", default=[1, 8, 32],
                       help="Padded batch sizes to compile.")
    p_dep.add_argument("--max_batch", type=int, default=32)
    p_dep.add_argument("--max_wait_ms", type=float, default=2.0)
    p_dep.add_argument("--poll-s", type=float, default=0.25, dest="poll_s",
                       help="Publish-journal tail interval.")
    p_dep.add_argument("--wait-first-s", type=float, default=60.0,
                       dest="wait_first_s",
                       help="How long to wait for the FIRST publish "
                            "before serving starts (the fleet needs one "
                            "checkpoint to answer at all).")
    p_dep.add_argument("--serve_seconds", type=float, default=0.0,
                       help="Auto-shutdown after this many seconds "
                            "(0 = run until SIGINT/SIGTERM).")
    p_dep.add_argument("--response_cache", type=int, default=64,
                       help="Response-cache capacity (0 disables); "
                            "reloads invalidate exactly the swapped "
                            "model's entries.")
    p_dep.add_argument("--exec_cache", type=int, default=16,
                       help="Shared AOT-executable LRU capacity "
                            "(0 = eager per-engine compilation).")
    _add_watchdog(p_dep, "deployer")
    _add_trace_id(p_dep)
    _add_telemetry_dir_flag(p_dep, "--deploy-dir")

    p_auto = sub.add_parser(
        "autopilot", help="Close the loop: tail the stream's drift "
                          "events, mint a targeted mini-study per drift, "
                          "and apply the refreshed transition-β estimates "
                          "back as the re-anneal schedule + zoo routing "
                          "metadata (crash-safe, poison-proof, "
                          "circuit-broken; docs/streaming.md).")
    _add_stream_dir(p_auto)
    p_auto.add_argument("--autopilot-dir", "--autopilot_dir",
                        dest="autopilot_dir", default=None,
                        help="Supervisor state dir: autopilot.jsonl + the "
                             "studies/ tree (default: "
                             "<stream-dir>/autopilot).")
    p_auto.add_argument("--duration-s", type=float, default=0.0,
                        dest="duration_s",
                        help="Tail this long (0 = one catch-up pass over "
                             "the drift backlog, then exit).")
    p_auto.add_argument("--poll-s", type=float, default=2.0, dest="poll_s",
                        help="Drift-journal tail interval.")
    p_auto.add_argument("--cooldown-rounds", type=int, default=None,
                        dest="cooldown_rounds",
                        help="Debounce: rounds a new drift must clear "
                             "past the last study before it may seed "
                             "another (default 4).")
    p_auto.add_argument("--breaker-threshold", type=int, default=None,
                        dest="breaker_threshold",
                        help="Consecutive failed/unconverged drift "
                             "studies that trip the circuit breaker "
                             "(default 3).")
    p_auto.add_argument("--breaker-probe-after", type=int, default=None,
                        dest="breaker_probe_after",
                        help="Half-open: after this many breaker-skipped "
                             "drifts, let ONE probe study through "
                             "(default 0 = operator reset only).")
    p_auto.add_argument("--margin-decades", type=float, default=None,
                        dest="margin_decades",
                        help="Re-anneal floor margin below the lowest "
                             "refreshed transition β (default 0.25).")
    p_auto.add_argument("--watch-wait-s", type=float, default=None,
                        dest="watch_wait_s",
                        help="Follow the live stream this long when "
                             "harvesting study centers (default 0: one "
                             "poll).")
    p_auto.add_argument("--study-set", action="append", default=[],
                        dest="study_set", metavar="FIELD=VALUE",
                        help="Mini-study config override (repeatable), "
                             "e.g. --study-set max_units=20 "
                             "--study-set max_rounds=3; max_units IS the "
                             "per-drift budget cap.")
    p_auto.add_argument("--workers", type=int, default=2,
                        help="Pool workers draining each study round "
                             "(ignored with --fleet).")
    p_auto.add_argument("--fleet", default=None,
                        help="Submit drift studies to this external "
                             "scheduler directory (a long-lived 'sched "
                             "run-pool --serve' fleet) instead of "
                             "draining them in-process "
                             "(docs/scheduling.md).")
    p_auto.add_argument("--tenant", default="autopilot",
                        help="Fair-share tenant the fleet-submitted "
                             "studies bill to (default 'autopilot').")
    p_auto.add_argument("--priority", type=int, default=0,
                        help="Fleet job priority for drift studies "
                             "(lower parks first under load shedding).")
    p_auto.add_argument("--reset-breaker", action="store_true",
                        dest="reset_breaker",
                        help="Operator reset: durably close a tripped "
                             "breaker before tailing.")
    p_auto.add_argument("--reconfigure", action="store_true",
                        help="Journal the flags' config even when a "
                             "config record already exists (last record "
                             "wins on replay).")
    _add_trace_id(p_auto)
    _add_telemetry_dir_flag(p_auto, "--autopilot-dir")

    p_stat = sub.add_parser(
        "status", help="Replay the publish/deploy journals into a "
                       "snapshot.")
    _add_stream_dir(p_stat)
    p_stat.add_argument("--deploy-dir", "--deploy_dir", dest="deploy_dir",
                        default=None,
                        help="Also fold this deployer's deploys.jsonl "
                             "(promotion/rollback/lag view).")
    p_stat.add_argument("--autopilot-dir", "--autopilot_dir",
                        dest="autopilot_dir", default=None,
                        help="Also fold this autopilot's journal (drift-"
                             "study/breaker/applied-schedule view).")
    p_stat.add_argument("--json", action="store_true",
                        help="Machine-readable snapshot.")
    return parser


def _supervised(args, argv: Sequence[str], journal_file: str,
                terminal_kind: str, run_dir: str) -> int:
    """Re-exec this stream command as a supervised worker process: the
    publish/deploy journal makes a relaunch resume exactly, so progress
    is journal records of the terminal kind (the sched run-pool idiom)."""
    from dib_tpu.telemetry import open_writer, shared_run_id
    from dib_tpu.telemetry.context import ensure_context
    from dib_tpu.train.watchdog import WatchdogConfig, supervise_pool

    run_id = shared_run_id()
    os.environ["DIB_TELEMETRY_RUN_ID"] = run_id
    # pin the causal lineage next to the run id so watchdog relaunches
    # of the worker process inherit the same trace_id
    ctx = ensure_context("stream", trace_id=getattr(args, "trace_id", None))
    ctx.activate()
    telemetry = open_writer(args.telemetry_dir, run_dir,
                            run_id=run_id, process_index=0,
                            tags={"src": "supervisor"}, ctx=ctx)
    # strip only the FIRST token spelling the flag — argparse accepts
    # unambiguous prefixes, and option values can never start with "--"
    # (the sched run-pool idiom, regression-tested there)
    worker = list(argv)
    for i, token in enumerate(worker):
        if token.startswith("--wa") and "--watchdog".startswith(token):
            del worker[i]
            break
    result = supervise_pool(
        [sys.executable, "-m", "dib_tpu.cli", "stream", *worker],
        config=WatchdogConfig(max_restarts=args.max_restarts),
        telemetry=telemetry,
        journal_path=os.path.join(run_dir, journal_file),
        terminal_kinds=(terminal_kind,),
    )
    if telemetry is not None:
        telemetry.close()
    print(json.dumps({"watchdog": result}))
    return 0 if result["returncode"] == 0 else 1


def _run_main(args, argv: Sequence[str]) -> int:
    from dib_tpu.stream.online import PUBLISHES_FILENAME

    if args.watchdog:
        return _supervised(args, argv, PUBLISHES_FILENAME, "publish",
                           args.stream_dir)

    from dib_tpu.cli import (
        _bundle_from_args,
        _enable_cli_compile_cache,
        _model_from_args,
    )

    _enable_cli_compile_cache()

    import jax

    from dib_tpu.stream.online import OnlineConfig, OnlineDIBTrainer
    from dib_tpu.stream.source import parse_drift_specs
    from dib_tpu.telemetry import open_writer, runtime_manifest, shared_run_id
    from dib_tpu.train import TrainConfig
    from dib_tpu.train.preempt import (
        PREEMPT_EXIT_CODE,
        PreemptionGuard,
        TrainingPreempted,
    )

    bundle = _bundle_from_args(args)
    model, y_encoder = _model_from_args(args, bundle)
    config = TrainConfig(
        learning_rate=args.learning_rate,
        batch_size=args.batch_size,
        beta_start=args.beta_start,
        beta_end=args.beta_end,
        num_pretraining_epochs=args.number_pretraining_epochs,
        num_annealing_epochs=args.number_annealing_epochs,
        optimizer=args.optimizer,
        infonce_similarity=args.infonce_similarity
        if hasattr(args, "infonce_similarity") else "l2",
    )
    online = OnlineConfig(
        window=args.window,
        stride=args.stride or None,
        chunk_epochs=args.chunk_epochs,
        publish_every=args.publish_every,
        rounds=args.rounds,
        source=args.stream_source,
        seed=args.seed,
        drift=parse_drift_specs(args.drift),
        drift_threshold=args.drift_threshold,
        reanneal_on_drift=args.reanneal,
        keep_publishes=args.keep_publishes,
    )
    os.makedirs(args.stream_dir, exist_ok=True)
    from dib_tpu.telemetry.context import ensure_context

    ctx = ensure_context("stream", trace_id=args.trace_id)
    ctx.activate()
    telemetry = open_writer(args.telemetry_dir, args.stream_dir,
                            run_id=shared_run_id(),
                            process_index=jax.process_index(), ctx=ctx)
    if telemetry is not None:
        telemetry.run_start(runtime_manifest(config=config, extra={
            "mode": "stream_run", "dataset": args.dataset,
            "stream_dir": os.path.abspath(args.stream_dir),
            "window": online.window, "stride": online.stride,
            "chunk_epochs": online.chunk_epochs,
            "publish_every": online.publish_every,
            "source": online.source,
        }))
    guard = None
    if args.preempt_grace_s and args.preempt_grace_s > 0:

        def _grace_flush():
            if telemetry is not None:
                telemetry.run_end(status="preempted", aborted_chunk=True)
                telemetry.close()

        guard = PreemptionGuard(args.preempt_grace_s,
                                on_grace_expired=_grace_flush)

    online_trainer = OnlineDIBTrainer(
        model, bundle, config, online, args.stream_dir,
        telemetry=telemetry, y_encoder=y_encoder)
    key = jax.random.key(args.seed)
    try:
        if guard is not None:
            with guard:
                summary = online_trainer.run(key, preempt=guard)
        else:
            summary = online_trainer.run(key)
    except TrainingPreempted:
        if telemetry is not None:
            telemetry.run_end(status="preempted")
            telemetry.close()
        print(json.dumps({"status": "preempted",
                          "publishes": online_trainer.publishes}))
        return PREEMPT_EXIT_CODE
    summary["status"] = "ok"
    if telemetry is not None:
        telemetry.run_end(status="ok", epoch=summary["epochs"])
        telemetry.close()
        _maybe_register(args, telemetry)
    print(json.dumps(summary))
    return 0


def _maybe_register(args, telemetry) -> None:
    root = args.runs_root or os.environ.get("DIB_RUNS_ROOT")
    if root:
        from dib_tpu.telemetry.registry import register_run

        register_run(os.path.dirname(telemetry.path), root=root)


def _deploy_main(args, argv: Sequence[str]) -> int:
    from dib_tpu.stream.deployer import DEPLOYS_FILENAME

    if args.watchdog:
        return _supervised(args, argv, DEPLOYS_FILENAME, "deploy",
                           args.deploy_dir)

    from dib_tpu.cli import (
        _bundle_from_args,
        _enable_cli_compile_cache,
        _model_from_args,
    )

    _enable_cli_compile_cache()

    import threading
    import time

    import jax

    from dib_tpu.serve import DIBServer, ModelZoo
    from dib_tpu.stream.deployer import Deployer
    from dib_tpu.stream.online import read_publishes
    from dib_tpu.telemetry import (
        MetricsRegistry,
        Tracer,
        open_writer,
        runtime_manifest,
        shared_run_id,
    )
    from dib_tpu.train import DIBTrainer, TrainConfig

    bundle = _bundle_from_args(args)
    model, y_encoder = _model_from_args(args, bundle)
    config = TrainConfig(
        batch_size=args.batch_size,
        beta_start=args.beta_start,
        beta_end=args.beta_end,
        num_pretraining_epochs=args.number_pretraining_epochs,
        num_annealing_epochs=args.number_annealing_epochs,
        optimizer=args.optimizer,
    )
    trainer = DIBTrainer(model, bundle, config, y_encoder=y_encoder)

    os.makedirs(args.deploy_dir, exist_ok=True)
    from dib_tpu.telemetry.context import ensure_context

    ctx = ensure_context("deploy", trace_id=args.trace_id)
    ctx.activate()
    telemetry = open_writer(args.telemetry_dir, args.deploy_dir,
                            run_id=shared_run_id(),
                            process_index=jax.process_index(), ctx=ctx)
    registry = MetricsRegistry()
    tracer = Tracer(telemetry)
    if telemetry is not None:
        telemetry.run_start(runtime_manifest(config=config, extra={
            "mode": "stream_deploy", "dataset": args.dataset,
            "stream_dir": os.path.abspath(args.stream_dir),
            "deploy_dir": os.path.abspath(args.deploy_dir),
            "model_name": args.model_name,
            "poll_s": args.poll_s,
        }))

    zoo = ModelZoo(
        exec_capacity=args.exec_cache or None,
        response_capacity=args.response_cache or None,
        telemetry=telemetry, registry=registry,
    )
    deployer = Deployer(
        args.stream_dir, args.deploy_dir, trainer, zoo,
        model_name=args.model_name, telemetry=telemetry,
        registry=registry, poll_s=args.poll_s,
        router_kwargs=dict(
            batch_buckets=args.buckets, telemetry=telemetry,
            tracer=tracer, max_batch=args.max_batch,
            max_wait_ms=args.max_wait_ms,
        ))

    # the fleet needs one promoted checkpoint before it can answer —
    # wait for the trainer's first publish (they only share the journal)
    deadline = time.monotonic() + args.wait_first_s
    while not read_publishes(args.stream_dir)[0]:
        if time.monotonic() >= deadline:
            print(json.dumps({
                "error": f"no publish within {args.wait_first_s}s in "
                         f"{args.stream_dir} — is `stream run` up?"}),
                file=sys.stderr)
            if telemetry is not None:
                telemetry.run_end(status="error", error="no_publish")
                telemetry.close()
            deployer.close()
            return 1
        time.sleep(min(args.poll_s, 0.2))
    deployer.catch_up()

    server = DIBServer(zoo, host=args.host, port=args.port,
                       telemetry=telemetry, registry=registry,
                       tracer=tracer)
    server.start()
    deployer.start()
    print(json.dumps({
        "serving": server.url, "port": server.port,
        "model": args.model_name, "run_dir": args.deploy_dir,
        **deployer.status(),
    }), flush=True)

    stop = threading.Event()
    if threading.current_thread() is threading.main_thread():
        import signal

        for signum in (signal.SIGINT, signal.SIGTERM):
            signal.signal(signum, lambda *_: stop.set())
    try:
        if args.serve_seconds > 0:
            stop.wait(args.serve_seconds)
        else:
            stop.wait()
    finally:
        deployer.close()
        server.close()
    if telemetry is not None:
        _maybe_register(args, telemetry)
    print(json.dumps(deployer.status()), flush=True)
    return 0


def _autopilot_main(args) -> int:
    from dib_tpu.autopilot import AutopilotConfig, DriftAutopilot
    from dib_tpu.cli import _parse_sets
    from dib_tpu.telemetry import open_writer, runtime_manifest, shared_run_id
    from dib_tpu.telemetry.context import ensure_context

    autopilot_dir = args.autopilot_dir or os.path.join(
        args.stream_dir, "autopilot")
    os.makedirs(autopilot_dir, exist_ok=True)
    kw: dict = {}
    for name in ("cooldown_rounds", "breaker_threshold",
                 "breaker_probe_after", "margin_decades", "watch_wait_s"):
        value = getattr(args, name)
        if value is not None:
            kw[name] = value
    study = _parse_sets(args.study_set)
    if study:
        kw["study"] = study
    config = AutopilotConfig(**kw) if kw else None

    ctx = ensure_context("autopilot", trace_id=args.trace_id)
    ctx.activate()
    telemetry = open_writer(args.telemetry_dir, autopilot_dir,
                            run_id=shared_run_id(), process_index=0, ctx=ctx)
    if telemetry is not None:
        telemetry.run_start(runtime_manifest(device_info=False, extra={
            "mode": "autopilot",
            "stream_dir": os.path.abspath(args.stream_dir),
            "autopilot_dir": os.path.abspath(autopilot_dir),
        }))
    pilot = DriftAutopilot(args.stream_dir, autopilot_dir, config=config,
                           telemetry=telemetry, ctx=ctx,
                           workers=args.workers, fleet=args.fleet,
                           tenant=args.tenant, priority=args.priority)
    pilot.ensure_config(reconfigure=args.reconfigure)
    if args.reset_breaker:
        pilot.reset_breaker()
    # a tripped breaker is the DEGRADED-BUT-HEALTHY state (the stream
    # re-anneals on its fixed schedule), so the supervisor always exits
    # 0 — alerting is the telemetry plane's job, not the exit code's
    snapshot = pilot.run(duration_s=args.duration_s, poll_s=args.poll_s)
    snapshot["trace_id"] = ctx.trace_id
    if telemetry is not None:
        telemetry.run_end(status="ok")
        telemetry.close()
        _maybe_register(args, telemetry)
    print(json.dumps(snapshot))
    return 0


def _status_main(args) -> int:
    from dib_tpu.stream.deployer import stream_status

    snapshot = stream_status(args.stream_dir, args.deploy_dir)
    if args.autopilot_dir:
        from dib_tpu.autopilot import autopilot_status

        snapshot["autopilot"] = autopilot_status(args.autopilot_dir)
    if args.json:
        print(json.dumps(snapshot, indent=1))
        return 0
    print(f"publishes: {snapshot['publishes']}"
          + (f"  (latest {snapshot['latest_publish']})"
             if snapshot["latest_publish"] else ""))
    if "deploys" in snapshot:
        print(f"deploys: {snapshot['deploys']} "
              f"({snapshot['promoted']} promoted / "
              f"{snapshot['rollbacks']} rolled back / "
              f"{snapshot['pending']} pending)")
        print(f"invariants: lost={snapshot['lost_publishes']} "
              f"double={snapshot['double_promotions']}")
    if "reanneal" in snapshot:
        re = snapshot["reanneal"]
        print(f"reanneal: floor β={re['beta_floor']} "
              f"(drift round {re['drift_round']}, {re['study_id']})")
    if "autopilot" in snapshot:
        auto = snapshot["autopilot"]
        brk = auto["breaker"]
        print(f"autopilot: {auto['drifts_decided']} drifts decided "
              f"({auto['studies']} studied / {auto['applied']} applied / "
              f"{auto['skipped']} skipped)")
        print(f"breaker: {'OPEN' if brk['open'] else 'closed'} "
              f"(trips={brk['trips']} resets={brk['resets']} "
              f"consecutive={brk['consecutive']})")
    return 0


def stream_main(argv: Sequence[str]) -> int:
    argv = list(argv)
    args = build_stream_parser().parse_args(argv)
    if args.action == "status":
        return _status_main(args)
    if args.action == "autopilot":
        return _autopilot_main(args)
    # argv keeps the leading action token: the --watchdog path re-execs
    # `python -m dib_tpu.cli stream <argv minus --watchdog>` and the
    # worker's parser needs `run`/`deploy` back in first position
    if args.action == "run":
        return _run_main(args, argv)
    return _deploy_main(args, argv)
