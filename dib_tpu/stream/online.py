"""Online DIB training on a stream, publishing chunk-aligned checkpoints.

The trainer half of the always-on control plane (docs/streaming.md): a
``DIBTrainer`` driven window-by-window over a :mod:`dib_tpu.stream.source`
stream, β annealing exactly as in a batch run — and, past the anneal,
HOLDING at ``beta_end`` while the model tracks the moving window. On
detected drift (window feature means shifted beyond the threshold, in
baseline-σ units) β optionally RE-ANNEALS: the schedule epoch rewinds to
the anneal start so the model re-explores compression against the new
distribution, while the history cursor (and the published trajectory)
keeps counting forward.

Checkpoints publish on a cadence through the atomic protocol the
deployer's promotion safety rests on:

  1. save the full resume payload (state, history, next key, chunk size)
     to ``<stream-dir>/staging/<publish-id>``;
  2. fsync every staged file and directory;
  3. ``os.replace`` the staging dir to
     ``<stream-dir>/checkpoints/<publish-id>`` (atomic on POSIX);
  4. append ONE ``publish`` record to ``publishes.jsonl`` — the same
     O_APPEND torn-line-tolerant journal idiom as the PR 8 scheduler
     (:class:`dib_tpu.sched.journal.JobJournal`, reused directly).

A trainer SIGKILLed anywhere in 1–3 leaves at most a torn staging dir or
an orphaned-but-complete checkpoint dir — never a publish record
pointing at torn bytes, so the deployer can never promote one. The
publish record carries the source snapshot, the drift baseline, and the
round counter, so a relaunched trainer resumes the EXACT stream position
and detector state — the continuation is bit-identical
(``tests/test_stream.py``).
"""

from __future__ import annotations

import dataclasses
import math
import os
import shutil
from dataclasses import dataclass

import numpy as np

from dib_tpu.sched.journal import JobJournal, read_journal
from dib_tpu.stream.source import DriftSpec, RowStream, make_source

__all__ = ["OnlineConfig", "OnlineDIBTrainer", "PUBLISHES_FILENAME",
           "REANNEAL_FILENAME", "load_reanneal_schedule", "publishes_path",
           "read_publishes", "reanneal_path", "reanneal_rewind_epoch"]

PUBLISHES_FILENAME = "publishes.jsonl"
CHECKPOINTS_DIRNAME = "checkpoints"
STAGING_DIRNAME = "staging"
REANNEAL_FILENAME = "reanneal.json"


def publishes_path(stream_dir: str) -> str:
    return os.path.join(stream_dir, PUBLISHES_FILENAME)


def reanneal_path(stream_dir: str) -> str:
    return os.path.join(stream_dir, REANNEAL_FILENAME)


def load_reanneal_schedule(stream_dir: str) -> dict | None:
    """The autopilot-applied re-anneal schedule, or None when the stream
    runs on its fixed schedule. Written atomically (tmp → fsync →
    rename, ``dib_tpu/autopilot``) so a reader never sees torn bytes;
    anything unreadable is treated as ABSENT — the fixed schedule is the
    safe degradation, never a crash."""
    import json

    try:
        with open(reanneal_path(stream_dir), encoding="utf-8") as fh:
            payload = json.load(fh)
    except (OSError, ValueError):
        return None
    return payload if isinstance(payload, dict) else None


def reanneal_rewind_epoch(schedule: dict, config) -> int:
    """The schedule epoch a drift re-anneal rewinds to under an applied
    schedule: the epoch where the β ramp sits at the schedule's
    ``beta_floor`` (just below the lowest refreshed transition-β), so
    the re-anneal re-explores every transition against the drifted
    distribution without replaying the decades below them. Inverse of
    :func:`dib_tpu.ops.schedules.log_annealed_beta`; clamps to the full
    re-anneal (the fixed behavior) whenever the floor is absent, out of
    range, or the ramp is degenerate."""
    pre = int(config.num_pretraining_epochs)
    ann = int(config.num_annealing_epochs)
    b0, b1 = float(config.beta_start), float(config.beta_end)
    floor = schedule.get("beta_floor")
    if (not isinstance(floor, (int, float)) or not math.isfinite(floor)
            or floor <= 0 or ann <= 0 or b0 <= 0 or b1 <= b0
            or floor <= b0):
        return pre
    frac = (math.log(floor) - math.log(b0)) / (math.log(b1) - math.log(b0))
    # at least one annealing epoch must remain: rewinding to (or past)
    # the ramp's end would "re-anneal" at a constant beta_end
    return pre + min(int(frac * ann), ann - 1)


def read_publishes(stream_dir: str) -> tuple[list[dict], int]:
    """All parseable ``publish`` records of a stream dir, oldest first,
    plus the torn-line count (the journal contract's replay)."""
    records, torn = read_journal(publishes_path(stream_dir))
    return [r for r in records if r.get("kind") == "publish"], torn


@dataclass(frozen=True)
class OnlineConfig:
    """Knobs of the online loop, separate from the model's TrainConfig."""

    window: int = 256            # working-set rows per round
    stride: int = 64             # fresh rows consumed per round
    chunk_epochs: int = 2        # epochs per jitted chunk (= one round)
    publish_every: int = 1       # publish a checkpoint every N rounds
    rounds: int = 8              # total rounds this invocation runs
    source: str = "sliding"      # 'sliding' | 'reservoir'
    seed: int = 0                # RowStream shuffle/reservoir seed
    drift: tuple = ()            # scripted DriftSpec schedule (tests/chaos)
    drift_threshold: float = 1.0  # baseline-σ units of window-mean shift
    reanneal_on_drift: bool = True
    keep_publishes: int = 0      # retain newest N checkpoint dirs (0 = all)

    def __post_init__(self):
        if self.chunk_epochs < 1:
            raise ValueError("chunk_epochs must be >= 1")
        if self.publish_every < 1:
            raise ValueError("publish_every must be >= 1")
        if self.keep_publishes < 0:
            raise ValueError("keep_publishes must be >= 0")


#: Deliberate SIGKILL-shaped fault injection for the chaos suite
#: (scripts/chaos_stream.py): ``DIB_STREAM_FAULT="<point>:<n>"`` makes
#: the n-th (0-based) arrival at ``<point>`` emit a durable ``fault``
#: event and die with ``os._exit`` — the same "record lands before the
#: signal" contract as ``dib_tpu/faults``.
FAULT_ENV = "DIB_STREAM_FAULT"
_FAULT_KINDS = {
    "mid_publish": "stream_mid_publish_kill",
    "post_rename": "stream_mid_publish_kill",
    "deployer_tail": "stream_deployer_kill",
}
_fault_hits: dict[str, int] = {}


def maybe_kill(point: str, telemetry=None) -> None:
    """Die at ``point`` when the chaos suite scheduled a kill there."""
    spec = os.environ.get(FAULT_ENV, "")
    if ":" not in spec:
        return
    p, _, n = spec.rpartition(":")
    if p != point:
        return
    hit = _fault_hits.get(point, 0)
    _fault_hits[point] = hit + 1
    if hit != int(n):
        return
    if telemetry is not None:
        # one O_APPEND write — durable before the exit below
        telemetry.fault(kind=_FAULT_KINDS[point], via=point)
    os._exit(137)


def _fsync_tree(directory: str) -> None:
    """fsync every file and directory under ``directory`` (bottom-up), so
    the subsequent rename publishes fully-durable bytes."""
    for dirpath, _, filenames in os.walk(directory, topdown=False):
        for name in filenames:
            fd = os.open(os.path.join(dirpath, name), os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        fd = os.open(dirpath, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)


def _fsync_dir(directory: str) -> None:
    fd = os.open(directory, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class OnlineDIBTrainer:
    """Drives a ``DIBTrainer`` on a streaming source and publishes
    chunk-aligned checkpoints through the atomic publish protocol.

    ``bundle`` supplies the stream's row pool and the FIXED held-out
    validation split (val_loss stays comparable across windows — under
    drift it is exactly the signal that decays). The jitted hot path is
    ``DIBTrainer.run_stream_chunk``, which takes the window as real
    arguments: one compile serves every round.
    """

    def __init__(self, model, bundle, config, online: OnlineConfig,
                 stream_dir: str, telemetry=None, y_encoder=None):
        from dib_tpu.train import DIBTrainer

        if online.window < config.batch_size:
            raise ValueError(
                f"window ({online.window}) must be >= batch_size "
                f"({config.batch_size}) — an epoch needs one full batch")
        # steps_per_epoch must reflect the WINDOW, not the backing pool
        # (DIBTrainer derives it from bundle.x_train otherwise)
        if not config.steps_per_epoch:
            config = dataclasses.replace(
                config,
                steps_per_epoch=-(-online.window // config.batch_size))
        self.online = online
        self.stream_dir = os.path.abspath(stream_dir)
        self.telemetry = telemetry
        self.trainer = DIBTrainer(model, bundle, config, y_encoder=y_encoder)
        self.config = config
        drift = tuple(d if isinstance(d, DriftSpec) else DriftSpec(**d)
                      for d in online.drift)
        self.stream = RowStream(bundle.x_train, bundle.y_train,
                                seed=online.seed, drift=drift)
        self.source = make_source(online.source, self.stream,
                                  online.window, online.stride)
        os.makedirs(self.stream_dir, exist_ok=True)
        self._journal: JobJournal | None = None
        self._baseline: tuple[np.ndarray, np.ndarray] | None = None
        self.publishes = 0
        self.drifts = 0

    # ------------------------------------------------------------- resume
    def _restore_or_init(self, key):
        """(state, history, key, round0, epochs_done): from the newest
        INTACT publish record when one exists (the exact resume point —
        source offset, drift baseline, and PRNG chain included), else
        fresh.

        Intact means the restore — including the v3 content-digest
        verification — succeeds: a publish whose bytes rotted (or were
        bit-flipped) after the rename must not crash-loop the always-on
        trainer any more than it may be promoted by the deployer. Corrupt
        publishes are skipped newest→oldest with a durable
        ``checkpoint_fallback`` mitigation each. The artifact is
        deliberately left IN PLACE (skip-only, unlike the train-side
        quarantine): the journal is an append-only ledger, the deployer
        owns its own decision on the same artifact, and the resumed
        trainer republishes the skipped step with clean bytes anyway —
        each later restart re-walks (and re-reports) the corrupt dir
        until retention prunes it, which is the honest trade for never
        mutating the published plane."""
        import jax

        from dib_tpu.train import CheckpointCorruptionError, DIBCheckpointer

        records, torn = read_publishes(self.stream_dir)
        if torn and self.telemetry is not None:
            self.telemetry.mitigation(mtype="journal_recovered",
                                      detail=f"publishes.jsonl: {torn} "
                                             "torn line(s) skipped")
        # sweep away torn staging remains of a dead trainer — they were
        # never published, so nothing references them
        shutil.rmtree(os.path.join(self.stream_dir, STAGING_DIRNAME),
                      ignore_errors=True)
        rec = state = history = None
        last_exc = None
        for candidate in reversed(records):
            ckpt_dir = os.path.join(self.stream_dir, candidate["path"])
            if not os.path.isdir(ckpt_dir):
                continue   # pruned by keep_publishes — older ones remain
            ckpt = DIBCheckpointer(ckpt_dir)
            try:
                state, history, key = ckpt.restore(
                    self.trainer, chunk_size=self.online.chunk_epochs)
            except CheckpointCorruptionError as exc:
                last_exc = exc
                if self.telemetry is not None:
                    self.telemetry.mitigation(
                        mtype="checkpoint_fallback",
                        step=int(candidate.get("step", -1)),
                        detail=candidate.get("publish_id"),
                        error=str(exc))
                continue
            finally:
                ckpt.close()
            rec = candidate
            break
        if rec is None:
            if records and last_exc is not None:
                # every on-disk publish is corrupt: restarting fresh
                # would silently fork the published trajectory — raise
                # with the evidence instead
                raise CheckpointCorruptionError(
                    f"no intact publish checkpoint under "
                    f"{self.stream_dir} ({len(records)} record(s) "
                    f"walked); last error: {last_exc}"
                ) from last_exc
            key, k_init = jax.random.split(key)
            state, history = self.trainer.init(k_init)
            return state, history, key, 0, 0
        self.source.restore(rec["source"])
        # the snapshot was taken mid-round (before the round's advance);
        # resuming at round+1 owes exactly the one advance the dead
        # trainer performed (or would have performed) after publishing
        self.source.advance()
        if rec.get("baseline") is not None:
            self._baseline = (np.asarray(rec["baseline"]["mean"]),
                              np.asarray(rec["baseline"]["std"]))
        self.publishes = int(rec.get("index", 0)) + 1
        self.drifts = int(rec.get("drifts", 0))
        if self.telemetry is not None:
            self.telemetry.mitigation(
                mtype="stream_resumed", detail=rec["publish_id"],
                restored_epoch=int(rec["step"]))
        return state, history, key, int(rec["round"]) + 1, int(rec["step"])

    # -------------------------------------------------------------- drift
    def _detect_drift(self, x_win: np.ndarray) -> float | None:
        """Normalized worst-feature shift of the window mean vs the
        baseline window, or None below threshold. The first window (and
        each post-drift window) becomes the new baseline."""
        mean = x_win.mean(axis=0)
        std = x_win.std(axis=0)
        if self._baseline is None:
            self._baseline = (mean, std)
            return None
        base_mean, base_std = self._baseline
        shift = float(np.max(np.abs(mean - base_mean)
                             / np.maximum(base_std, 1e-6)))
        if shift <= self.online.drift_threshold:
            return None
        self._baseline = (mean, std)
        return shift

    # ------------------------------------------------------------ publish
    def _publish(self, state, history, key, *, step: int, round_index: int,
                 beta: float, boundary: dict | None = None) -> dict:
        """The atomic publish protocol: stage → fsync → rename → journal.

        The record lands ONLY after the checkpoint is fully durable under
        its final path, so a record is a promotion-safe pointer by
        construction — a kill at any earlier point leaves staging litter
        the next launch sweeps, never a torn promoted checkpoint."""
        from dib_tpu.train import DIBCheckpointer

        pub_id = f"pub-{step:08d}"
        rel = os.path.join(CHECKPOINTS_DIRNAME, pub_id)
        staging = os.path.join(self.stream_dir, STAGING_DIRNAME, pub_id)
        final = os.path.join(self.stream_dir, rel)
        shutil.rmtree(staging, ignore_errors=True)
        ckpt = DIBCheckpointer(staging, max_to_keep=1)
        try:
            ckpt.save(step, state, history, key,
                      chunk_size=self.online.chunk_epochs)
        finally:
            ckpt.close()   # waits for any async write
        _fsync_tree(staging)
        maybe_kill("mid_publish", self.telemetry)
        os.makedirs(os.path.dirname(final), exist_ok=True)
        # An existing final dir is an ORPHAN: a previous trainer died
        # between rename and journal append, so no record references it,
        # the deployer never saw it — and the resumed (bit-identical)
        # trainer is republishing the very same step. Replace it.
        shutil.rmtree(final, ignore_errors=True)
        os.replace(staging, final)
        _fsync_dir(os.path.dirname(final))
        maybe_kill("post_rename", self.telemetry)
        base = self._baseline
        record = self._journal.append(
            "publish",
            publish_id=pub_id,
            index=self.publishes,
            step=int(step),
            round=int(round_index),
            path=rel,
            beta=float(beta),
            chunk_epochs=self.online.chunk_epochs,
            source=self.source.snapshot(),
            drifts=self.drifts,
            baseline=(None if base is None else
                      {"mean": [float(v) for v in base[0]],
                       "std": [float(v) for v in base[1]]}),
            # the publisher's boundary stats: the deployer's canary
            # compares the candidate's per-channel KL against these, so
            # a checkpoint predicting finite garbage fails promotion
            # (stream/deployer.py; docs/robustness.md "Numerical
            # integrity"). Older records without them canary vacuously.
            boundary=(None if boundary is None else {
                "loss": float(boundary["loss"]),
                "val_loss": float(boundary["val_loss"]),
                "kl_per_feature": [float(v) for v in
                                   np.asarray(
                                       boundary["kl_per_feature"]
                                   ).ravel()],
            }),
        )
        self.publishes += 1
        if self.telemetry is not None:
            self.telemetry.publish(publish_id=pub_id, step=int(step),
                                   path=rel, round=int(round_index),
                                   beta=float(beta))
        self._prune_checkpoints()
        return record

    def _prune_checkpoints(self) -> None:
        """Bound on-disk checkpoints to the newest ``keep_publishes``
        (0 = unlimited). The journals only grow — they are the durable
        ledger — but an always-on stream must not fill the disk with one
        full resume payload per cadence. The newest publish (the resume
        anchor) is always in the kept tail; a deployer catching up past a
        pruned checkpoint gates the restore failure like a failed canary
        (rolled_back, the previous checkpoint keeps answering)."""
        keep = self.online.keep_publishes
        if keep <= 0:
            return
        root = os.path.join(self.stream_dir, CHECKPOINTS_DIRNAME)
        # pub-%08d: lexicographic order IS publish order
        names = sorted(n for n in os.listdir(root) if n.startswith("pub-"))
        for name in names[:-keep]:
            shutil.rmtree(os.path.join(root, name), ignore_errors=True)

    # ---------------------------------------------------------------- run
    def run(self, key, rounds: int | None = None, preempt=None,
            boundary_hook=None) -> dict:
        """Train ``rounds`` rounds (one chunk per round), publishing on
        the configured cadence. Resumes from the newest publish record
        when the stream dir already holds one. ``preempt`` (a
        ``PreemptionGuard``) makes SIGTERM land as a final publish at the
        next boundary; ``boundary_hook(round_index, epochs_done)`` is the
        chaos suite's fault-injection point (called after each round's
        publish decision, exactly like the sched runner's hook)."""
        import jax
        import jax.numpy as jnp

        from dib_tpu.train.history import history_extend
        from dib_tpu.train.preempt import TrainingPreempted
        from dib_tpu.utils.profiling import PhaseTimer

        online = self.online
        cfg = self.config
        rounds = online.rounds if rounds is None else rounds
        self._journal = JobJournal(self.stream_dir,
                                   filename=PUBLISHES_FILENAME)
        timer = PhaseTimer()
        row = {"loss": float("nan"), "val_loss": float("nan"),
               "beta": float("nan")}
        try:
            state, history, key, round0, epochs_done = \
                self._restore_or_init(key)
            # capacity for THIS invocation's rounds (resume may land past
            # the template's preallocation)
            capacity = int(history["beta"].shape[0])
            needed = epochs_done + (rounds - round0) * online.chunk_epochs
            if needed > capacity:
                history = history_extend(history, needed - capacity)
            for round_index in range(round0, rounds):
                x_win, y_win = self.source.window()
                shift = self._detect_drift(x_win)
                if shift is not None:
                    self.drifts += 1
                    action = ("reanneal" if online.reanneal_on_drift
                              else "hold")
                    # an autopilot-applied schedule (reanneal.json,
                    # dib_tpu/autopilot) narrows the rewind to the floor
                    # below the refreshed transition-β estimates; absent
                    # or unreadable, the fixed full re-anneal applies
                    schedule = (load_reanneal_schedule(self.stream_dir)
                                if online.reanneal_on_drift else None)
                    rewind = (cfg.num_pretraining_epochs
                              if schedule is None
                              else reanneal_rewind_epoch(schedule, cfg))
                    if self.telemetry is not None:
                        self.telemetry.drift(
                            round=round_index, detector="window_mean",
                            shift=round(shift, 4),
                            threshold=online.drift_threshold,
                            action=action, epoch=epochs_done,
                            rewind_epoch=(int(rewind)
                                          if online.reanneal_on_drift
                                          else None),
                            schedule_study=(None if schedule is None
                                            else schedule.get("study_id")))
                    self._journal.append(
                        "drift", round=round_index, shift=round(shift, 4),
                        action=action,
                        rewind_epoch=(int(rewind)
                                      if online.reanneal_on_drift
                                      else None),
                        schedule_study=(None if schedule is None
                                        else schedule.get("study_id")))
                    if online.reanneal_on_drift:
                        # rewind the SCHEDULE epoch: β re-anneals toward
                        # beta_end against the drifted distribution from
                        # the anneal start (fixed schedule) or from the
                        # applied schedule's transition floor;
                        # params/optimizer/history continue
                        state = type(state)(
                            state.params, state.opt_state,
                            jnp.asarray(rewind, jnp.int32))
                key, k_chunk = jax.random.split(key)
                with timer.phase("stream_chunk"):
                    state, history = self.trainer.run_stream_chunk(
                        state, history, k_chunk,
                        jnp.asarray(x_win), jnp.asarray(y_win),
                        online.chunk_epochs)
                    epochs_done += online.chunk_epochs
                    # ONE explicit blocking fetch per boundary (the
                    # honest sync point): the boundary row + the
                    # schedule epoch, inside the blocking phase
                    cursor = epochs_done - 1
                    row = jax.device_get({
                        "loss": history["loss"][cursor],
                        "val_loss": history["val_loss"][cursor],
                        "beta": history["beta"][cursor],
                        # per-channel KL rides the same fetch: the publish
                        # record carries it as the deployer's canary
                        # reference (a promoted checkpoint must reproduce
                        # the publisher's boundary KL, not just predict
                        # finite numbers — stream/deployer.py)
                        "kl_per_feature": history["kl_per_feature"][cursor],
                        "epoch": state.epoch,
                    })
                if self.telemetry is not None:
                    self.telemetry.chunk(
                        epoch=epochs_done,
                        steps=online.chunk_epochs * self.trainer.steps_per_epoch,
                        seconds=timer.intervals["stream_chunk"][-1],
                        loss=float(row["loss"]),
                        val_loss=float(row["val_loss"]),
                        beta=float(row["beta"]))
                # ABSOLUTE cadence (not relative to this launch's first
                # round), so a resumed run publishes at the same rounds an
                # uninterrupted one would — the bit-identity tests compare
                # the two journals record for record
                published = ((round_index + 1) % online.publish_every == 0
                             or round_index == rounds - 1)
                if published:
                    self._publish(state, history, key, step=epochs_done,
                                  round_index=round_index,
                                  beta=float(row["beta"]), boundary=row)
                if boundary_hook is not None:
                    boundary_hook(round_index, epochs_done)
                if preempt is not None and preempt.requested:
                    if not published:
                        # chunk-aligned grace checkpoint: the publish IS
                        # the resume point, so a preempted round must
                        # leave one before unwinding
                        self._publish(state, history, key,
                                      step=epochs_done,
                                      round_index=round_index,
                                      beta=float(row["beta"]),
                                      boundary=row)
                    if self.telemetry is not None:
                        self.telemetry.mitigation(
                            mtype="preempt_checkpoint", epoch=epochs_done)
                    raise TrainingPreempted(
                        f"preempted at round {round_index} "
                        f"(epoch {epochs_done}); latest publish is the "
                        "resume point")
                self.source.advance()
        finally:
            self._journal.close()
            self._journal = None
        # None, not NaN, when this invocation ran zero rounds (a resume
        # already past --rounds): json.dumps would emit a bare NaN token
        # that strict parsers reject (the EventWriter sanitation rule)
        def _finite(v):
            f = float(v)
            return f if math.isfinite(f) else None

        return {
            "rounds": rounds,
            "epochs": epochs_done,
            "publishes": self.publishes,
            "drifts": self.drifts,
            "final_loss": _finite(row["loss"]),
            "final_val_loss": _finite(row["val_loss"]),
            "final_beta": _finite(row["beta"]),
        }
