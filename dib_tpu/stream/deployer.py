"""Deployer: tails the publish journal and hot-swaps the serving fleet.

The serving half of the always-on control plane (docs/streaming.md). A
:class:`Deployer` owns one zoo model name and the durable record of what
it promoted:

  - it TAILS ``<stream-dir>/publishes.jsonl`` (the only thing trainer
    and deployer share) and processes publish records strictly in order;
  - each candidate checkpoint is restored into a fresh router and
    HEALTH-GATED: the canary rows run through the new engine before any
    traffic routes to it — a canary that throws or returns non-finite
    numbers ROLLS the promotion BACK (the candidate is closed, the
    previous checkpoint keeps answering, and the rollback is durably
    recorded);
  - a healthy candidate is promoted via ``ModelZoo.reload`` — the atomic
    router swap plus exactly-the-reloaded-model response/executable cache
    invalidation pinned by ``tests/test_serve_zoo.py``, so live traffic
    rides the swap with every response numerically from exactly one
    published checkpoint, never a params/cache hybrid;
  - every decision lands as ONE ``deploy`` record in the deployer's own
    ``deploys.jsonl`` (same torn-line-tolerant journal idiom), appended
    AFTER the swap. A deployer SIGKILLed at any point therefore restarts
    into exact catch-up: publishes with no deploy record are processed
    (the kill-between-reload-and-append case re-runs an idempotent
    reload of the same checkpoint), publishes with one are never
    re-promoted — no skipped and no double-promoted checkpoint
    (``tests/test_stream_deploy.py``).

The tail loop runs on a plain daemon-free worker thread, never on the
server's event loop: a reload (restore + compile) costs real seconds and
the serving loop must not block on it (the ``async-blocking`` lint pass
guards the invariant).
"""

from __future__ import annotations

import os
import sys
import threading
import time

import numpy as np

from dib_tpu.sched.journal import JobJournal, read_journal
from dib_tpu.stream.online import (load_reanneal_schedule, maybe_kill,
                                   publishes_path, read_publishes)

__all__ = ["CanaryFailure", "DEPLOYS_FILENAME", "Deployer",
           "ROUTING_FILENAME", "deploys_path", "load_routing",
           "read_deploys", "routing_path", "stream_status"]

DEPLOYS_FILENAME = "deploys.jsonl"
ROUTING_FILENAME = "routing.json"


def routing_path(stream_dir: str) -> str:
    return os.path.join(stream_dir, ROUTING_FILENAME)


def load_routing(stream_dir: str) -> dict | None:
    """The autopilot-applied β-routing metadata (refreshed transition-β
    map, ``dib_tpu/autopilot``), or None. Written atomically, so a
    reader never sees torn bytes; anything unreadable is treated as
    absent — routing metadata is advisory, never a serving gate."""
    import json

    try:
        with open(routing_path(stream_dir), encoding="utf-8") as fh:
            payload = json.load(fh)
    except (OSError, ValueError):
        return None
    return payload if isinstance(payload, dict) else None


def _publish_key(rec: dict) -> str:
    """Stable identity of a publish record for exactly-once accounting.

    A record a foreign writer left without ``publish_id`` still needs an
    identity that is deterministic across polls and restarts — otherwise
    its rolled_back decision can never match it on the next read and the
    journal grows one duplicate decision per poll."""
    pid = rec.get("publish_id")
    if pid:
        return pid
    return f"malformed-idx{rec.get('index')}-step{rec.get('step')}"


def deploys_path(deploy_dir: str) -> str:
    return os.path.join(deploy_dir, DEPLOYS_FILENAME)


def read_deploys(deploy_dir: str) -> tuple[list[dict], int]:
    """All parseable ``deploy`` records of a deploy dir, oldest first,
    plus the torn-line count."""
    records, torn = read_journal(deploys_path(deploy_dir))
    return [r for r in records if r.get("kind") == "deploy"], torn


class CanaryFailure(RuntimeError):
    """The candidate checkpoint failed its canary probe."""


class Deployer:
    """Tails ``publishes.jsonl``, canary-gates, and hot-swaps via the zoo.

    Args:
      stream_dir: the trainer's stream directory (the shared journal).
      deploy_dir: this deployer's durable state (``deploys.jsonl``).
      trainer: a ``DIBTrainer`` restore template (architecture must match
        the published checkpoints; the integrity manifest enforces it).
      zoo: the serving ``ModelZoo`` the fleet routes through.
      model_name: the zoo name promotions swap (first promotion registers
        it; later ones ``reload`` it).
      canary_rows: [k, width] probe input; default is the bundle's first
        validation rows via ``trainer``.
      router_kwargs: forwarded to ``ReplicaRouter.from_params`` (batcher
        knobs, telemetry, registry, tracer).
    """

    #: Per-channel KL tolerance of the canary's publisher-stats check: a
    #: candidate channel may sit within KL_BAND× of the publisher's
    #: recorded boundary KL (plus KL_SLACK_NATS absolute slack so
    #: compressed-away channels near zero never trip). Generous on
    #: purpose — canary rows differ from the training batch — while
    #: finite-garbage params put the KL orders of magnitude out.
    KL_BAND = 8.0
    KL_SLACK_NATS = 0.5

    def __init__(self, stream_dir: str, deploy_dir: str, trainer, zoo,
                 model_name: str = "stream", canary_rows=None,
                 telemetry=None, registry=None, poll_s: float = 0.25,
                 router_kwargs: dict | None = None):
        self.stream_dir = os.path.abspath(stream_dir)
        self.deploy_dir = os.path.abspath(deploy_dir)
        self.trainer = trainer
        self.zoo = zoo
        self.model_name = model_name
        self.telemetry = telemetry
        self.registry = registry
        self.poll_s = float(poll_s)
        self.router_kwargs = dict(router_kwargs or {})
        if canary_rows is None:
            canary_rows = np.asarray(trainer.bundle.x_valid[:4], np.float32)
        self.canary_rows = np.asarray(canary_rows, np.float32)
        os.makedirs(self.deploy_dir, exist_ok=True)
        self._journal = JobJournal(self.deploy_dir,
                                   filename=DEPLOYS_FILENAME)
        # all counters/flags below are mutated from the tail thread and
        # read from callers; one lock guards them (and journal appends
        # pair with counter updates under it)
        self._lock = threading.Lock()
        self._processed: set[str] = set()
        self.promoted = 0
        self.rollbacks = 0
        self.publishes_seen = 0
        # the newest promoted publish_id from the journal replay: a
        # restart re-registers it into the fresh (empty) zoo so the fleet
        # answers immediately instead of waiting for the NEXT publish
        self._warm_restore_id: str | None = None
        records, _ = read_deploys(self.deploy_dir)
        for rec in records:
            self._processed.add(rec.get("publish_id", ""))
            if rec.get("action") == "promoted":
                self.promoted += 1
                self._warm_restore_id = rec.get("publish_id")
            elif rec.get("action") == "rolled_back":
                self.rollbacks += 1
        # a non-empty deploy journal means THIS is a restart: the first
        # catch-up emits the deployer_caught_up mitigation (the chaos
        # suite's SIGKILL-detection marker)
        self._resumed = bool(records)
        # byte size of publishes.jsonl at the last full read: the journal
        # is append-only, so an unchanged size means no new records and
        # the idle poll can skip re-parsing the whole file
        self._publishes_size = -1
        # (mtime_ns, size) of routing.json at the last successful pickup:
        # the autopilot replaces the file atomically, so a changed stat
        # is the only signal the β-routing metadata needs re-attaching
        self._routing_sig: tuple[int, int] | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ----------------------------------------------------------- candidates
    def _build_router(self, checkpoint_dir: str):
        """Restore the published checkpoint into a fresh router wired to
        the zoo's shared executable cache under THIS model's key prefix —
        so ``reload`` invalidation hits exactly these executables."""
        from dib_tpu.serve.replicas import ReplicaRouter
        from dib_tpu.train import DIBCheckpointer

        ckpt = DIBCheckpointer(checkpoint_dir)
        try:
            state, _, _ = ckpt.restore(self.trainer)
        finally:
            ckpt.close()
        return ReplicaRouter.from_params(
            self.trainer.model, state.params["model"],
            exec_cache=self.zoo.exec_cache, cache_key=self.model_name,
            registry=self.registry, **self.router_kwargs)

    def _canary(self, router, rec: dict | None = None) -> float:
        """Probe the candidate's engine directly (no traffic routes to it
        yet); raises :class:`CanaryFailure` on any unhealthy signal.

        Three gates, in escalating subtlety: (1) ``predict`` must return
        finite, right-shaped rows; (2) ``encode`` must run and return
        finite channel params (a checkpoint can predict while its
        encoder plane is garbage — both ops serve live traffic); (3)
        when the publish record carries the publisher's boundary stats,
        the canary's per-channel KL must land within ``KL_BAND``× of the
        recorded values — the gate that catches a checkpoint predicting
        FINITE garbage, which ``np.isfinite`` waves straight through
        (ISSUE 14; docs/robustness.md "Numerical integrity"). Records
        without stats (older publishers) skip gate 3.
        """
        t0 = time.monotonic()
        engine = router.entries[0].engine
        try:
            out = engine.predict(self.canary_rows)
        except Exception as exc:
            raise CanaryFailure(f"canary dispatch failed: {exc}") from exc
        prediction = np.asarray(out.get("prediction"))
        if prediction.shape[0] != self.canary_rows.shape[0]:
            raise CanaryFailure(
                f"canary returned {prediction.shape[0]} rows for "
                f"{self.canary_rows.shape[0]} inputs")
        if not np.all(np.isfinite(prediction)):
            raise CanaryFailure("canary prediction is non-finite — the "
                                "checkpoint serves garbage")
        try:
            encoded = engine.encode(self.canary_rows)
        except Exception as exc:
            raise CanaryFailure(f"canary encode failed: {exc}") from exc
        for name, arr in encoded.items():
            if not np.all(np.isfinite(np.asarray(arr))):
                raise CanaryFailure(
                    f"canary encode returned non-finite {name!r} — the "
                    "checkpoint's encoder plane serves garbage")
        recorded = ((rec or {}).get("boundary") or {}).get("kl_per_feature")
        if recorded:
            canary_kl = np.asarray(out.get("kl_per_feature")).mean(axis=0)
            if canary_kl.shape[0] != len(recorded):
                raise CanaryFailure(
                    f"canary KL has {canary_kl.shape[0]} channels but "
                    f"the publish record holds {len(recorded)} — the "
                    "checkpoint does not match the publishing trainer")
            band, slack = self.KL_BAND, self.KL_SLACK_NATS
            bad = [
                i for i, (c, r) in enumerate(zip(canary_kl, recorded))
                if c > r * band + slack or c < r / band - slack
            ]
            if bad:
                detail = ", ".join(
                    f"channel {i}: {float(canary_kl[i]):.3g} vs recorded "
                    f"{float(recorded[i]):.3g}" for i in bad[:3])
                raise CanaryFailure(
                    f"canary per-channel KL disagrees with the "
                    f"publisher's boundary stats on {len(bad)} "
                    f"channel(s) ({detail}; band ×{band:g} + {slack:g} "
                    "nats) — the checkpoint predicts finite garbage")
        return time.monotonic() - t0

    # ------------------------------------------------------------ promotion
    def _process(self, rec: dict) -> str:
        """Promote (or roll back) ONE publish record; returns the action.

        The deploy record appends AFTER the swap: a kill in between makes
        the restart re-run an idempotent reload of the same checkpoint —
        exactly-once is defined by the journal, and the journal gets at
        most one record per publish."""
        pub_id = rec["publish_id"]
        path = os.path.join(self.stream_dir, rec["path"])
        try:
            router = self._build_router(path)
        except Exception as exc:
            # a restore that fails is gated exactly like a failed canary:
            # the previous checkpoint keeps answering
            return self._record(pub_id, rec, "rolled_back",
                                error=f"restore failed: {exc}")
        try:
            canary_s = self._canary(router, rec)
        except CanaryFailure as exc:
            router.close()
            return self._record(pub_id, rec, "rolled_back",
                                error=str(exc))
        if self.model_name in self.zoo.names():
            self.zoo.reload(self.model_name, router, checkpoint_dir=path)
        else:
            self.zoo.register(self.model_name, router, checkpoint_dir=path)
        return self._record(pub_id, rec, "promoted",
                            canary_s=canary_s)

    def _record(self, pub_id: str, rec: dict, action: str,
                **fields) -> str:
        # wall-clock vs the publish record's journal stamp, taken AFTER
        # the decision completed — restore + canary + hot swap are INSIDE
        # the interval, so this is the full publish→serve latency the
        # stream_publish_to_serve_p99_ceiling SLO gates
        # lint-ok(timing-hygiene): host-side latency vs a journal unix
        # timestamp; no jitted work inside the interval
        t_done = time.time()
        latency_s = round(max(t_done - rec.get("t", t_done), 0.0), 6)
        with self._lock:
            self._journal.append(
                "deploy", publish_id=pub_id, action=action,
                publish_index=rec.get("index"), step=rec.get("step"),
                model=self.model_name, latency_s=latency_s, **fields)
            self._processed.add(pub_id)
            if action == "promoted":
                self.promoted += 1
            else:
                self.rollbacks += 1
        if self.telemetry is not None:
            # best-effort once the journal append landed: the decision is
            # durable, and letting an events.jsonl write error escape here
            # would make catch_up's guard append a SECOND record for this
            # publish — the exact double-decision the journal forbids
            try:
                self.telemetry.deploy(
                    publish_id=pub_id, action=action, model=self.model_name,
                    step=rec.get("step"), index=rec.get("index"),
                    latency_s=latency_s,
                    **({"error": fields["error"]}
                       if "error" in fields else {}))
                if action == "rolled_back":
                    self.telemetry.mitigation(
                        mtype="canary_rollback", model=self.model_name,
                        detail=pub_id, error=fields.get("error"))
            except Exception as exc:
                # the one failure with no telemetry channel left: say so
                # on stderr rather than roll back a healthy promotion
                print(f"stream deployer: telemetry write failed for "
                      f"{pub_id} ({action}): {exc}", file=sys.stderr)
        return action

    def _warm_restore(self, pub_id: str, publishes: list[dict]) -> None:
        """Re-register the newest PROMOTED checkpoint after a restart.

        The deploy journal is the durable record of WHAT was promoted,
        but the zoo is in-memory: a deployer restarted when every publish
        is already decided would otherwise serve NOTHING until the
        trainer's next publish (unbounded if the trainer is between
        publishes or down). No new deploy record lands — rebuilding
        in-memory state is not a promotion decision, and a second record
        for the same publish would read as a double promotion. A failed
        restore/canary is only a mitigation: pending publishes (or the
        next one) will supply a fresh checkpoint."""
        rec = next((r for r in publishes
                    if r.get("publish_id") == pub_id), None)
        if rec is None:
            return
        path = os.path.join(self.stream_dir, rec["path"])
        try:
            router = self._build_router(path)
        except Exception as exc:
            self._warm_restore_failed(pub_id, f"restore failed: {exc}")
            return
        try:
            self._canary(router, rec)
        except CanaryFailure as exc:
            router.close()
            self._warm_restore_failed(pub_id, str(exc))
            return
        self.zoo.register(self.model_name, router, checkpoint_dir=path)
        if self.telemetry is not None:
            self.telemetry.mitigation(
                mtype="deployer_warm_restore", model=self.model_name,
                detail=pub_id)

    def _warm_restore_failed(self, pub_id: str, error: str) -> None:
        if self.telemetry is not None:
            self.telemetry.mitigation(
                mtype="warm_restore_failed", model=self.model_name,
                detail=pub_id, error=error)

    def _refresh_routing(self) -> None:
        """Attach autopilot-applied β-routing metadata to the served
        model. Stat-gated like the publish tail (``routing.json`` is
        replaced atomically, so a changed stat is the only re-attach
        signal); advisory only — an absent or unreadable file, or a zoo
        with no model registered yet, just retries on a later poll."""
        try:
            st = os.stat(routing_path(self.stream_dir))
            sig = (st.st_mtime_ns, st.st_size)
        except OSError:
            return
        with self._lock:
            if sig == self._routing_sig:
                return
        routing = load_routing(self.stream_dir)
        if routing is None or self.model_name not in self.zoo.names():
            return
        self.zoo.set_routing(self.model_name, routing)
        with self._lock:
            self._routing_sig = sig
        if self.telemetry is not None:
            # best-effort: the zoo already carries the metadata, and an
            # events.jsonl write error must not wedge the tail loop
            try:
                self.telemetry.link(
                    target=f"study:{routing.get('study_id')}",
                    relation="routes_by", plane="serve",
                    detail=self.model_name)
            except Exception as exc:
                print(f"stream deployer: telemetry write failed for "
                      f"routing refresh: {exc}", file=sys.stderr)

    # -------------------------------------------------------------- tailing
    def catch_up(self) -> int:
        """Process every publish record not yet in the deploy journal, in
        publish order. Returns how many were processed.

        The idle poll (every ``poll_s`` forever on an always-on stream)
        stats the publish journal instead of re-parsing it: append-only
        means an unchanged byte size is "nothing new". The size stored is
        the PRE-read stat, so a record appended mid-read just costs one
        extra re-read on the next poll, never a miss."""
        self._refresh_routing()
        try:
            size = os.path.getsize(publishes_path(self.stream_dir))
        except OSError:
            size = -1
        with self._lock:
            if (size >= 0 and size == self._publishes_size
                    and self._warm_restore_id is None
                    and not self._resumed):
                return 0
        records, _ = read_publishes(self.stream_dir)
        with self._lock:
            self.publishes_seen = len(records)
            pending = [r for r in records
                       if _publish_key(r) not in self._processed]
            # consumed exactly once, on the restart's first catch-up
            warm_id, self._warm_restore_id = self._warm_restore_id, None
        if warm_id is not None and self.model_name not in self.zoo.names():
            self._warm_restore(warm_id, records)
        if self._resumed:
            self._resumed = False
            if self.telemetry is not None:
                self.telemetry.mitigation(
                    mtype="deployer_caught_up", model=self.model_name,
                    detail=f"{len(self._processed)} decided, "
                           f"{len(pending)} pending")
        done = 0
        for rec in pending:
            try:
                self._process(rec)
            except Exception as exc:
                # _process gates restore and canary failures itself;
                # anything ELSE (the zoo swap raising, a malformed
                # record) must neither kill the tail thread nor wedge
                # the loop retrying one poisoned record forever: decide
                # it as rolled_back so the journal moves on. Only a
                # failing journal append escapes, to the run-loop guard.
                # Never re-decide: if _record already journaled this
                # publish before the failure, a second append would read
                # as a double decision.
                with self._lock:
                    decided = _publish_key(rec) in self._processed
                if not decided:
                    self._record(_publish_key(rec), rec,
                                 "rolled_back",
                                 error=f"deploy failed: {exc}")
            done += 1
            maybe_kill("deployer_tail", self.telemetry)
        # recorded only once every pending record is decided: an append
        # failure that escaped above leaves the size stale, so the next
        # poll re-reads and retries instead of short-circuiting past the
        # undecided tail
        with self._lock:
            self._publishes_size = size
        return done

    def run(self, duration_s: float | None = None) -> dict:
        """Tail until :meth:`stop` (or ``duration_s``); returns status."""
        deadline = (None if not duration_s
                    else time.monotonic() + float(duration_s))
        while not self._stop.is_set():
            try:
                self.catch_up()
            except Exception as exc:
                # the tail thread must never die silently — the fleet
                # would pin to a stale checkpoint with no decision and
                # no signal. Whatever escaped catch_up (a journal append
                # failing, the publish journal unreadable) lands as a
                # durable mitigation and is retried on the next poll.
                if self.telemetry is not None:
                    self.telemetry.mitigation(
                        mtype="deployer_tail_error",
                        model=self.model_name, error=str(exc))
            if deadline is not None and time.monotonic() >= deadline:
                break
            self._stop.wait(self.poll_s)
        return self.status()

    def start(self) -> "Deployer":
        """Run the tail loop on a worker thread (NOT the serving event
        loop: restores and compiles block for real seconds)."""
        self._thread = threading.Thread(
            target=self.run, name="dib-stream-deployer")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def close(self) -> None:
        self.stop()
        if self._thread is not None:
            self._thread.join(timeout=60.0)
            self._thread = None
        with self._lock:
            self._journal.close()

    def __enter__(self) -> "Deployer":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()

    # --------------------------------------------------------------- status
    def status(self) -> dict:
        with self._lock:
            return {
                "publishes_seen": self.publishes_seen,
                "processed": len(self._processed),
                "promoted": self.promoted,
                "rollbacks": self.rollbacks,
                "model": self.model_name,
            }


def stream_status(stream_dir: str, deploy_dir: str | None = None) -> dict:
    """Pure file-analysis snapshot of a stream (the ``stream status``
    CLI): publish/deploy counts, catch-up lag, and the two invariants'
    live values (lost = a gap below the newest processed publish;
    double = more than one deploy record for one publish)."""
    publishes, pub_torn = read_publishes(stream_dir)
    out = {
        "stream_dir": os.path.abspath(stream_dir),
        "publishes": len(publishes),
        "publishes_torn": pub_torn,
        "latest_publish": publishes[-1]["publish_id"] if publishes else None,
    }
    # the autopilot's applied artifacts, when the closed loop has run:
    # the operator sees WHICH drift round steers the trainer's re-anneal
    # and the zoo's β routing without reading any journal
    schedule = load_reanneal_schedule(stream_dir)
    if schedule is not None:
        out["reanneal"] = {
            "drift_round": schedule.get("drift_round"),
            "study_id": schedule.get("study_id"),
            "beta_floor": schedule.get("beta_floor"),
        }
    routing = load_routing(stream_dir)
    if routing is not None:
        out["routing"] = {
            "drift_round": routing.get("drift_round"),
            "study_id": routing.get("study_id"),
            "transition_betas": routing.get("transition_betas"),
        }
    if deploy_dir is None:
        return out
    deploys, dep_torn = read_deploys(deploy_dir)
    by_publish: dict[str, int] = {}
    for rec in deploys:
        pid = rec.get("publish_id", "")
        by_publish[pid] = by_publish.get(pid, 0) + 1
    seen = {rec.get("publish_index") for rec in deploys
            if rec.get("publish_index") is not None}
    # distinct indices absent INSIDE the decided range = span - count;
    # anchored at min(seen) like streaming_rollup — indices below the
    # oldest record in view were decided before this ledger began, not
    # skipped
    lost = max(seen) - min(seen) + 1 - len(seen) if seen else 0
    out.update({
        "deploy_dir": os.path.abspath(deploy_dir),
        "deploys": len(deploys),
        "deploys_torn": dep_torn,
        "promoted": sum(r.get("action") == "promoted" for r in deploys),
        "rollbacks": sum(r.get("action") == "rolled_back" for r in deploys),
        "pending": len(publishes) - len(by_publish),
        "lost_publishes": lost,
        "double_promotions": sum(1 for c in by_publish.values() if c > 1),
    })
    return out
