"""Run summaries and regression gating over an events.jsonl.

``summarize`` rolls a run's event stream into one flat record shaped like
the repo's committed bench records (``metric`` / ``value`` / ``unit`` plus
breakdown keys), so the event stream and the historical one-line JSON
artifacts stay comparable. ``compare`` diffs two runs — steps/s, final
losses, MI lower bound, mitigation counts — and reports a regression when
a metric moves past a threshold in its bad direction; the CLI exits
nonzero on regression, making it a perf gate ``bench.py`` and CI can call:

    python -m dib_tpu telemetry summarize <run_dir>
    python -m dib_tpu telemetry compare <run_a> <run_b> --threshold 0.05
    python -m dib_tpu telemetry report <run_dir>      # static HTML report

``summarize`` additionally rolls ``span`` events into per-path totals
(dynamic indices collapsed: ``sweep/replica3/...`` -> ``sweep/replica*/...``),
ranks the top self-time hotspots, joins cost-analyzed ``compile`` events
with span durations into per-callable roofline utilization, and reports
device/host memory high-water marks.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import re
import sys
import warnings
from math import log
from typing import Sequence

from dib_tpu.telemetry.events import (
    SCHEMA_VERSION,
    _sanitize_nonfinite,
    read_events,
)

__all__ = ["summarize", "compare", "autopilot_rollup", "faults_rollup",
           "mesh_rollup", "overlap_rollup",
           "scheduler_rollup", "serving_rollup", "span_rollup",
           "streaming_rollup", "study_rollup",
           "span_hotspots", "telemetry_main"]

_LN2 = log(2.0)


def _mean(xs):
    xs = [x for x in xs if x is not None]
    return sum(xs) / len(xs) if xs else None


def _as_floats(value) -> list[float]:
    """Flatten a scalar / list / nested list event field to floats.

    Strings parse through ``float()`` — the event writer encodes a
    diverged run's non-finite values as "NaN"/"Infinity" spellings
    (events.py) and they must survive the round trip.
    """
    if value is None:
        return []
    if isinstance(value, (int, float)):
        return [float(value)]
    if isinstance(value, str):
        try:
            return [float(value)]
        except ValueError:
            return []
    out = []
    for v in value:
        out.extend(_as_floats(v))
    return out


# one wire format for non-finite floats, shared with the writer side
# (events.py) so the round-trip cannot drift
_enc = _sanitize_nonfinite


def _normalize_span_path(path: str) -> str:
    """Collapse dynamic trailing indices so per-instance span names roll up:
    ``sweep/replica3/chunk12/mi_bounds`` -> ``sweep/replica*/chunk*/mi_bounds``.
    Only a segment's TRAILING digit run is dynamic by convention."""
    return "/".join(
        re.sub(r"\d+$", "*", seg) for seg in path.split("/")
    )


def span_rollup(span_events) -> dict:
    """{normalized path: {"total_s", "count", "mean_s"}} over span events,
    ordered by total descending."""
    totals: dict[str, list] = {}
    for e in span_events:
        path = _normalize_span_path(e.get("path") or e.get("name") or "?")
        entry = totals.setdefault(path, [0.0, 0])
        entry[0] += e.get("seconds") or 0.0
        entry[1] += 1
    return {
        path: {
            "total_s": round(total, 4),
            "count": count,
            "mean_s": round(total / count, 4) if count else 0.0,
        }
        for path, (total, count) in sorted(
            totals.items(), key=lambda kv: -kv[1][0]
        )
    }


def span_hotspots(rollup: dict, n: int = 3) -> list[dict]:
    """Top-``n`` spans by SELF time (own total minus its children's) —
    total time would double-charge every parent for its children. A child
    is any path whose NEAREST present ancestor in the rollup is this one
    (slash-named spans may skip intermediate levels)."""
    child_s: dict[str, float] = {}
    for path, stats in rollup.items():
        parts = path.split("/")
        for i in range(len(parts) - 1, 0, -1):
            ancestor = "/".join(parts[:i])
            if ancestor in rollup:
                child_s[ancestor] = child_s.get(ancestor, 0.0) \
                    + stats["total_s"]
                break
    rows = [
        {
            "path": path,
            "self_s": round(
                max(stats["total_s"] - child_s.get(path, 0.0), 0.0), 4),
            "total_s": stats["total_s"],
            "count": stats["count"],
        }
        for path, stats in rollup.items()
    ]
    rows.sort(key=lambda r: -r["self_s"])
    return rows[:n]


def overlap_rollup(span_events) -> dict | None:
    """Measurement-overlap accounting over ``overlapped`` spans
    (docs/performance.md "Overlapped measurement"): an overlapped span's
    ``seconds`` is the EXPOSED wait its collection boundary actually paid
    and ``queued_s`` the dispatch→ready window it rode under other work.
    ``hidden_s`` = queued − exposed (wall-clock the measurement spent in
    flight without the host waiting); ``exposed_frac`` = exposed/queued —
    the number ``compare`` gates (a measurement that starts serializing
    boundaries again shows up as the fraction rising toward 1). None when
    the stream carries no overlapped spans."""
    rows = [e for e in span_events if e.get("overlapped")]
    if not rows:
        return None
    exposed = sum(e.get("seconds") or 0.0 for e in rows)
    queued = sum(e.get("queued_s") or 0.0 for e in rows)
    out = {
        "spans": len(rows),
        "exposed_s": round(exposed, 4),
        "queued_s": round(queued, 4),
        "hidden_s": round(max(queued - exposed, 0.0), 4),
    }
    if queued > 0:
        out["exposed_frac"] = round(min(exposed / queued, 1.0), 6)
    by_name: dict[str, int] = {}
    for e in rows:
        by_name[e.get("name", "?")] = by_name.get(e.get("name", "?"), 0) + 1
    out["by_name"] = by_name
    return out


def _percentile(ordered: list[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list (the histogram
    convention in telemetry/metrics.py)."""
    return ordered[min(int(q * len(ordered)), len(ordered) - 1)]


def serving_rollup(span_events, counters: dict | None = None) -> dict | None:
    """Latency/throughput view of a SERVING stream's ``request``/``batch``
    spans (docs/serving.md): request count + status mix + latency
    percentiles, micro-batch count + mean fill ratio, multi-tenancy and
    cache accounting. ``counters`` is the final ``metrics`` event's
    counter snapshot — the zoo's cache hit/miss/eviction counters ride it
    into ``response_cache``/``exec_cache`` keys. None when the stream
    carries no serving spans (training runs)."""
    requests = [e for e in span_events if e.get("name") == "request"]
    batches = [e for e in span_events if e.get("name") == "batch"]
    if not requests and not batches:
        return None
    out: dict = {}
    if requests:
        latencies = sorted(e.get("seconds") or 0.0 for e in requests)
        statuses: dict[str, int] = {}
        tenants: dict[str, int] = {}
        cached = 0
        for e in requests:
            s = e.get("status", "?")
            statuses[s] = statuses.get(s, 0) + 1
            t = e.get("tenant")
            if t is not None:
                tenants[t] = tenants.get(t, 0) + 1
            if e.get("cached"):
                cached += 1
        span = (max(e.get("mono", 0.0) for e in requests)
                - min(e.get("mono", 0.0) for e in requests))
        out.update({
            "requests": len(requests),
            "rows": int(sum(e.get("rows") or 0 for e in requests)),
            "statuses": statuses,
            "request_p50_ms": round(_percentile(latencies, 0.5) * 1e3, 3),
            "request_p99_ms": round(_percentile(latencies, 0.99) * 1e3, 3),
            "request_mean_ms": round(
                sum(latencies) / len(latencies) * 1e3, 3),
        })
        # The UNCACHED view is what the latency SLO means: cache hits
        # answer from memory in ~µs and quota/shed rejections never
        # dispatch at all — enough of either would drag the blended
        # percentile below what a real dispatch costs and wave a breach
        # past the gate. Always present (= the blended view when nothing
        # is cached/rejected), so serve_uncached_p99_ceiling can gate it.
        uncached = sorted(e.get("seconds") or 0.0 for e in requests
                          if not e.get("cached")
                          and e.get("status") not in ("quota", "shed"))
        if uncached:
            out["uncached_request_p99_ms"] = round(
                _percentile(uncached, 0.99) * 1e3, 3)
        if cached:
            out["cached_requests"] = cached
            out["cache_hit_frac"] = round(cached / len(requests), 6)
        if tenants:
            out["tenants"] = dict(sorted(tenants.items()))
        # quota/shed rejections (server-side spans): the rejection-rate
        # SLO guard reads the fraction — a well-behaved tenant mix must
        # keep 429s bounded (docs/serving.md "Tenancy and quotas")
        quota = statuses.get("quota", 0)
        if quota:
            out["quota_rejected"] = quota
        out["quota_rejected_frac"] = round(quota / len(requests), 6)
        if span > 0:
            out["requests_per_s"] = round(len(requests) / span, 3)
        # Request anatomy: per-phase latency rollup from the spans'
        # `phases` field (docs/observability.md "Request anatomy") — a
        # request carries only the phases it traversed, so counts differ
        # per phase (cache hits skip queue/batch, rejections skip
        # dispatch). `share` is each phase's fraction of total phase
        # time — the number the parse/serialize optimization campaign
        # watches.
        phase_values: dict[str, list[float]] = {}
        for e in requests:
            ph = e.get("phases")
            if not isinstance(ph, dict):
                continue
            for name, dt in ph.items():
                if isinstance(dt, (int, float)):
                    phase_values.setdefault(name, []).append(float(dt))
        if phase_values:
            total_s = sum(sum(v) for v in phase_values.values())
            out["phases"] = {
                name: {
                    "count": len(values),
                    "p50_ms": round(
                        _percentile(sorted(values), 0.5) * 1e3, 4),
                    "p99_ms": round(
                        _percentile(sorted(values), 0.99) * 1e3, 4),
                    "mean_ms": round(
                        sum(values) / len(values) * 1e3, 4),
                    "share": round(sum(values) / total_s, 4)
                    if total_s else 0.0,
                }
                for name, values in sorted(phase_values.items())
            }
    if batches:
        fills = [e.get("fill") for e in batches
                 if isinstance(e.get("fill"), (int, float))]
        out["batches"] = len(batches)
        if fills:
            out["batch_fill_mean"] = round(sum(fills) / len(fills), 4)
    for prefix, key in (("serve.cache.response.", "response_cache"),
                        ("serve.cache.exec.", "exec_cache")):
        stats = {name[len(prefix):]: int(value)
                 for name, value in (counters or {}).items()
                 if name.startswith(prefix)}
        if stats:
            hits, misses = stats.get("hits", 0), stats.get("misses", 0)
            if hits + misses:
                stats["hit_frac"] = round(hits / (hits + misses), 6)
            out[key] = stats
    return out


# Which mitigation mtypes count as DETECTING each injected fault kind
# (dib_tpu/faults). A fault whose detector never fires after it is
# UNDETECTED — `telemetry compare` treats that as a regression: the drill
# proved a recovery path is broken.
_FAULT_DETECTORS: dict[str, tuple[str, ...]] = {
    "stall": ("stall_kill",),
    "kill": ("crash_restart",),
    "nan": ("divergence_rollback", "divergence_detected"),
    "inf": ("divergence_rollback", "divergence_detected"),
    "ckpt_truncate": ("checkpoint_fallback",),
    "ckpt_bitflip_manifest": ("checkpoint_fallback",),
    "replica_error": ("replica_ejected",),
    "replica_slow": ("replica_ejected",),
    "batcher_crash": ("serving_unhealthy", "batcher_restarted"),
    # sweep-level self-healing (docs/robustness.md "Sweep and pod
    # failures"): a poisoned member is detected by its quarantine heal —
    # or, when the divergence is deterministic, by its ejection
    "replica_nan": ("divergence_rollback", "replica_ejected",
                    "divergence_detected"),
    # cooperative preemption: the worker's chunk-aligned grace checkpoint,
    # the supervisor's immediate relaunch, and the scheduler's lease-free
    # re-queue (dib_tpu/sched) all prove detection
    "preempt": ("preempt_checkpoint", "preempt_restart",
                "preempt_requeue"),
    # the multihost barrier emits desync_detected before raising
    "desync": ("desync_detected",),
    # scheduler faults (dib_tpu/sched, docs/robustness.md "Sweep as a
    # service"): a killed worker is detected by the pool's dead-worker
    # steal (worker_dead and/or the lease_stolen it provokes); a forced
    # lease expiry by the steal alone; a torn journal by the restarted
    # scheduler's replay surfacing journal_recovered
    "sched_worker_kill": ("worker_dead", "lease_stolen"),
    "lease_expire": ("lease_stolen",),
    "journal_torn": ("journal_recovered",),
    # streaming control-plane faults (dib_tpu/stream, docs/streaming.md):
    # a trainer SIGKILLed mid-publish is detected by the relaunch
    # resuming from the newest durable publish (stream_resumed) or by the
    # publish journal's torn-line replay; a deployer SIGKILL by the
    # restart's exactly-once catch-up; a poisoned published checkpoint by
    # the canary gate rolling the promotion back
    "stream_mid_publish_kill": ("stream_resumed", "journal_recovered"),
    "stream_deployer_kill": ("deployer_caught_up",),
    "stream_poison": ("canary_rollback",),
    # silent-data-corruption faults (ISSUE 14, docs/robustness.md
    # "Numerical integrity"): a finite param corruption is detected by
    # the β-aware anomaly detector's rollback (or, if the garbage
    # overflows mid-chunk, by the classic divergence rollback); a
    # flipped payload bit by the content-digest gate's fallback walk on
    # any restore path — or by the deployer's canary refusing the
    # poisoned artifact before it ever answers a request
    "sdc": ("anomaly_rollback", "anomaly_detected",
            "divergence_rollback", "divergence_detected"),
    "replica_sdc": ("anomaly_rollback", "replica_ejected",
                    "anomaly_detected"),
    "ckpt_bitflip_payload": ("checkpoint_fallback", "canary_rollback"),
    # closed-loop study controller (dib_tpu/study, docs/study.md): a
    # controller SIGKILLed inside the exactly-once window (between the
    # round's journal append and the scheduler submit, or between the
    # submit and the journal ack) is detected by the restarted
    # controller's resume — which resolves the unacked round against the
    # scheduler journal instead of blindly resubmitting
    "study_kill": ("study_resumed",),
}

# Recovery markers per kind, evaluated on events AFTER the detection:
# train-scope faults recover when training demonstrably resumes (a chunk
# with finite loss, or a clean run_end); serve-scope faults recover on the
# matching re-admission/recovery mitigation.
_SERVE_RECOVERERS: dict[str, tuple[str, ...]] = {
    "replica_error": ("replica_readmitted",),
    "replica_slow": ("replica_readmitted",),
    "batcher_crash": ("serving_recovered", "batcher_restarted"),
}

# Scheduler faults recover when the queue demonstrably moves again: a
# unit (or the whole job) completing AFTER the detection proves the
# stolen/recovered work actually ran to the end — a clean run_end alone
# would also say so, but the job event is the sharper signal.
_SCHED_FAULT_KINDS = ("sched_worker_kill", "lease_expire", "journal_torn")

# Streaming faults recover when the control plane demonstrably moves
# again AFTER detection: a fresh publish (trainer side) or a promoted
# deploy (deployer side) — the always-on loop's own terminal records.
_STREAM_FAULT_KINDS = ("stream_mid_publish_kill", "stream_deployer_kill",
                       "stream_poison")


def _chunk_loss_finite(event: dict) -> bool:
    vals = _as_floats(event.get("loss"))
    return bool(vals) and all(math.isfinite(v) for v in vals)


def _marks_recovery(kind: str, event: dict) -> bool:
    if kind in _SERVE_RECOVERERS:
        return (event.get("type") == "mitigation"
                and event.get("mtype") in _SERVE_RECOVERERS[kind])
    if kind in _SCHED_FAULT_KINDS:
        return (event.get("type") == "job"
                and event.get("action") in ("unit_done", "done"))
    if kind in _STREAM_FAULT_KINDS:
        return (event.get("type") == "publish"
                or (event.get("type") == "deploy"
                    and event.get("action") == "promoted")
                or (event.get("type") == "run_end"
                    and event.get("status") == "ok"))
    if event.get("type") == "chunk":
        return _chunk_loss_finite(event)
    return (event.get("type") == "run_end"
            and event.get("status") == "ok")


def faults_rollup(events) -> dict | None:
    """Injected vs detected vs recovered over a stream's ``fault`` events.

    Computed over the GLOBAL event list (faults fire in the worker,
    stall/crash mitigations land from the supervisor process — scoping to
    one process would blind the join). Deltas use the wall-clock ``t``
    envelope field, the only clock shared across processes. None when the
    stream carries no injections (normal runs).
    """
    ordered = sorted(events, key=lambda e: e.get("t", 0.0))
    faults = [e for e in ordered if e.get("type") == "fault"]
    if not faults:
        return None
    per_fault = []
    for fault in faults:
        kind = fault.get("kind", "?")
        t0 = fault.get("t", 0.0)
        # An UNREGISTERED kind scores undetected — defaulting to "any
        # later mitigation counts" would let a routine unrelated
        # mitigation wave a genuinely undetected fault past the compare
        # gate. (http_malformed intentionally has no detector: its
        # containment evidence is HTTP status codes, so drills record it
        # in FAULT_DRILL.json rather than as fault events.)
        detectors = _FAULT_DETECTORS.get(kind, ())
        record: dict = {"kind": kind, "spec": fault.get("spec")}

        def _identity_matches(event: dict) -> bool:
            # when BOTH sides name a replica, the join must respect it —
            # replica 0's ejection must not mark replica 1's injected
            # fault "detected" and wave a broken path past the gate
            fr, mr = fault.get("replica"), event.get("replica")
            return fr is None or mr is None or fr == mr

        detection = next(
            (e for e in ordered
             if e.get("t", 0.0) >= t0 and e.get("type") == "mitigation"
             and e.get("mtype") in detectors and _identity_matches(e)),
            None,
        )
        record["detected"] = detection is not None
        if detection is not None:
            record["detected_by"] = detection.get("mtype")
            record["time_to_detect_s"] = round(
                detection.get("t", t0) - t0, 3)
            recovery = next(
                (e for e in ordered
                 if e.get("t", 0.0) >= detection.get("t", t0)
                 and e is not detection and _marks_recovery(kind, e)
                 and _identity_matches(e)),   # replica 0's readmission is
                 # not replica 1's recovery
                None,
            )
            record["recovered"] = recovery is not None
            if recovery is not None:
                record["time_to_recover_s"] = round(
                    recovery.get("t", t0) - t0, 3)
        else:
            record["recovered"] = False
        per_fault.append(record)

    def _stats(key):
        vals = [r[key] for r in per_fault if key in r]
        if not vals:
            return None
        return {"mean": round(sum(vals) / len(vals), 3),
                "max": round(max(vals), 3)}

    by_kind: dict[str, dict] = {}
    for r in per_fault:
        entry = by_kind.setdefault(
            r["kind"], {"injected": 0, "detected": 0, "recovered": 0})
        entry["injected"] += 1
        entry["detected"] += r["detected"]
        entry["recovered"] += r["recovered"]
    rollup = {
        "injected": len(per_fault),
        "detected": sum(r["detected"] for r in per_fault),
        "recovered": sum(r["recovered"] for r in per_fault),
        "undetected": [r["kind"] for r in per_fault if not r["detected"]],
        "by_kind": by_kind,
        "faults": per_fault,
    }
    for key in ("time_to_detect_s", "time_to_recover_s"):
        stats = _stats(key)
        if stats is not None:
            rollup[key] = stats
    return rollup


def scheduler_rollup(events) -> dict | None:
    """Queue-health view of a stream's ``job``/``lease`` events
    (``dib_tpu/sched``): job/unit outcome counts, lease transition
    counts (``leases_expired`` is the SLO ceiling's metric), the worst
    per-unit retry count (``unit_retries_max`` vs the retry-budget
    ceiling), and queue-wait percentiles from lease grants
    (``queue_wait_p99_s`` vs its ceiling — see SLO.json). None when the
    stream carries no scheduler events (ordinary runs).

    Multi-tenant fleets (docs/scheduling.md) additionally get a
    ``tenants`` block (per-tenant job/unit outcomes, admission rejects,
    and queue-wait percentiles), ``admission_rejected`` /
    ``admission_reject_frac`` (rejects over admission attempts — the
    ``sched_admission_reject_ceiling`` SLO metric), and
    ``tenant_wait_p99_ratio`` (worst tenant queue-wait p99 over the
    fleet median — the ``sched_starvation_ceiling`` metric; a fair
    scheduler keeps it near 1 even under a greedy-tenant flood). All
    absent on single-tenant streams whose events carry no tenant.
    """
    jobs = [e for e in events if e.get("type") == "job"]
    leases = [e for e in events if e.get("type") == "lease"]
    if not jobs and not leases:
        return None
    job_actions: dict[str, int] = {}
    for e in jobs:
        a = e.get("action", "?")
        job_actions[a] = job_actions.get(a, 0) + 1
    lease_actions: dict[str, int] = {}
    for e in leases:
        a = e.get("action", "?")
        lease_actions[a] = lease_actions.get(a, 0) + 1
    out: dict = {
        "jobs": {
            "submitted": job_actions.get("submitted", 0),
            "done": job_actions.get("done", 0),
            "failed": job_actions.get("failed", 0),
        },
        "units": {
            "submitted": sum(e.get("units") or 0 for e in jobs
                             if e.get("action") == "submitted"),
            "done": job_actions.get("unit_done", 0),
            "failed_attempts": job_actions.get("unit_failed", 0),
        },
        "leases": lease_actions,
        "leases_expired": lease_actions.get("expired", 0),
        "leases_rejected": lease_actions.get("rejected", 0),
    }
    # `retries` on a unit_failed event is the job's retries_used AFTER
    # that failure, so the max over the stream is the worst per-job spend
    retries = [e.get("retries") for e in jobs
               if e.get("action") == "unit_failed"
               and isinstance(e.get("retries"), (int, float))]
    out["retries_max"] = int(max(retries)) if retries else 0
    waits = sorted(e.get("queue_wait_s") for e in leases
                   if e.get("action") == "granted"
                   and isinstance(e.get("queue_wait_s"), (int, float)))
    if waits:
        out["queue_wait_p50_s"] = round(_percentile(waits, 0.5), 3)
        out["queue_wait_p99_s"] = round(_percentile(waits, 0.99), 3)
        out["queue_wait_max_s"] = round(waits[-1], 3)

    # ---- multi-tenant fleet view (only when events carry tenants)
    tenants: dict[str, dict] = {}

    def tenant_entry(name: str) -> dict:
        return tenants.setdefault(name, {
            "jobs": 0, "units": 0, "units_done": 0, "units_failed": 0,
            "admission_rejected": 0,
        })

    for e in jobs:
        name = e.get("tenant")
        if not name:
            continue
        action = e.get("action")
        if action == "submitted":
            entry = tenant_entry(name)
            entry["jobs"] += 1
            entry["units"] += int(e.get("units") or 0)
        elif action == "unit_done":
            tenant_entry(name)["units_done"] += 1
        elif action == "unit_failed":
            tenant_entry(name)["units_failed"] += 1
        elif action == "rejected":
            tenant_entry(name)["admission_rejected"] += 1
    tenant_waits: dict[str, list[float]] = {}
    for e in leases:
        if (e.get("action") == "granted" and e.get("tenant")
                and isinstance(e.get("queue_wait_s"), (int, float))):
            tenant_waits.setdefault(e["tenant"], []).append(
                float(e["queue_wait_s"]))
    for name, vals in tenant_waits.items():
        vals.sort()
        entry = tenant_entry(name)
        entry["queue_wait_p50_s"] = round(_percentile(vals, 0.5), 3)
        entry["queue_wait_p99_s"] = round(_percentile(vals, 0.99), 3)
    if tenants:
        out["tenants"] = tenants
        rejected = sum(t["admission_rejected"] for t in tenants.values())
        admitted = sum(t["jobs"] for t in tenants.values())
        out["admission_rejected"] = rejected
        out["admission_reject_frac"] = round(
            rejected / max(rejected + admitted, 1), 4)
        p99s = sorted(t["queue_wait_p99_s"] for t in tenants.values()
                      if "queue_wait_p99_s" in t)
        if len(p99s) >= 2:
            median = _percentile(p99s, 0.5)
            out["tenant_wait_p99_ratio"] = round(
                p99s[-1] / max(median, 1e-9), 3)
    return out


def streaming_rollup(events) -> dict | None:
    """Control-plane view of a stream's ``publish``/``deploy``/``drift``
    events (``dib_tpu/stream``, docs/streaming.md). A trainer stream
    carries publishes and drifts; a deployer stream carries deploys —
    the rollup reports whichever are present, and the deploy-side keys
    are what the streaming SLO rules gate: ``publish_to_serve_p50_s``/
    ``publish_to_serve_p99_s`` from each deploy's ``latency_s``,
    ``rollbacks``, and the two journal invariants — ``lost_publishes``
    (a publish index below the newest processed one with no deploy
    decision: the deployer skipped it) and ``double_promotions`` (two
    decisions for one publish id). None when the stream carries no
    streaming events."""
    publishes = [e for e in events if e.get("type") == "publish"]
    deploys = [e for e in events if e.get("type") == "deploy"]
    drifts = [e for e in events if e.get("type") == "drift"]
    if not publishes and not deploys and not drifts:
        return None
    out: dict = {}
    if publishes:
        out["publishes"] = len(publishes)
    if drifts:
        out["drifts"] = len(drifts)
    if deploys:
        out["deploys"] = len(deploys)
        out["promoted"] = sum(e.get("action") == "promoted"
                              for e in deploys)
        out["rollbacks"] = sum(e.get("action") == "rolled_back"
                               for e in deploys)
        latencies = sorted(e.get("latency_s") for e in deploys
                           if isinstance(e.get("latency_s"), (int, float)))
        if latencies:
            out["publish_to_serve_p50_s"] = round(
                _percentile(latencies, 0.5), 3)
            out["publish_to_serve_p99_s"] = round(
                _percentile(latencies, 0.99), 3)
        by_publish: dict[str, int] = {}
        for e in deploys:
            pid = str(e.get("publish_id"))
            by_publish[pid] = by_publish.get(pid, 0) + 1
        out["double_promotions"] = sum(
            1 for c in by_publish.values() if c > 1)
        # lost = a gap in the processed publish-INDEX sequence: the
        # trainer numbers publishes 0, 1, 2, … and deploy events copy the
        # index, so an index missing below the newest decided one means
        # the deployer decided a LATER publish without ever deciding this
        # one — the skip the exactly-once contract forbids. Anchored at
        # the SMALLEST index in view, not 0: a restarted deployer with a
        # fresh telemetry dir only carries events for the publishes it
        # decided this launch (earlier ones live in the prior launch's
        # stream), and the deployer structurally processes in order from
        # the journal head — so indices below the view are decided, not
        # lost, and counting them would page stream_lost_publish_max
        # falsely
        indices = {int(e["index"]) for e in deploys
                   if isinstance(e.get("index"), (int, float))}
        out["lost_publishes"] = (
            max(indices) - min(indices) + 1 - len(indices)
            if indices else 0)
    return out


def study_rollup(events) -> dict | None:
    """Closed-loop study view of a stream's ``study`` events
    (``dib_tpu/study``, docs/study.md): rounds run, units
    submitted/done, the latest transition-β ``estimates`` with their
    round-over-round ``deltas_decades`` and ensemble ``band_nats``, the
    budget accounting, and the terminal ``verdict``. The two derived
    gate keys are what the SLO rows read: ``rounds_over_budget``
    (``study_rounds_ceiling`` — a controller refining past its own round
    budget is a runaway loop) and ``unconverged_full_budget``
    (``study_unconverged_max`` — a study that spent its whole budget
    without the estimates stabilizing needs a human, not more units).
    None when the stream carries no study events (ordinary runs skip
    both rules)."""
    studies = [e for e in events if e.get("type") == "study"]
    if not studies:
        return None
    out: dict = {}
    study_id = next((e.get("study_id") for e in studies
                     if e.get("study_id")), None)
    if study_id is not None:
        out["study_id"] = study_id
    out["rounds"] = sum(1 for e in studies if e.get("action") == "round")
    out["units_submitted"] = sum(
        e.get("units") or 0 for e in studies
        if e.get("action") == "submit")
    # unit completions ride the scheduler's job events on the SAME
    # stream (the controller hands its writer to the scheduler)
    out["units_done"] = sum(
        1 for e in events if e.get("type") == "job"
        and e.get("action") == "unit_done")
    last_round = next((e for e in reversed(studies)
                       if e.get("action") == "round"), None)
    if last_round is not None:
        if last_round.get("estimates"):
            out["estimates"] = last_round["estimates"]
        if last_round.get("deltas_decades"):
            out["deltas_decades"] = last_round["deltas_decades"]
        if last_round.get("band_nats") is not None:
            out["band_nats"] = last_round["band_nats"]
    verdict = next((e for e in reversed(studies)
                    if e.get("action") in ("converged", "unconverged",
                                           "no_transitions")), None)
    if verdict is not None:
        out["verdict"] = verdict["action"]
    spent = next((e.get("budget_spent") for e in reversed(studies)
                  if e.get("budget_spent") is not None), None)
    if spent is not None:
        out["budget_spent"] = spent
    budget_max = next((e.get("budget_max") for e in reversed(studies)
                       if e.get("budget_max") is not None), None)
    if budget_max is not None:
        out["budget_max"] = budget_max
    max_rounds = next((e.get("max_rounds") for e in reversed(studies)
                       if e.get("max_rounds") is not None), None)
    if max_rounds is not None:
        out["max_rounds"] = max_rounds
    out["rounds_over_budget"] = (
        max(out["rounds"] - max_rounds, 0) if max_rounds is not None
        else 0)
    # the gate key is the verdict itself: the controller's _decide ends
    # a study unconverged when it cannot produce a stable localized
    # estimate — budget (rounds/units) exhausted, every unit failed, or
    # refinement saturated with unresolved ensemble disagreement — and
    # all of those need a human before more units are spent
    out["unconverged_full_budget"] = int(
        (verdict or {}).get("action") == "unconverged")
    return out


def autopilot_rollup(events) -> dict | None:
    """Drift-autopilot view of a stream (``dib_tpu/autopilot``,
    docs/streaming.md "Closed loop"): the traffic→drift→study→re-anneal
    control plane's ``autopilot`` + ``breaker`` events folded into the
    counts the SLO rules read. ``duplicate_studies`` (rounds that minted
    more than one study intent) is the exactly-once gate
    (``autopilot_duplicate_study_max``); ``breaker_trips`` feeds
    ``autopilot_breaker_trip_ceiling``; ``drift_to_apply_p99_s`` —
    drift event to re-anneal schedule applied, from the ``applied``
    records' own clocks — feeds ``drift_to_apply_p99_ceiling``. None
    when the stream carries no autopilot activity (ordinary runs skip
    all three rules)."""
    pilots = [e for e in events if e.get("type") == "autopilot"]
    breakers = [e for e in events if e.get("type") == "breaker"]
    if not pilots and not breakers:
        return None
    out: dict = {}
    out["intents"] = sum(1 for e in pilots if e.get("action") == "intent")
    out["studies"] = sum(1 for e in pilots
                         if e.get("action") == "submitted")
    out["applied"] = sum(1 for e in pilots if e.get("action") == "applied")
    skips = [e for e in pilots if e.get("action") == "skip"]
    out["skipped"] = len(skips)
    reasons: dict[str, int] = {}
    for e in skips:
        reason = str(e.get("reason") or "unknown")
        reasons[reason] = reasons.get(reason, 0) + 1
    out["skip_reasons"] = {k: reasons[k] for k in sorted(reasons)}
    # exactly-once gate: every drift round may mint AT MOST one study
    # intent across every restart of the supervisor
    intent_rounds: dict[int, int] = {}
    for e in pilots:
        if e.get("action") == "intent" and e.get("round") is not None:
            idx = int(e["round"])
            intent_rounds[idx] = intent_rounds.get(idx, 0) + 1
    out["duplicate_studies"] = sum(
        1 for n in intent_rounds.values() if n > 1)
    out["breaker_trips"] = sum(1 for e in breakers
                               if e.get("action") == "trip")
    out["breaker_resets"] = sum(1 for e in breakers
                                if e.get("action") == "reset")
    last_flip = next((e for e in reversed(breakers)
                      if e.get("action") in ("trip", "reset")), None)
    out["breaker_open"] = int(
        last_flip is not None and last_flip["action"] == "trip")
    latencies = sorted(
        float(e["drift_to_apply_s"]) for e in pilots
        if e.get("action") == "applied"
        and isinstance(e.get("drift_to_apply_s"), (int, float)))
    if latencies:
        out["drift_to_apply_p50_s"] = _percentile(latencies, 0.50)
        out["drift_to_apply_p99_s"] = _percentile(latencies, 0.99)
    last_applied = next((e.get("round") for e in reversed(pilots)
                         if e.get("action") == "applied"
                         and e.get("round") is not None), None)
    if last_applied is not None:
        out["last_applied_round"] = int(last_applied)
    return out


def integrity_rollup(events) -> dict | None:
    """Numerical-integrity view of a stream (ISSUE 14,
    docs/robustness.md "Numerical integrity"): the β-aware anomaly
    detector's verdicts (``anomaly`` events), the rollbacks they
    provoked, and every checkpoint step moved to ``quarantine/``
    (``quarantine`` events) — corrupt at restore, flagged by ``ckpt
    scrub``, or written during an anomalous window. ``anomaly_rollbacks``
    is what the ``anomaly_rollback_ceiling`` SLO rule gates. None when
    the stream carries no integrity events (clean runs skip the rule).
    """
    anomalies = [e for e in events if e.get("type") == "anomaly"]
    quarantines = [e for e in events if e.get("type") == "quarantine"]
    mitigations = [e for e in events if e.get("type") == "mitigation"]
    anomaly_rollbacks = [m for m in mitigations
                         if m.get("mtype") == "anomaly_rollback"]
    divergence_rollbacks = [m for m in mitigations
                            if m.get("mtype") == "divergence_rollback"]
    fallbacks = [m for m in mitigations
                 if m.get("mtype") == "checkpoint_fallback"]
    if not anomalies and not quarantines and not anomaly_rollbacks:
        return None
    out: dict = {}
    out["anomalies"] = len(anomalies)
    out["anomaly_channels"] = sorted(
        {str(e.get("channel")) for e in anomalies if e.get("channel")})
    out["anomaly_rollbacks"] = len(anomaly_rollbacks)
    out["divergence_rollbacks"] = len(divergence_rollbacks)
    out["quarantines"] = len(quarantines)
    out["quarantined_steps"] = sorted(
        {int(e["step"]) for e in quarantines
         if isinstance(e.get("step"), (int, float))})
    out["checkpoint_fallbacks"] = len(fallbacks)
    return out


def mesh_rollup(events) -> dict | None:
    """Mesh-execution view of a run (``parallel/sweep.py`` shard_map
    engine + mesh-shape-portable checkpoints, docs/parallelism.md).

    ``axes``/``engine`` come from the run_start provenance manifest
    (``mesh_shape``/``sweep_engine``); ``reshards``/``backfills`` count
    the ``sweep_reshard``/``member_backfill`` mitigations restores emit,
    with each reshard's width/layout transition listed under
    ``reshard_events``. None for runs with neither a mesh manifest nor
    elastic activity — serial runs carry no mesh block.
    """
    out: dict = {}
    for e in events:
        if e.get("type") != "run_start":
            continue
        manifest = e.get("manifest") or {}
        if manifest.get("mesh_shape"):
            out["axes"] = manifest["mesh_shape"]
        if manifest.get("sweep_engine"):
            out["engine"] = manifest["sweep_engine"]
    reshards = [e for e in events if e.get("type") == "mitigation"
                and e.get("mtype") == "sweep_reshard"]
    backfills = [e for e in events if e.get("type") == "mitigation"
                 and e.get("mtype") == "member_backfill"]
    if reshards:
        out["reshards"] = len(reshards)
        out["reshard_events"] = [
            {k: e.get(k) for k in ("saved_width", "restored_width",
                                   "saved_mesh_axes", "mesh_axes")
             if e.get(k) is not None}
            for e in reshards
        ]
    if backfills:
        out["backfills"] = len(backfills)
        out["backfilled_replicas"] = sorted(
            {e.get("replica") for e in backfills
             if e.get("replica") is not None})
    return out or None


def _utilization_rollup(compiles, rollup: dict, device_kind) -> dict:
    """Join cost-analyzed ``compile`` events with span durations into
    per-callable roofline coordinates. A compiled callable matches the span
    whose path's last segment equals its name (or the whole path does);
    durations are the span's MEAN, so partial final chunks blur slightly —
    the live gauges in the ``metrics`` event are the per-chunk-exact view."""
    from dib_tpu.telemetry.xla_stats import achieved, backend_peaks

    peaks = backend_peaks(device_kind)
    util = {}
    for c in compiles:
        if not (c.get("flops") or c.get("bytes_accessed")):
            continue
        name = c.get("name", "?")
        # compiled callables carry their method names ("run_chunk",
        # "channel_mi_bounds") while spans carry phase names ("chunk",
        # "mi_bounds") — match modulo the conventional verb prefix
        aliases = {name, name.removeprefix("run_"),
                   name.removeprefix("channel_")}
        span = next(
            (s for p, s in rollup.items()
             if p in aliases or p.split("/")[-1] in aliases), None
        )
        entry = {
            "flops": c.get("flops"),
            "bytes_accessed": c.get("bytes_accessed"),
        }
        if span is not None:
            entry["span_mean_s"] = span["mean_s"]
            entry["span_count"] = span["count"]
            entry.update({
                k: round(v, 6) for k, v in achieved(
                    span["mean_s"], flops=c.get("flops"),
                    bytes_accessed=c.get("bytes_accessed"), peaks=peaks,
                ).items()
            })
        util[name] = entry
    if util and peaks:
        util["_peaks"] = peaks
    return util


def summarize(path: str, process_index: int | None = None,
              run_id: str | None = None) -> dict:
    """Roll an events.jsonl (or its run dir) into one flat summary record.

    A supervised run's stream holds several ``run_start`` events (one per
    watchdog relaunch) plus the supervisor's ``mitigation`` events; the
    summary reports the LAST manifest (the run that finished) and counts
    chunks/steps across all launches — that is the honest end-to-end view
    the watchdog report takes too. ``run_id`` restricts to one run's
    events (for streams several invocations appended to, e.g. a reused
    ``DIB_BENCH_TELEMETRY_DIR``).

    Multihost: in an SPMD run EVERY process emits chunk/mi_bounds events
    for the SAME global training, so with no explicit ``process_index``
    the per-run totals (launches, steps, throughput, finals) are computed
    from the lowest process index present — summing across processes
    would multiply steps/s by ``process_count``. Mitigations and event
    counts stay global.
    """
    events = list(read_events(path, process_index=process_index))
    if run_id is not None:
        events = [e for e in events if e.get("run") == run_id]
    if not events:
        raise ValueError(
            f"{path}: no telemetry events"
            + (f" for run_id {run_id!r}" if run_id is not None else "")
            + " (expected an events.jsonl stream or its run dir)"
        )

    def of_type(t, pool):
        return [e for e in pool if e.get("type") == t]

    mitigations = of_type("mitigation", events)
    per_run = events
    if process_index is None:
        chunk_procs = {e.get("proc", 0) for e in of_type("chunk", events)}
        if len(chunk_procs) > 1:
            lead = min(chunk_procs)
            per_run = [e for e in events if e.get("proc", 0) == lead]
    if not any(e.get("type") for e in events):
        # e.g. a bench one-liner or arbitrary JSON handed to summarize:
        # every line parsed, but nothing is an event
        raise ValueError(
            f"{path}: parsed {len(events)} JSON line(s) but none carry an "
            "event 'type' — not a telemetry stream"
        )
    run_starts = of_type("run_start", per_run)
    chunks = of_type("chunk", per_run)
    compiles = of_type("compile", per_run)
    hooks = of_type("hook", per_run)
    mi_events = of_type("mi_bounds", per_run)
    run_ends = of_type("run_end", per_run)

    total_steps = sum(c.get("steps") or 0 for c in chunks)
    total_chunk_s = sum(c.get("seconds") or 0.0 for c in chunks)
    steps_per_s = total_steps / total_chunk_s if total_chunk_s > 0 else None

    # Steady state excludes each launch's first chunk (compile-laden):
    # walk the stream in order and drop the first chunk after every
    # run_start — robust to whatever other events a launch emits in
    # between, and to (run, seq) collisions across relaunched writers.
    steady = []
    awaiting_first_chunk = False
    for e in per_run:
        if e.get("type") == "run_start":
            awaiting_first_chunk = True
        elif e.get("type") == "chunk":
            if awaiting_first_chunk:
                awaiting_first_chunk = False
            else:
                steady.append(e)
    steady_steps = sum(c.get("steps") or 0 for c in steady)
    steady_s = sum(c.get("seconds") or 0.0 for c in steady)
    steady_steps_per_s = steady_steps / steady_s if steady_s > 0 else steps_per_s

    summary: dict = {
        "metric": "run_telemetry_summary",
        "value": round(steps_per_s, 3) if steps_per_s else None,
        "unit": "steps_per_s",
        "schema_version": SCHEMA_VERSION,
        "num_events": len(events),
        "launches": len(run_starts),
        "num_chunks": len(chunks),
        "total_steps": total_steps,
        "total_chunk_s": round(total_chunk_s, 3),
        "steps_per_s": round(steps_per_s, 3) if steps_per_s else None,
        "steady_steps_per_s": (
            round(steady_steps_per_s, 3) if steady_steps_per_s else None
        ),
        "processes": sorted({e.get("proc", 0) for e in events}),
    }

    runs: list[str] = []
    for e in events:
        if e.get("run") is not None and e["run"] not in runs:
            runs.append(e["run"])
    if len(runs) > 1:
        summary["runs"] = runs
    if run_id is None:
        # Several run_starts are the supervised-run norm (one per watchdog
        # relaunch of the SAME training) and aggregate honestly; several
        # DIFFERENT configs mean independent invocations appended to a
        # reused dir, whose blended totals gate on garbage — scope with
        # run_id (CLI: --run-id).
        hashes = {s.get("manifest", {}).get("config_hash")
                  for s in of_type("run_start", events)}
        hashes.discard(None)
        if len(hashes) > 1:
            warnings.warn(
                f"{path}: {len(runs)} runs with {len(hashes)} distinct "
                "config hashes blended into one summary — pass run_id= "
                "(CLI: --run-id) to scope to one run"
            )

    if run_starts:
        manifest = run_starts[-1].get("manifest", {})
        summary["run_id"] = run_starts[-1]["run"]
        for key in ("git_sha", "device_kind", "device_platform",
                    "device_count", "process_count", "config_hash",
                    "mode"):
            if key in manifest:
                summary[key] = manifest[key]
    if run_starts and run_ends:
        summary["wall_clock_s"] = round(run_ends[-1]["t"] - run_starts[0]["t"], 3)
    # Status comes from the LAST launch's terminal record; a launch that
    # never reached run_end (SIGKILL, still in flight) is visibly
    # "incomplete", never silently "ok" from an earlier launch.
    last_end = None
    if run_starts:
        ends_for_last = [e for e in run_ends
                         if e.get("run") == run_starts[-1]["run"]]
        last_end = ends_for_last[-1] if ends_for_last else None
    elif run_ends:
        last_end = run_ends[-1]
    summary["status"] = (last_end.get("status") if last_end is not None
                         else "incomplete")

    if chunks:
        last = chunks[-1]
        summary["final_epoch"] = last.get("epoch")
        for key in ("loss", "val_loss", "beta"):
            if last.get(key) is not None:
                vals = _as_floats(last[key])
                summary[f"final_{key}"] = _enc(
                    vals[0] if len(vals) == 1 else vals
                )
        kl = _as_floats(last.get("kl_per_feature"))
        if kl:
            summary["final_total_kl"] = _enc(sum(kl))
        elif last.get("kl_total") is not None:
            totals = _as_floats(last["kl_total"])
            summary["final_total_kl"] = _enc(
                totals[0] if len(totals) == 1 else totals
            )

    if mi_events:
        last = mi_events[-1]
        lower = _as_floats(last.get("lower_bits"))
        upper = _as_floats(last.get("upper_bits"))
        if not lower:  # nats-tagged emitters
            lower = [x / _LN2 for x in _as_floats(last.get("lower_nats"))]
            upper = [x / _LN2 for x in _as_floats(last.get("upper_nats"))]
        if lower:
            summary["final_mi_lower_bits_mean"] = _enc(round(_mean(lower), 4))
        if upper:
            summary["final_mi_upper_bits_mean"] = _enc(round(_mean(upper), 4))
        summary["mi_checkpoints"] = len(mi_events)

    counts: dict[str, int] = {}
    for m in mitigations:
        counts[m.get("mtype", "unknown")] = counts.get(m.get("mtype", "unknown"), 0) + 1
    summary["mitigations"] = counts
    summary["mitigations_total"] = len(mitigations)

    # injected-fault drills (dib_tpu/faults): joined over GLOBAL events —
    # faults fire in the worker, stall/crash detections land from the
    # supervisor process
    faults = faults_rollup(events)
    if faults is not None:
        summary["faults"] = faults

    # β-grid scheduler queue health (dib_tpu/sched): job/lease events are
    # global like mitigations — the pool's workers and the supervisor may
    # emit from different processes onto one stream
    sched = scheduler_rollup(events)
    if sched is not None:
        summary["scheduler"] = sched

    # streaming control plane (dib_tpu/stream): publish/deploy/drift
    # events are global for the same reason — a supervised trainer's
    # relaunches and its supervisor share one stream
    streaming = streaming_rollup(events)
    if streaming is not None:
        summary["streaming"] = streaming

    # closed-loop study controller (dib_tpu/study): study events are
    # global like the scheduler's — the controller and the pool workers
    # it drives share one stream
    study = study_rollup(events)
    if study is not None:
        summary["study"] = study

    # drift-autopilot control plane (dib_tpu/autopilot): the supervisor
    # journals exactly-once, but its telemetry is the fleet-visible view
    # the SLO rules gate — intents/applies/breaker flips are global like
    # the study's (the supervisor and its restarts share one stream)
    autopilot = autopilot_rollup(events)
    if autopilot is not None:
        summary["autopilot"] = autopilot

    # mesh execution plane (parallel/sweep.py shard_map engine +
    # mesh-shape-portable checkpoints): axis sizes from the run_start
    # provenance, reshard/backfill mitigations from restores
    mesh = mesh_rollup(events)
    if mesh is not None:
        summary["mesh"] = mesh

    # numerical-integrity plane (train/anomaly.py + the v3 content-digest
    # checkpoints): anomaly verdicts, the rollbacks they provoked, and
    # quarantined checkpoint steps — global like mitigations (a scrub or
    # supervisor may emit onto the worker's stream)
    integrity = integrity_rollup(events)
    if integrity is not None:
        summary["integrity"] = integrity

    if compiles:
        by_cache: dict[str, int] = {}
        for c in compiles:
            by_cache[c.get("cache", "unknown")] = by_cache.get(c.get("cache", "unknown"), 0) + 1
        summary["compile"] = {
            "events": len(compiles),
            "total_s": round(sum(c.get("seconds") or 0.0 for c in compiles), 3),
            "cache": by_cache,
            # hit/miss counters (utils/compile_cache.py statuses): a
            # recompile storm shows up as a miss count out of line with the
            # baseline's, without digging through individual events
            "cache_hits": by_cache.get("warm", 0),
            "cache_misses": (by_cache.get("cold-populating", 0)
                             + by_cache.get("cold", 0)),
        }

    span_events = of_type("span", per_run)
    if span_events:
        rollup = span_rollup(span_events)
        summary["spans"] = rollup
        summary["span_hotspots"] = span_hotspots(rollup)
        util = _utilization_rollup(compiles, rollup,
                                   summary.get("device_kind"))
        if util:
            summary["utilization"] = util
        # the final metrics event's counters carry the zoo cache stats
        # (snapshots are flat dicts: "counters.serve.cache.response.hits")
        counter_snaps = of_type("metrics", per_run)
        counters = None
        if counter_snaps:
            snaps = counter_snaps[-1].get("snapshots") or []
            if snaps:
                counters = {k[len("counters."):]: v
                            for k, v in snaps[0].items()
                            if k.startswith("counters.")}
        serving = serving_rollup(span_events, counters=counters)
        if serving:
            summary["serving"] = serving
        overlap = overlap_rollup(span_events)
        if overlap:
            summary["overlap"] = overlap
            if overlap.get("exposed_frac") is not None:
                # flat alias the compare gate reads (a regression = the
                # overlapped measurement exposing more of its wall-clock)
                summary["overlap_exposed_frac"] = overlap["exposed_frac"]

    mem_device = [((c.get("memory") or {}).get("peak_bytes_in_use"))
                  for c in chunks]
    # sandboxed kernels hide VmHWM: fall back to the max sampled RSS,
    # which is a chunk-boundary high-water mark of its own
    mem_host = [(c.get("host_memory") or {}).get(
                    "peak_rss_bytes", (c.get("host_memory") or {}).get(
                        "rss_bytes"))
                for c in chunks]
    mem_device = [m for m in mem_device if m is not None]
    mem_host = [m for m in mem_host if m is not None]
    if mem_device or mem_host:
        summary["memory"] = {}
        if mem_device:
            summary["memory"]["device_peak_bytes"] = max(mem_device)
        if mem_host:
            summary["memory"]["host_peak_rss_bytes"] = max(mem_host)

    if hooks:
        by_hook: dict[str, float] = {}
        for h in hooks:
            by_hook[h.get("name", "?")] = (
                by_hook.get(h.get("name", "?"), 0.0) + (h.get("seconds") or 0.0)
            )
        summary["hook_s"] = {k: round(v, 4) for k, v in by_hook.items()}
        # instrumentation share: host-hook wall-clock as a fraction of the
        # run's train+hook time — the SLO overhead ceiling gates on it
        hook_total = sum(by_hook.values())
        if total_chunk_s > 0:
            summary["overhead"] = {
                "hook_s_total": round(hook_total, 4),
                "hook_frac": round(
                    hook_total / (total_chunk_s + hook_total), 6),
            }

    # headline MFU alias: the chunk program's roofline FLOP fraction (the
    # SLO mfu floor and the run registry read this without digging through
    # the per-callable utilization table)
    util = summary.get("utilization") or {}
    for name in ("run_chunk", "sweep_chunk"):
        frac = (util.get(name) or {}).get("flops_frac_of_peak")
        if frac is not None:
            summary["mfu"] = frac
            break

    # Heartbeat coverage (docs/observability.md): the liveness signal's
    # max silent gap, measured over the lead process's beats INCLUDING the
    # edges (run_start -> first beat, last beat -> run_end) — a worker that
    # died silent mid-run shows the gap even though no beat recorded it.
    # Present only when the stream carries heartbeats (older streams gate
    # as "not comparable", never as a fake zero-gap).
    heartbeats = of_type("heartbeat", per_run)
    if heartbeats:
        stamps = [e.get("t", 0.0) for e in heartbeats]
        for edge in of_type("run_start", per_run) + run_ends:
            stamps.append(edge.get("t", 0.0))
        stamps.sort()
        max_gap = max(
            (b - a for a, b in zip(stamps, stamps[1:])), default=0.0)
        intervals = [e.get("interval_s") for e in heartbeats
                     if e.get("interval_s")]
        summary["heartbeats"] = {
            "count": len(heartbeats),
            "boundary_beats": sum(
                1 for e in heartbeats if e.get("phase") == "boundary"),
            "max_gap_s": round(max_gap, 3),
            "interval_s": intervals[-1] if intervals else None,
        }
        summary["heartbeat_max_gap_s"] = round(max_gap, 3)

    # SLO engine residue (telemetry/slo.py): durable alerts + info-plane
    # transitions, counted so `compare`/dashboards see them at a glance
    alerts = of_type("alert", events)
    if alerts:
        by_rule: dict[str, int] = {}
        for a in alerts:
            by_rule[a.get("rule", "?")] = by_rule.get(a.get("rule", "?"), 0) + 1
        summary["alerts"] = {"count": len(alerts), "by_rule": by_rule}
    transitions = of_type("transition", events)
    if transitions:
        summary["transitions"] = {
            "count": len(transitions),
            "channels": sorted({t.get("channel") for t in transitions
                                if t.get("channel") is not None}),
            "down": sum(1 for t in transitions
                        if t.get("direction") == "down"),
            "up": sum(1 for t in transitions if t.get("direction") == "up"),
        }

    metrics_events = of_type("metrics", per_run)
    if metrics_events:
        # last end-of-fit rollup, lead process's flat snapshot (chunk-time
        # percentiles, step counters — see telemetry/metrics.py)
        snaps = metrics_events[-1].get("snapshots") or []
        if snaps:
            summary["metrics"] = {
                k: v for k, v in snaps[0].items() if k != "proc"
            }
    return summary


# Gated fields: (summary key, bad direction). "down" = a drop beyond the
# threshold regresses (throughput, MI lower bound); "up" = a rise does
# (losses). Mitigations are gated separately — ANY increase regresses.
_GATES: Sequence[tuple[str, str]] = (
    ("steps_per_s", "down"),
    ("steady_steps_per_s", "down"),
    ("final_loss", "up"),
    ("final_val_loss", "up"),
    ("final_mi_lower_bits_mean", "down"),
    # silent-gap regression: the longest interval with no heartbeat grew —
    # a run that goes dark for longer than its baseline did is a liveness
    # regression even when throughput held (docs/observability.md)
    ("heartbeat_max_gap_s", "up"),
    # overlap regression: the overlapped measurement's exposed fraction
    # grew — MI bounds are serializing chunk boundaries again
    # (docs/performance.md "Overlapped measurement")
    ("overlap_exposed_frac", "up"),
)


def compare(
    summary_a: dict, summary_b: dict, threshold: float = 0.05
) -> tuple[dict, bool]:
    """Diff run B (candidate) against run A (baseline).

    Returns ``(report, regressed)``. A field regresses when its RELATIVE
    move in the bad direction exceeds ``threshold``. Per-replica LIST
    fields (sweep runs' final losses) gate on their MEAN — skipping them
    silently would leave the flagship sweep runs ungated on quality.
    Comparisons where either side is missing or unusable are reported
    with an explicit ``"gated": false``.
    """

    def scalarize(v):
        # "NaN"/"Infinity" string spellings (events.py's strict-JSON
        # encoding of a diverged run) parse back to real floats here
        if isinstance(v, str):
            try:
                v = float(v)
            except ValueError:
                return None
        if isinstance(v, bool) or v is None:
            return None
        if isinstance(v, (int, float)):
            return float(v)
        if isinstance(v, (list, tuple)):
            nums = [scalarize(x) for x in v]
            if v and all(x is not None for x in nums):
                return sum(nums) / len(nums)
            return None
        return None

    fields: dict[str, dict] = {}
    regressed = False
    for key, bad in _GATES:
        a_raw, b_raw = summary_a.get(key), summary_b.get(key)
        row: dict = {"a": a_raw, "b": b_raw, "bad_direction": bad}
        a, b = scalarize(a_raw), scalarize(b_raw)
        if isinstance(a_raw, (list, tuple)) or isinstance(b_raw, (list, tuple)):
            row["gated_on"] = "mean"
        if a is not None and math.isfinite(a) \
                and b is not None and math.isfinite(b):
            row["delta"] = round(b - a, 6)
            denom = max(abs(a), 1e-12)
            rel = (b - a) / denom
            row["rel"] = round(rel, 6)
            row["regressed"] = (
                rel < -threshold if bad == "down" else rel > threshold
            )
        elif (a is not None and math.isfinite(a)
              and b is not None and not math.isfinite(b)):
            # a finite baseline against a diverged candidate: that is THE
            # regression the gate exists for, not an ungateable comparison
            row["regressed"] = True
            row["reason"] = "candidate non-finite"
        else:
            row["gated"] = False
            row["regressed"] = False
        regressed = regressed or row["regressed"]
        fields[key] = row

    a_mit = summary_a.get("mitigations_total", 0) or 0
    b_mit = summary_b.get("mitigations_total", 0) or 0
    fields["mitigations_total"] = {
        "a": a_mit, "b": b_mit, "delta": b_mit - a_mit,
        "bad_direction": "up",
        # reliability, not noise: one extra kill/restart is a regression
        "regressed": b_mit > a_mit,
    }
    regressed = regressed or b_mit > a_mit

    # Per-phase latency gates (docs/observability.md "Request anatomy"):
    # a serving phase's p99 growing past threshold is gated like any
    # scalar, but with a small ABSOLUTE floor — µs-scale phases (parse on
    # a tiny body) jitter by whole multiples without meaning anything, so
    # only moves of at least 0.1 ms can regress. Gated dynamically over
    # the phases PRESENT IN BOTH summaries (a phase one side never
    # traversed is not comparable).
    a_phases = (summary_a.get("serving") or {}).get("phases") or {}
    b_phases = (summary_b.get("serving") or {}).get("phases") or {}
    for phase in sorted(set(a_phases) & set(b_phases)):
        a_p99 = scalarize((a_phases[phase] or {}).get("p99_ms"))
        b_p99 = scalarize((b_phases[phase] or {}).get("p99_ms"))
        row = {"a": a_p99, "b": b_p99, "bad_direction": "up"}
        if a_p99 is not None and math.isfinite(a_p99) \
                and b_p99 is not None and math.isfinite(b_p99):
            row["delta"] = round(b_p99 - a_p99, 6)
            rel = (b_p99 - a_p99) / max(abs(a_p99), 1e-12)
            row["rel"] = round(rel, 6)
            row["regressed"] = rel > threshold \
                and (b_p99 - a_p99) > 0.1
        else:
            row["gated"] = False
            row["regressed"] = False
        regressed = regressed or row["regressed"]
        fields[f"serving_phase_{phase}_p99_ms"] = row

    def undetected(summary):
        f = summary.get("faults") or {}
        return (f.get("injected", 0) or 0) - (f.get("detected", 0) or 0)

    a_und, b_und = undetected(summary_a), undetected(summary_b)
    # An injected fault nobody detected is a broken recovery path — a
    # regression in the candidate REGARDLESS of the baseline (a drilled
    # mitigation that stopped firing must never pass the gate).
    fields["faults_undetected"] = {
        "a": a_und, "b": b_und, "bad_direction": "up",
        "regressed": b_und > 0,
    }
    regressed = regressed or b_und > 0

    if (summary_a.get("config_hash") and summary_b.get("config_hash")
            and summary_a["config_hash"] != summary_b["config_hash"]):
        note = "config_hash differs: runs are not like-for-like"
    else:
        note = None
    report = {
        "threshold": threshold,
        "fields": fields,
        "regressed": regressed,
    }
    if note:
        report["note"] = note
    return report, regressed


def _load_side(path: str, process_index: int | None,
               run_id: str | None = None) -> dict:
    """A compare operand: an events.jsonl / run dir, a precomputed summary
    JSON (detected by its ``metric`` field), or a bench one-liner (its
    summary rides under a ``telemetry`` key — every bench line is a valid
    baseline)."""
    if os.path.isfile(path):
        try:
            with open(path) as f:
                record = json.load(f)
        except (json.JSONDecodeError, UnicodeDecodeError):
            record = None  # multi-line jsonl: summarize below
        if isinstance(record, dict):
            if record.get("metric") == "run_telemetry_summary":
                return record
            embedded = record.get("telemetry")
            if (isinstance(embedded, dict)
                    and embedded.get("metric") == "run_telemetry_summary"):
                return embedded
    return summarize(path, process_index=process_index, run_id=run_id)


def telemetry_main(argv: Sequence[str]) -> int:
    argv = list(argv)
    if argv and argv[0] == "fleet":
        # the fleet aggregator owns its own subparser tree
        # (tail|summarize|report|prometheus over many roots) — dispatch
        # before the single-run parser (docs/observability.md "Fleet
        # causality")
        from dib_tpu.telemetry.fleet import fleet_main

        return fleet_main(argv[1:])
    parser = argparse.ArgumentParser(
        prog="dib_tpu telemetry",
        description="Summarize or diff run event streams (docs/observability.md).",
    )
    sub = parser.add_subparsers(dest="action", required=True)
    p_sum = sub.add_parser("summarize", help="Roll an events.jsonl into one record.")
    p_sum.add_argument("path", help="Run dir or events.jsonl path.")
    p_sum.add_argument("--process-index", type=int, default=None)
    p_sum.add_argument("--run-id", default=None,
                       help="Restrict to one run's events when several "
                            "invocations appended to the same stream.")
    p_sum.add_argument("--indent", action="store_true")
    p_cmp = sub.add_parser("compare", help="Diff run B against baseline A.")
    p_cmp.add_argument("baseline", help="Run dir / events.jsonl / summary JSON.")
    p_cmp.add_argument("candidate", help="Run dir / events.jsonl / summary JSON.")
    p_cmp.add_argument("--threshold", type=float, default=0.05,
                       help="Relative regression threshold (default 0.05).")
    p_cmp.add_argument("--process-index", type=int, default=None)
    p_cmp.add_argument("--run-id-a", default=None,
                       help="Restrict the baseline to one run's events.")
    p_cmp.add_argument("--run-id-b", default=None,
                       help="Restrict the candidate to one run's events.")
    p_cmp.add_argument("--indent", action="store_true")
    p_rep = sub.add_parser(
        "report",
        help="Render a self-contained static HTML run report (span "
             "breakdown, training trajectory, MI bounds, memory, roofline "
             "utilization) — or, with --index, the multi-run fleet index "
             "page with the perf trajectory.")
    p_rep.add_argument("path", nargs="?", default=None,
                       help="Run dir or events.jsonl path (omit with "
                            "--index).")
    p_rep.add_argument("--out", default=None,
                       help="Output HTML path (default: report.html next to "
                            "the events file; index.html under the runs "
                            "root with --index).")
    p_rep.add_argument("--process-index", type=int, default=None)
    p_rep.add_argument("--run-id", default=None,
                       help="Restrict to one run's events.")
    p_rep.add_argument("--index", action="store_true",
                       help="Render the fleet index page from the run "
                            "registry instead of one run's report.")
    p_rep.add_argument("--runs-root", "--runs_root", dest="runs_root",
                       default=None,
                       help="Runs root for --index (default: DIB_RUNS_ROOT "
                            "or ./runs).")
    p_tail = sub.add_parser(
        "tail",
        help="Follow a (growing) events.jsonl and render a live terminal "
             "dashboard: steps/s, loss, per-channel KL, live MFU vs the "
             "backend peak, span hotspots, mitigation/alert ticker, "
             "heartbeat liveness (docs/observability.md).")
    p_tail.add_argument("path", help="Run dir or events.jsonl path (may "
                                     "not exist yet — tail waits).")
    p_tail.add_argument("--refresh-s", type=float, default=1.0,
                        help="Poll/redraw period (default 1s).")
    p_tail.add_argument("--duration-s", type=float, default=None,
                        help="Detach after this many seconds (default: "
                             "until the run ends).")
    p_tail.add_argument("--follow-after-end", action="store_true",
                        help="Keep following after a run_end (supervised "
                             "runs relaunch onto the same stream).")
    p_tail.add_argument("--slo", default=None,
                        help="Evaluate SLO rules live (path to SLO.json); "
                             "violations/transitions are written DURABLY "
                             "onto the run's stream.")
    p_tail.add_argument("--once", action="store_true",
                        help="Render one frame and exit (scripts/tests).")
    p_tail.add_argument("--no-ansi", action="store_true",
                        help="Append frames instead of redrawing in place.")
    p_chk = sub.add_parser(
        "check",
        help="Evaluate a run against the committed SLO budgets "
             "(SLO.json); exits 1 on violation — the compare gate shape, "
             "against absolute budgets instead of a baseline run.")
    p_chk.add_argument("path", help="Run dir or events.jsonl path.")
    p_chk.add_argument("--slo", default=None,
                       help="SLO file (default: SLO.json next to the "
                            "package checkout, then ./SLO.json).")
    p_chk.add_argument("--process-index", type=int, default=None)
    p_chk.add_argument("--run-id", default=None)
    p_chk.add_argument("--no-write", action="store_true",
                       help="Report only; skip the durable alert/"
                            "transition writes.")
    p_chk.add_argument("--indent", action="store_true")
    p_runs = sub.add_parser(
        "runs",
        help="Query the fleet run registry (append-only "
             "<runs-root>/index.jsonl; docs/observability.md).")
    runs_sub = p_runs.add_subparsers(dest="runs_action", required=True)
    p_list = runs_sub.add_parser("list", help="Latest entry per run.")
    p_show = runs_sub.add_parser("show", help="One run's full entry.")
    p_show.add_argument("run_id")
    p_show.add_argument("--full-history", action="store_true",
                        help="Every index line for the run, not just the "
                             "latest.")
    p_traj = runs_sub.add_parser(
        "trajectory", help="The bench perf trajectory, oldest first.")
    for p in (p_list, p_show, p_traj):
        p.add_argument("--runs-root", "--runs_root", dest="runs_root",
                       default=None,
                       help="Runs root (default: DIB_RUNS_ROOT or ./runs).")
    # listed for --help only; the real dispatch happens above, before
    # this parser runs (fleet_main owns its own argument tree)
    sub.add_parser(
        "fleet",
        help="Merge many runs' planes (events/sched/study/stream "
             "journals) into one causally-ordered fleet timeline: "
             "tail|summarize|report|prometheus <roots...> "
             "(docs/observability.md 'Fleet causality').")
    args = parser.parse_args(argv)

    try:
        if args.action == "summarize":
            record = summarize(args.path, process_index=args.process_index,
                               run_id=args.run_id)
            print(json.dumps(record, indent=1 if args.indent else None))
            return 0
        if args.action == "report":
            from dib_tpu.telemetry.report import write_index, write_report

            if args.index:
                from dib_tpu.telemetry.registry import resolve_runs_root

                root = resolve_runs_root(args.runs_root)
                if not root:
                    print("telemetry report --index: no runs root",
                          file=sys.stderr)
                    return 2
                print(write_index(root, out=args.out))
                return 0
            if not args.path:
                print("telemetry report: a run dir/events path is required "
                      "(or pass --index)", file=sys.stderr)
                return 2
            out = write_report(args.path, out=args.out,
                               process_index=args.process_index,
                               run_id=args.run_id)
            print(out)
            return 0
        if args.action == "tail":
            return _tail_main(args)
        if args.action == "check":
            return _check_main(args)
        if args.action == "runs":
            from dib_tpu.telemetry.registry import runs_main

            return runs_main(args)
        a = _load_side(args.baseline, args.process_index,
                       run_id=args.run_id_a)
        b = _load_side(args.candidate, args.process_index,
                       run_id=args.run_id_b)
    except (ValueError, OSError) as exc:
        # bad operand (not a stream / no events / unreadable): distinct
        # from a regression verdict, which is exit code 1
        print(f"telemetry {args.action}: {exc}", file=sys.stderr)
        return 2
    report, regressed = compare(a, b, threshold=args.threshold)
    print(json.dumps(report, indent=1 if args.indent else None))
    if regressed:
        print("telemetry compare: REGRESSION beyond threshold "
              f"{args.threshold}", file=sys.stderr)
    return 1 if regressed else 0


def _default_slo_path() -> str:
    """The committed SLO.json: next to the package checkout first (the
    repo root), falling back to the working directory."""
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    candidate = os.path.join(here, "SLO.json")
    return candidate if os.path.exists(candidate) else "SLO.json"


def _tail_main(args) -> int:
    from dib_tpu.telemetry.live import tail

    engine = None
    if args.slo:
        from dib_tpu.telemetry.slo import SLOEngine, load_slo

        directory = (args.path if os.path.isdir(args.path)
                     else os.path.dirname(args.path) or ".")
        engine = SLOEngine(load_slo(args.slo), directory)
    try:
        state = tail(
            args.path, slo=engine, refresh_s=args.refresh_s,
            duration_s=args.duration_s,
            follow_after_end=args.follow_after_end,
            ansi=False if args.no_ansi else None,
            max_frames=1 if args.once else None,
        )
    except KeyboardInterrupt:
        return 0
    finally:
        if engine is not None:
            engine.close()
    if engine is not None and engine.alerts:
        print(f"telemetry tail: {len(engine.alerts)} SLO alert(s) written",
              file=sys.stderr)
        return 1
    return 0 if state.status in ("ok", "waiting", "running") else 1


def _check_main(args) -> int:
    from dib_tpu.telemetry.slo import check_run

    slo_path = args.slo or _default_slo_path()
    try:
        report = check_run(args.path, slo_path, run_id=args.run_id,
                           process_index=args.process_index,
                           write=not args.no_write)
    except FileNotFoundError as exc:
        print(f"telemetry check: {exc}", file=sys.stderr)
        return 2
    print(json.dumps(report, indent=1 if args.indent else None))
    if report["violations"]:
        print(f"telemetry check: {report['violations']} SLO violation(s) "
              f"against {slo_path}", file=sys.stderr)
        return 1
    return 0
