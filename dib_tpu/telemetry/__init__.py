"""Unified run telemetry: structured events, metrics, and run summaries.

See ``docs/observability.md``. The pieces:

  - :mod:`dib_tpu.telemetry.events` — append-only JSONL event stream per
    run (schema-versioned envelope; run_start / chunk / compile /
    mitigation / hook / mi_bounds / metrics / run_end records).
  - :mod:`dib_tpu.telemetry.metrics` — counters / gauges / histograms with
    multihost tag-and-forward aggregation (process 0 writes).
  - :mod:`dib_tpu.telemetry.summary` — rolls an events.jsonl into a
    bench-record-shaped summary and diffs two runs with a regression gate
    (``python -m dib_tpu telemetry summarize|compare``).
  - :mod:`dib_tpu.telemetry.hooks` — fit-hook adapters (chunk/
    instrumentation phase timing into ``PhaseTimer`` + events).
  - :mod:`dib_tpu.telemetry.trace` — nestable device-truth spans: one name
    lands on the event stream, the ``PhaseTimer``, and the XLA trace
    (``jax.profiler.TraceAnnotation``) at once.
  - :mod:`dib_tpu.telemetry.xla_stats` — ``cost_analysis()`` of compiled
    callables, the per-backend peak capability table, and roofline
    utilization arithmetic.
  - :mod:`dib_tpu.telemetry.report` — self-contained static HTML run
    reports (``python -m dib_tpu telemetry report <run-dir>``) and the
    multi-run fleet index page (``telemetry report --index``).
  - :mod:`dib_tpu.telemetry.live` — follow a growing events.jsonl and
    render a live terminal dashboard (``telemetry tail <run-dir>``).
  - :mod:`dib_tpu.telemetry.slo` — declarative SLO rules (``SLO.json``)
    evaluated live and terminally, writing durable ``alert`` /
    ``transition`` events (``telemetry check <run-dir>``).
  - :mod:`dib_tpu.telemetry.registry` — append-only fleet run registry
    under a runs root (``telemetry runs list|show|trajectory``).
"""

from dib_tpu.telemetry.context import (
    TraceContext,
    child_context,
    ensure_context,
    mint,
)
from dib_tpu.telemetry.events import (
    EVENTS_FILENAME,
    SCHEMA_VERSION,
    EventWriter,
    config_fingerprint,
    device_memory_stats,
    finalize_crashed,
    finalize_open_writers,
    host_memory_stats,
    open_writer,
    read_events,
    resolve_events_path,
    runtime_manifest,
    shared_run_id,
)
from dib_tpu.telemetry.hooks import ChunkPhaseHooks, heartbeat_interval_s
from dib_tpu.telemetry.live import (
    LiveRunState,
    StreamFollower,
    liveness,
    render_dashboard,
    tail,
)
from dib_tpu.telemetry.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    gather_snapshots,
    write_metrics,
)
from dib_tpu.telemetry.registry import (
    RunRegistry,
    register_run,
    resolve_runs_root,
)
from dib_tpu.telemetry.slo import (
    SLOEngine,
    TransitionTracker,
    check_run,
    detect_transitions,
    evaluate_rules,
    load_slo,
)
from dib_tpu.telemetry.summary import (
    compare,
    faults_rollup,
    serving_rollup,
    span_hotspots,
    span_rollup,
    summarize,
    telemetry_main,
)
from dib_tpu.telemetry.trace import (
    SpannedHook,
    Tracer,
    current_tracer,
    span,
    use_tracer,
)

__all__ = [
    "EVENTS_FILENAME",
    "SCHEMA_VERSION",
    "ChunkPhaseHooks",
    "Counter",
    "EventWriter",
    "Gauge",
    "Histogram",
    "LiveRunState",
    "MetricsRegistry",
    "RunRegistry",
    "SLOEngine",
    "SpannedHook",
    "StreamFollower",
    "TraceContext",
    "Tracer",
    "TransitionTracker",
    "check_run",
    "child_context",
    "compare",
    "detect_transitions",
    "evaluate_rules",
    "heartbeat_interval_s",
    "liveness",
    "load_slo",
    "mint",
    "register_run",
    "render_dashboard",
    "resolve_runs_root",
    "tail",
    "config_fingerprint",
    "current_tracer",
    "device_memory_stats",
    "ensure_context",
    "faults_rollup",
    "finalize_crashed",
    "finalize_open_writers",
    "gather_snapshots",
    "host_memory_stats",
    "open_writer",
    "read_events",
    "resolve_events_path",
    "runtime_manifest",
    "serving_rollup",
    "shared_run_id",
    "span",
    "span_hotspots",
    "span_rollup",
    "summarize",
    "telemetry_main",
    "use_tracer",
    "write_metrics",
]
