"""Unified run telemetry: structured events, metrics, and run summaries.

See ``docs/observability.md``. The pieces:

  - :mod:`dib_tpu.telemetry.events` — append-only JSONL event stream per
    run (schema-versioned envelope; run_start / chunk / compile /
    mitigation / hook / mi_bounds / metrics / run_end records).
  - :mod:`dib_tpu.telemetry.metrics` — counters / gauges / histograms with
    multihost tag-and-forward aggregation (process 0 writes).
  - :mod:`dib_tpu.telemetry.summary` — rolls an events.jsonl into a
    bench-record-shaped summary and diffs two runs with a regression gate
    (``python -m dib_tpu telemetry summarize|compare``).
  - :mod:`dib_tpu.telemetry.hooks` — fit-hook adapters (chunk/
    instrumentation phase timing into ``PhaseTimer`` + events).
"""

from dib_tpu.telemetry.events import (
    EVENTS_FILENAME,
    SCHEMA_VERSION,
    EventWriter,
    config_fingerprint,
    device_memory_stats,
    finalize_crashed,
    finalize_open_writers,
    open_writer,
    read_events,
    resolve_events_path,
    runtime_manifest,
    shared_run_id,
)
from dib_tpu.telemetry.hooks import ChunkPhaseHooks
from dib_tpu.telemetry.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    gather_snapshots,
    write_metrics,
)
from dib_tpu.telemetry.summary import compare, summarize, telemetry_main

__all__ = [
    "EVENTS_FILENAME",
    "SCHEMA_VERSION",
    "ChunkPhaseHooks",
    "Counter",
    "EventWriter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "compare",
    "config_fingerprint",
    "device_memory_stats",
    "finalize_crashed",
    "finalize_open_writers",
    "gather_snapshots",
    "open_writer",
    "read_events",
    "resolve_events_path",
    "runtime_manifest",
    "shared_run_id",
    "summarize",
    "telemetry_main",
    "write_metrics",
]
