"""Self-contained static HTML run reports from an events.jsonl.

``python -m dib_tpu telemetry report <run-dir>`` renders ONE html file with
zero external resources (inline CSS + SVG; light/dark via
``prefers-color-scheme``), so a run report can be attached to an issue or
kept next to the run artifacts forever:

  - header: provenance stat tiles (device, status, wall-clock, steps/s);
  - span breakdown: the trace hierarchy (``telemetry/trace.py``) as a
    flame-style indented bar list, by total time per normalized path;
  - training trajectory: per-chunk steps/s, loss/val-loss, and total KL
    line charts from ``chunk`` events;
  - MI sandwich: mean lower/upper bound trajectory with the gap shaded;
  - memory: device + host high-water marks;
  - utilization: per-compiled-callable roofline coordinates (achieved
    FLOP/s / bandwidth vs the backend peak table) when ``compile`` events
    carry cost-analysis numbers — degrading to a duration-only note on
    backends without a cost model.

All computation is host-side file analysis: this module never imports jax.
"""

from __future__ import annotations

import html
import json
import math
import os

from dib_tpu.telemetry.events import read_events, resolve_events_path
from dib_tpu.telemetry.summary import summarize

__all__ = ["render_index", "render_report", "write_index", "write_report"]


# Validated default palette (dataviz reference instance): categorical slots
# 1-3 stepped per mode, text/surface tokens, recessive grid.
_CSS = """
:root { color-scheme: light dark; }
body {
  margin: 0; padding: 24px; font: 14px/1.5 system-ui, sans-serif;
  background: var(--surface-1); color: var(--text-primary);
  --surface-1: #fcfcfb; --surface-2: #f1f0ee;
  --text-primary: #0b0b0b; --text-secondary: #52514e;
  --grid: #e4e3e0; --series-1: #2a78d6; --series-2: #eb6834;
  --series-3: #1baf7a; --band: rgba(42, 120, 214, 0.14);
}
@media (prefers-color-scheme: dark) {
  body {
    --surface-1: #1a1a19; --surface-2: #242423;
    --text-primary: #ffffff; --text-secondary: #c3c2b7;
    --grid: #343432; --series-1: #3987e5; --series-2: #d95926;
    --series-3: #199e70; --band: rgba(57, 135, 229, 0.22);
  }
}
h1 { font-size: 20px; margin: 0 0 4px; }
h2 { font-size: 15px; margin: 28px 0 8px; }
.sub { color: var(--text-secondary); margin: 0 0 16px; }
.tiles { display: flex; flex-wrap: wrap; gap: 12px; margin: 16px 0; }
.tile { background: var(--surface-2); border-radius: 8px;
        padding: 10px 14px; min-width: 120px; }
.tile .v { font-size: 18px; font-weight: 600; }
.tile .k { color: var(--text-secondary); font-size: 12px; }
.spans { margin: 8px 0; max-width: 860px; }
.span-row { display: flex; align-items: center; gap: 8px;
            margin: 2px 0; font-size: 13px; }
.span-name { flex: 0 0 340px; white-space: nowrap; overflow: hidden;
             text-overflow: ellipsis; font-family: ui-monospace, monospace; }
.span-bar-rail { flex: 1; background: var(--surface-2); border-radius: 4px;
                 height: 14px; position: relative; }
.span-bar { position: absolute; top: 0; bottom: 0; border-radius: 4px;
            background: var(--series-1); min-width: 2px; }
.span-secs { flex: 0 0 150px; color: var(--text-secondary);
             font-size: 12px; text-align: right; }
table { border-collapse: collapse; font-size: 13px; }
th, td { text-align: right; padding: 4px 10px;
         border-bottom: 1px solid var(--grid); }
th:first-child, td:first-child { text-align: left;
                                 font-family: ui-monospace, monospace; }
th { color: var(--text-secondary); font-weight: 500; }
svg text { fill: var(--text-secondary); font: 11px system-ui, sans-serif; }
svg .gridline { stroke: var(--grid); stroke-width: 1; }
svg .axis { stroke: var(--grid); stroke-width: 1; }
.legend { display: flex; gap: 16px; font-size: 12px;
          color: var(--text-secondary); margin: 2px 0 0 44px; }
.legend .swatch { display: inline-block; width: 10px; height: 10px;
                  border-radius: 2px; margin-right: 5px;
                  vertical-align: -1px; }
.note { color: var(--text-secondary); font-size: 13px; }
details { margin: 24px 0; }
details pre { background: var(--surface-2); padding: 12px;
              border-radius: 8px; overflow-x: auto; font-size: 12px; }
.charts { display: flex; flex-wrap: wrap; gap: 24px; }
.chart h3 { font-size: 13px; margin: 0 0 2px;
            color: var(--text-primary); font-weight: 600; }
"""


def _esc(x) -> str:
    return html.escape(str(x))


def _fmt_seconds(s: float) -> str:
    if s >= 120:
        return f"{s / 60:.1f} min"
    if s >= 1:
        return f"{s:.2f} s"
    return f"{s * 1e3:.1f} ms"


def _fmt_bytes(b: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(b) < 1024 or unit == "TiB":
            return f"{b:.1f} {unit}" if unit != "B" else f"{b:.0f} B"
        b /= 1024
    return f"{b:.1f} TiB"


def _finite_points(points):
    return [(x, y) for x, y in points
            if isinstance(y, (int, float)) and math.isfinite(y)]


def _ticks(lo: float, hi: float, n: int = 4) -> list[float]:
    if hi <= lo:
        hi = lo + 1.0
    raw = (hi - lo) / n
    mag = 10 ** math.floor(math.log10(raw)) if raw > 0 else 1.0
    step = next((m * mag for m in (1, 2, 2.5, 5, 10) if m * mag >= raw),
                raw)
    start = math.ceil(lo / step) * step
    out = []
    t = start
    while t <= hi + 1e-12 * abs(hi):
        out.append(round(t, 10))
        t += step
    return out or [lo, hi]


def _fmt_tick(v: float) -> str:
    if v == 0:
        return "0"
    if abs(v) >= 10000 or abs(v) < 0.01:
        return f"{v:.1e}"
    return f"{v:g}"


class _Scale:
    def __init__(self, points_lists, width, height, pad_l=44, pad_r=12,
                 pad_t=8, pad_b=20):
        xs = [p[0] for pts in points_lists for p in pts]
        ys = [p[1] for pts in points_lists for p in pts]
        self.x0, self.x1 = (min(xs), max(xs)) if xs else (0.0, 1.0)
        self.y0, self.y1 = (min(ys), max(ys)) if ys else (0.0, 1.0)
        if self.x1 <= self.x0:
            self.x1 = self.x0 + 1.0
        if self.y1 <= self.y0:
            self.y0, self.y1 = self.y0 - 0.5, self.y1 + 0.5
        else:  # headroom so lines don't kiss the frame
            span = self.y1 - self.y0
            self.y0 -= 0.05 * span
            self.y1 += 0.05 * span
        self.pl, self.pr, self.pt, self.pb = pad_l, pad_r, pad_t, pad_b
        self.w, self.h = width, height

    def x(self, v) -> float:
        return self.pl + (v - self.x0) / (self.x1 - self.x0) * (
            self.w - self.pl - self.pr)

    def y(self, v) -> float:
        return self.pt + (self.y1 - v) / (self.y1 - self.y0) * (
            self.h - self.pt - self.pb)


def _line_chart(title: str, series, *, width=420, height=150,
                x_label="epoch", band_pair=None) -> str:
    """One SVG line chart. ``series``: [(name, css_color_var, points)].
    ``band_pair``: (i, j) series indices to shade between (MI sandwich).
    Multi-series charts get a legend; every point carries a native hover
    tooltip (<title>)."""
    series = [(name, color, _finite_points(pts)) for name, color, pts in series]
    series = [s for s in series if s[2]]
    if not series:
        return ""
    sc = _Scale([pts for _, _, pts in series], width, height)
    parts = [f'<svg viewBox="0 0 {width} {height}" width="{width}" '
             f'height="{height}" role="img" aria-label="{_esc(title)}">']
    for t in _ticks(sc.y0, sc.y1):
        if not (sc.y0 <= t <= sc.y1):
            continue
        y = sc.y(t)
        parts.append(f'<line class="gridline" x1="{sc.pl}" y1="{y:.1f}" '
                     f'x2="{width - sc.pr}" y2="{y:.1f}"/>')
        parts.append(f'<text x="{sc.pl - 6}" y="{y + 3.5:.1f}" '
                     f'text-anchor="end">{_fmt_tick(t)}</text>')
    parts.append(f'<line class="axis" x1="{sc.pl}" y1="{height - sc.pb}" '
                 f'x2="{width - sc.pr}" y2="{height - sc.pb}"/>')
    for t in _ticks(sc.x0, sc.x1, 5):
        if not (sc.x0 <= t <= sc.x1):
            continue
        parts.append(f'<text x="{sc.x(t):.1f}" y="{height - 6}" '
                     f'text-anchor="middle">{_fmt_tick(t)}</text>')
    parts.append(f'<text x="{width - sc.pr}" y="{height - 6}" '
                 f'text-anchor="end">{_esc(x_label)}</text>')
    if band_pair is not None and len(series) > max(band_pair):
        lo = series[band_pair[0]][2]
        hi = series[band_pair[1]][2]
        if len(lo) == len(hi):
            pts = ([f"{sc.x(x):.1f},{sc.y(y):.1f}" for x, y in hi]
                   + [f"{sc.x(x):.1f},{sc.y(y):.1f}" for x, y in lo[::-1]])
            parts.append(f'<polygon points="{" ".join(pts)}" '
                         f'fill="var(--band)" stroke="none"/>')
    for name, color, pts in series:
        d = " ".join(f"{sc.x(x):.1f},{sc.y(y):.1f}" for x, y in pts)
        parts.append(f'<polyline points="{d}" fill="none" '
                     f'stroke="var({color})" stroke-width="2" '
                     f'stroke-linejoin="round"/>')
        for x, y in pts:
            parts.append(
                f'<circle cx="{sc.x(x):.1f}" cy="{sc.y(y):.1f}" r="2.5" '
                f'fill="var({color})"><title>{_esc(name)} @ '
                f'{_fmt_tick(x)}: {y:.5g}</title></circle>')
    parts.append("</svg>")
    legend = ""
    if len(series) > 1:
        legend = '<div class="legend">' + "".join(
            f'<span><span class="swatch" style="background:var({color})">'
            f'</span>{_esc(name)}</span>'
            for name, color, _ in series
        ) + "</div>"
    return (f'<div class="chart"><h3>{_esc(title)}</h3>'
            f"{''.join(parts)}{legend}</div>")


def _tiles(pairs) -> str:
    cells = "".join(
        f'<div class="tile"><div class="v">{_esc(v)}</div>'
        f'<div class="k">{_esc(k)}</div></div>'
        for k, v in pairs if v is not None
    )
    return f'<div class="tiles">{cells}</div>'


def _span_section(summary: dict) -> str:
    rollup = summary.get("spans") or {}
    if not rollup:
        return ('<p class="note">No span events in this stream — run with '
                "telemetry enabled on a spans-wired entry point "
                "(train/sweep/boolean/northstar) to get the trace "
                "breakdown.</p>")
    # Tree by NEAREST PRESENT ancestor: a span recorded with a slash name
    # and no enclosing spans ("sweep/replica*/mi_bounds" with no "sweep"
    # entry) roots the subtree itself instead of silently vanishing.
    children: dict[str, list[str]] = {}
    for path in rollup:
        parts = path.split("/")
        ancestor = ""
        for i in range(len(parts) - 1, 0, -1):
            candidate = "/".join(parts[:i])
            if candidate in rollup:
                ancestor = candidate
                break
        children.setdefault(ancestor, []).append(path)
    roots = children.get("", [])
    top_total = sum(rollup[p]["total_s"] for p in roots) or max(
        (s["total_s"] for s in rollup.values()), default=1.0)
    rows = []

    def emit(ancestor: str, depth: int):
        level = sorted(children.get(ancestor, []),
                       key=lambda p: -rollup[p]["total_s"])
        for path in level:
            stats = rollup[path]
            frac = stats["total_s"] / top_total if top_total else 0.0
            suffix = path[len(ancestor) + 1:] if ancestor else path
            label = ("&nbsp;" * 4 * depth) + _esc(suffix)
            rows.append(
                '<div class="span-row">'
                f'<div class="span-name" title="{_esc(path)}">{label}</div>'
                '<div class="span-bar-rail">'
                f'<div class="span-bar" style="left:0;'
                f'width:{min(frac, 1.0) * 100:.2f}%"></div></div>'
                f'<div class="span-secs">{_fmt_seconds(stats["total_s"])}'
                f' &middot; {stats["count"]}&times;'
                f' &middot; {frac * 100:.1f}%</div></div>'
            )
            emit(path, depth + 1)

    emit("", 0)
    hot = summary.get("span_hotspots") or []
    hot_html = ""
    if hot:
        hot_html = ('<p class="note">Hotspots (self time): '
                    + ", ".join(
                        f"<code>{_esc(h['path'])}</code> "
                        f"{_fmt_seconds(h['self_s'])}"
                        for h in hot) + "</p>")
    return f'<div class="spans">{"".join(rows)}</div>{hot_html}'


def _utilization_section(summary: dict) -> str:
    util = dict(summary.get("utilization") or {})
    peaks = util.pop("_peaks", None)
    if not util:
        return ('<p class="note">No XLA cost-analysis numbers on this '
                "stream (backend without a cost model, or "
                "<code>DIB_XLA_COST_ANALYSIS=0</code>) — spans above carry "
                "the duration-only view.</p>")
    head = ""
    if peaks:
        head = (f'<p class="note">Backend peaks: '
                f"{peaks.get('bf16_tflops', '?')} TFLOP/s bf16, "
                f"{peaks.get('hbm_gbps', '?')} GB/s HBM "
                "(per-backend capability table, "
                "<code>telemetry/xla_stats.py</code>).</p>")
    rows = ["<tr><th>compiled callable</th><th>FLOPs/call</th>"
            "<th>bytes/call</th><th>mean span</th>"
            "<th>achieved GFLOP/s</th><th>% FLOP peak</th>"
            "<th>achieved GB/s</th><th>% HBM peak</th>"
            "<th>FLOP/byte</th></tr>"]
    for name, entry in util.items():
        def num(key, fmt="{:.3g}", scale=1.0, pct=False):
            v = entry.get(key)
            if v is None:
                return "—"
            return (f"{v * 100:.2f}%" if pct else fmt.format(v * scale))
        rows.append(
            f"<tr><td>{_esc(name)}</td>"
            f"<td>{num('flops', '{:.3e}')}</td>"
            f"<td>{num('bytes_accessed', '{:.3e}')}</td>"
            f"<td>{_fmt_seconds(entry['span_mean_s']) if entry.get('span_mean_s') else '—'}</td>"
            f"<td>{num('achieved_gflops', '{:.2f}')}</td>"
            f"<td>{num('flops_frac_of_peak', pct=True)}</td>"
            f"<td>{num('achieved_gbps', '{:.2f}')}</td>"
            f"<td>{num('bandwidth_frac_of_peak', pct=True)}</td>"
            f"<td>{num('arithmetic_intensity', '{:.2f}')}</td></tr>"
        )
    note = ('<p class="note">Achieved rates divide each callable\'s '
            "cost-analyzed FLOPs/bytes by its mean span duration; "
            "cost-analysis flop counts are backend-reported and can "
            "undercount (see docs/performance.md) — the analytic-MFU "
            "headline in bench.py is the cross-round comparable.</p>")
    return head + "<table>" + "".join(rows) + "</table>" + note


def _memory_section(chunks) -> str:
    dev = [(c.get("epoch"), (c.get("memory") or {}).get("peak_bytes_in_use"))
           for c in chunks]
    host = [(c.get("epoch"),
             (c.get("host_memory") or {}).get(
                 "peak_rss_bytes", (c.get("host_memory") or {}).get(
                     "rss_bytes")))
            for c in chunks]
    dev = [(e, v) for e, v in dev if v is not None]
    host = [(e, v) for e, v in host if v is not None]
    if not dev and not host:
        return ('<p class="note">No memory stats on this stream (CPU '
                "backend without the host-RSS fallback, or a pre-span "
                "schema).</p>")
    tiles = _tiles([
        ("device peak", _fmt_bytes(max(v for _, v in dev)) if dev else None),
        ("host RSS peak", _fmt_bytes(max(v for _, v in host)) if host else None),
    ])
    series = []
    if dev:
        series.append(("device peak bytes", "--series-1",
                       [(e, v / 2**20) for e, v in dev]))
    if host:
        series.append(("host RSS", "--series-2",
                       [(e, v / 2**20) for e, v in host]))
    chart = _line_chart("Memory high-water (MiB)", series) if series else ""
    return tiles + f'<div class="charts">{chart}</div>'


def _faults_section(summary: dict) -> str:
    """Fault-drill evidence (docs/robustness.md): injected vs detected vs
    recovered tiles plus the per-injection join. Empty string for normal
    (uninjected) runs — the section only renders when drills ran."""
    faults = summary.get("faults")
    if not faults:
        return ""
    ttd = (faults.get("time_to_detect_s") or {}).get("mean")
    ttr = (faults.get("time_to_recover_s") or {}).get("mean")
    tiles = _tiles([
        ("injected", faults.get("injected")),
        ("detected", faults.get("detected")),
        ("recovered", faults.get("recovered")),
        ("mean detect", _fmt_seconds(ttd) if ttd is not None else None),
        ("mean recover", _fmt_seconds(ttr) if ttr is not None else None),
    ])
    rows = []
    for f in faults.get("faults", []):
        det = ("✓ " + _esc(str(f.get("detected_by", "")))
               if f.get("detected") else "✗ UNDETECTED")
        rec = "✓" if f.get("recovered") else "✗"
        ttd_s = f.get("time_to_detect_s")
        ttr_s = f.get("time_to_recover_s")
        rows.append(
            "<tr>"
            f"<td><code>{_esc(f.get('spec') or f.get('kind', '?'))}</code></td>"
            f"<td>{det}</td>"
            f"<td>{_fmt_seconds(ttd_s) if ttd_s is not None else '—'}</td>"
            f"<td>{rec}</td>"
            f"<td>{_fmt_seconds(ttr_s) if ttr_s is not None else '—'}</td>"
            "</tr>"
        )
    undetected = faults.get("undetected") or []
    warn = ""
    if undetected:
        warn = ('<p class="note">⚠ undetected injected fault(s): '
                + ", ".join(f"<code>{_esc(k)}</code>" for k in undetected)
                + " — <code>telemetry compare</code> gates on this.</p>")
    table = ("<table><thead><tr><th>injection</th><th>detected</th>"
             "<th>t-detect</th><th>recovered</th><th>t-recover</th>"
             "</tr></thead><tbody>" + "".join(rows) + "</tbody></table>")
    return ("<h2>Fault drills</h2>"
            '<p class="note">Deliberate injections '
            "(<code>dib_tpu/faults</code>) joined with the mitigations "
            "they provoked.</p>" + tiles + table + warn)


def _slo_section(events) -> str:
    """Durable SLO residue (telemetry/slo.py): alert and info-plane
    transition events on the stream. Empty for runs with neither."""
    alerts = [e for e in events if e.get("type") == "alert"]
    transitions = [e for e in events if e.get("type") == "transition"]
    if not alerts and not transitions:
        return ""
    parts = ["<h2>SLO alerts &amp; info-plane transitions</h2>"]
    if alerts:
        rows = "".join(
            f"<tr><td>{_esc(a.get('rule', '?'))}</td>"
            f"<td>{_esc(a.get('metric', '?'))}</td>"
            f"<td>{_esc(a.get('value'))}</td>"
            f"<td>{_esc(a.get('bound', '?'))} {_esc(a.get('budget'))}</td>"
            f"<td>{_esc(a.get('severity', '?'))}</td>"
            f"<td>{_esc(a.get('source', '?'))}</td></tr>"
            for a in alerts)
        parts.append(
            '<p class="note">⚠ budgets violated (SLO.json, '
            "<code>telemetry check</code>):</p>"
            "<table><thead><tr><th>rule</th><th>metric</th><th>observed</th>"
            "<th>budget</th><th>severity</th><th>source</th></tr></thead>"
            f"<tbody>{rows}</tbody></table>")
    if transitions:
        rows = "".join(
            f"<tr><td>{_esc(t.get('channel', '?'))}</td>"
            f"<td>{_esc(t.get('epoch', '?'))}</td>"
            f"<td>{_esc(t.get('direction', '?'))}</td>"
            f"<td>{_esc(t.get('kl_before'))} → {_esc(t.get('kl_after'))}</td>"
            f"<td>{_esc(t.get('beta', '—'))}</td></tr>"
            for t in transitions)
        parts.append(
            '<p class="note">Per-channel KL threshold crossings — the '
            "info-plane transitions the β-grid refinement targets:</p>"
            "<table><thead><tr><th>channel</th><th>epoch</th>"
            "<th>direction</th><th>KL (nats)</th><th>β</th></tr></thead>"
            f"<tbody>{rows}</tbody></table>")
    return "".join(parts)


def render_report(path: str, run_id: str | None = None,
                  process_index: int | None = None) -> str:
    """The report HTML for one events.jsonl (or its run dir)."""
    events = list(read_events(path, process_index=process_index))
    if run_id is not None:
        events = [e for e in events if e.get("run") == run_id]
    summary = summarize(path, process_index=process_index, run_id=run_id)

    chunks = [e for e in events if e.get("type") == "chunk"]
    mi = [e for e in events if e.get("type") == "mi_bounds"]

    def chunk_series(key):
        pts = []
        for c in chunks:
            v = c.get(key)
            if isinstance(v, list):   # sweep runs carry [R] lists
                vals = [x for x in v if isinstance(x, (int, float))]
                v = sum(vals) / len(vals) if vals else None
            if isinstance(v, (int, float)):
                pts.append((c.get("epoch", 0), v))
        return pts

    charts = [
        _line_chart("Throughput (steps/s)",
                    [("steps/s", "--series-1", chunk_series("steps_per_s"))]),
        _line_chart("Loss",
                    [("train", "--series-1", chunk_series("loss")),
                     ("validation", "--series-2", chunk_series("val_loss"))]),
    ]
    kl = chunk_series("kl_total")
    if not kl:
        kl = []
        for c in chunks:
            v = c.get("kl_per_feature")
            if isinstance(v, list):
                vals = [x for x in v if isinstance(x, (int, float))]
                if vals:
                    kl.append((c.get("epoch", 0), sum(vals)))
    charts.append(_line_chart("Total KL (per-replica mean for sweeps)",
                              [("total KL", "--series-3", kl)]))
    charts = [c for c in charts if c]

    mi_chart = ""
    if mi:
        def mean_bits(e, which):
            vals = e.get(f"{which}_bits")
            if vals is None and e.get(f"{which}_nats") is not None:
                vals = [x / math.log(2.0) for x in e[f"{which}_nats"]
                        if isinstance(x, (int, float))]
            if isinstance(vals, list):
                vals = [x for x in vals if isinstance(x, (int, float))]
                return sum(vals) / len(vals) if vals else None
            return vals if isinstance(vals, (int, float)) else None

        lower = [(e.get("epoch", 0), mean_bits(e, "lower")) for e in mi]
        upper = [(e.get("epoch", 0), mean_bits(e, "upper")) for e in mi]
        lower = [(x, y) for x, y in lower if y is not None]
        upper = [(x, y) for x, y in upper if y is not None]
        mi_chart = _line_chart(
            "MI sandwich bounds (mean bits per feature)",
            [("lower bound", "--series-1", lower),
             ("upper bound", "--series-2", upper)],
            band_pair=(0, 1), width=640, height=180,
        )

    status = summary.get("status", "?")
    wall = summary.get("wall_clock_s")
    header_tiles = _tiles([
        ("status", status),
        ("device", f"{summary.get('device_kind', '?')} ×"
                   f"{summary.get('device_count', '?')}"),
        ("steps/s", summary.get("steps_per_s")),
        ("steady steps/s", summary.get("steady_steps_per_s")),
        ("total steps", summary.get("total_steps")),
        ("wall clock", _fmt_seconds(wall) if wall else None),
        ("launches", summary.get("launches")),
        ("mitigations", summary.get("mitigations_total") or None),
    ])
    run_label = summary.get("run_id", "run")
    git = summary.get("git_sha")
    sub = (f"run <code>{_esc(run_label)}</code>"
           + (f" · git <code>{_esc(str(git)[:12])}</code>" if git else "")
           + (f" · config <code>{_esc(summary['config_hash'])}</code>"
              if summary.get("config_hash") else ""))

    summary_json = _esc(json.dumps(summary, indent=1, default=str))
    return f"""<!DOCTYPE html>
<html lang="en"><head><meta charset="utf-8">
<meta name="viewport" content="width=device-width, initial-scale=1">
<title>dib-tpu run report — {_esc(run_label)}</title>
<style>{_CSS}</style></head>
<body>
<h1>dib-tpu run report</h1>
<p class="sub">{sub}</p>
{header_tiles}
<h2>Span breakdown</h2>
<p class="note">Blocked wall-clock per trace span
(<code>telemetry/trace.py</code>); bars are fractions of the top-level
total, indented by nesting. The same names appear in captured XLA traces
via <code>jax.profiler.TraceAnnotation</code>.</p>
{_span_section(summary)}
<h2>Training trajectory</h2>
<div class="charts">{''.join(charts)}</div>
<h2>MI-bound trajectory</h2>
{mi_chart or '<p class="note">No mi_bounds events in this stream.</p>'}
<h2>Memory</h2>
{_memory_section(chunks)}
<h2>Roofline utilization</h2>
{_utilization_section(summary)}
{_faults_section(summary)}
{_slo_section(events)}
<details><summary>Full summary record (table view)</summary>
<pre>{summary_json}</pre></details>
</body></html>
"""


def write_report(path: str, out: str | None = None,
                 run_id: str | None = None,
                 process_index: int | None = None) -> str:
    """Render and write the report; returns the output path (default:
    ``report.html`` next to the events file)."""
    html_text = render_report(path, run_id=run_id,
                              process_index=process_index)
    if out is None:
        out = os.path.join(
            os.path.dirname(resolve_events_path(path)), "report.html")
    with open(out, "w") as f:
        f.write(html_text)
    return out


# ------------------------------------------------------------- fleet index
def render_index(runs_root: str, out_dir: str | None = None) -> str:
    """The multi-run index page for a runs root (``telemetry report
    --index``): one row per registered run linking its per-run report,
    plus the bench perf trajectory as table + SVG chart. Same
    self-contained HTML contract as the per-run report."""
    from dib_tpu.telemetry.registry import RunRegistry

    registry = RunRegistry(runs_root)
    out_dir = out_dir or runs_root
    latest = registry.latest()
    bench = registry.bench_history()

    rows = []
    for run_id, entry in sorted(latest.items(),
                                key=lambda kv: kv[1].get("t", 0.0)):
        metrics = entry.get("metrics") or {}
        prov = entry.get("provenance") or {}
        run_dir = entry.get("run_dir") or ""
        report_path = os.path.join(run_dir, "report.html")
        # link relative to where the index page lands, when expressible
        try:
            href = os.path.relpath(report_path, out_dir)
        except ValueError:   # different drive (windows): absolute
            href = report_path
        name = (f'<a href="{_esc(href)}">{_esc(run_id)}</a>'
                if run_dir and os.path.exists(report_path)
                else _esc(run_id))
        alerts = metrics.get("alerts", 0)
        rows.append(
            "<tr>"
            f"<td>{name}</td>"
            f"<td>{_esc(entry.get('status', '?'))}</td>"
            f"<td>{_esc(prov.get('device_kind', '—'))}</td>"
            f"<td>{_esc(_num(metrics.get('steps_per_s')))}</td>"
            f"<td>{_esc(_num(metrics.get('mfu')))}</td>"
            f"<td>{_esc(_num(metrics.get('final_val_loss')))}</td>"
            f"<td>{_esc(_num(metrics.get('serving_p99_ms')))}</td>"
            f"<td>{'⚠ ' if alerts else ''}{alerts or '—'}</td>"
            f"<td>{_esc(_num(metrics.get('mitigations_total', 0)))}</td>"
            "</tr>")
    runs_table = (
        "<table><thead><tr><th>run</th><th>status</th><th>device</th>"
        "<th>steps/s</th><th>MFU</th><th>val loss</th><th>serve p99 ms</th>"
        "<th>alerts</th><th>mitigations</th></tr></thead>"
        f"<tbody>{''.join(rows)}</tbody></table>"
        if rows else
        '<p class="note">No runs registered yet — runs register at '
        "run_end when a runs root is configured (<code>--runs-root</code> "
        "/ <code>DIB_RUNS_ROOT</code>; docs/observability.md).</p>")

    trajectory_html = ('<p class="note">No bench entries yet — '
                       "<code>bench.py</code> appends every invocation's "
                       "headline numbers here.</p>")
    if bench:
        # the minutes chart is the north-star projection only — other
        # bench kinds (serve req/s) carry different units and would
        # scramble the axis
        minutes = [(i, e.get("value")) for i, e in enumerate(bench)
                   if isinstance(e.get("value"), (int, float))
                   and e.get("unit") == "minutes"]
        steps = [(i, e.get("steps_per_s")) for i, e in enumerate(bench)
                 if isinstance(e.get("steps_per_s"), (int, float))]
        mfu = [(i, e.get("mfu") * 100) for i, e in enumerate(bench)
               if isinstance(e.get("mfu"), (int, float))]
        charts = [c for c in (
            _line_chart("Projected north-star sweep (minutes)",
                        [("minutes", "--series-1", minutes)],
                        x_label="bench #"),
            _line_chart("Sweep throughput (steps/s)",
                        [("steps/s", "--series-2", steps)],
                        x_label="bench #"),
            _line_chart("MFU (%)", [("mfu %", "--series-3", mfu)],
                        x_label="bench #"),
        ) if c]
        bench_rows = "".join(
            "<tr>"
            f"<td>{i}</td>"
            f"<td>{_esc(e.get('measured_at', '—'))}</td>"
            f"<td>{_esc(_num(e.get('value')))}</td>"
            f"<td>{_esc(e.get('unit', '—'))}</td>"
            f"<td>{_esc(_num(e.get('steps_per_s')))}</td>"
            f"<td>{_esc(_num(e.get('mfu')))}</td>"
            f"<td>{_esc(_num(e.get('vs_baseline')))}</td>"
            f"<td>{_esc(e.get('device_kind', '—'))}"
            f"{' [degraded]' if e.get('degraded') else ''}</td></tr>"
            for i, e in enumerate(bench))
        trajectory_html = (
            f'<div class="charts">{"".join(charts)}</div>'
            "<table><thead><tr><th>#</th><th>measured at</th><th>value</th>"
            "<th>unit</th><th>steps/s</th><th>MFU</th><th>vs baseline</th>"
            "<th>device</th></tr></thead>"
            f"<tbody>{bench_rows}</tbody></table>")

    return f"""<!DOCTYPE html>
<html lang="en"><head><meta charset="utf-8">
<meta name="viewport" content="width=device-width, initial-scale=1">
<title>dib-tpu fleet index — {_esc(runs_root)}</title>
<style>{_CSS}</style></head>
<body>
<h1>dib-tpu fleet index</h1>
<p class="sub">runs root <code>{_esc(os.path.abspath(runs_root))}</code>
 · {len(latest)} run(s) · {len(bench)} bench point(s)</p>
<h2>Runs</h2>
<p class="note">Latest registry entry per run
(<code>{_esc(os.path.join(runs_root, 'index.jsonl'))}</code>, append-only);
run names link to each run's per-run report where one has been
rendered.</p>
{runs_table}
<h2>Performance trajectory</h2>
<p class="note">Every <code>bench.py</code> invocation's headline numbers,
oldest first — the cross-run record the MFU and serving campaigns gate
against (<code>telemetry runs trajectory</code> is the terminal view).</p>
{trajectory_html}
</body></html>
"""


def _num(v) -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def write_index(runs_root: str, out: str | None = None) -> str:
    """Render and write the fleet index page (default:
    ``<runs_root>/index.html``)."""
    out = out or os.path.join(runs_root, "index.html")
    html_text = render_index(runs_root, out_dir=os.path.dirname(out) or ".")
    with open(out, "w") as f:
        f.write(html_text)
    return out
