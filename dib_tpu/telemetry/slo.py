"""Declarative SLOs over run telemetry: budgets in, durable alerts out.

``compare`` (telemetry/summary.py) gates a candidate run against a
BASELINE run. This module gates a run against ABSOLUTE budgets — a
committed ``SLO.json`` of declarative rules — the same exit-code shape,
usable both terminally and live:

    python -m dib_tpu telemetry check <run-dir> [--slo SLO.json]  # rc 1 on violation
    python -m dib_tpu telemetry tail  <run-dir> --slo SLO.json    # live evaluation

Rule grammar (``SLO.json``)::

    {
      "slo_version": 1,
      "rules": [
        {"name": "north_star_mfu_floor",       # unique id, rides the alert
         "metric": "mfu",                      # dotted path into the run summary
         "min": 0.05,                          # exactly one of min / max
         "when": {"device_platform": "tpu"},   # optional applicability guard
         "severity": "warn",                   # free-form label, default "page"
         "description": "..."}
      ],
      "transitions": {"kl_threshold_nats": 0.05}
    }

Semantics:

- ``metric`` resolves dotted paths against the ``summarize`` record
  (``serving.request_p99_ms``, ``heartbeats.max_gap_s``, ...). Numeric
  lists resolve to their MEAN (a sweep's per-replica finals), non-numeric
  lists to their LENGTH (``faults.undetected`` — "zero undetected faults"
  is ``max: 0``). A rule whose metric is absent is **skipped**, not
  violated (a training rule must not fire on a serving stream); pass
  ``"required": true`` to make absence itself a violation.
- ``when`` guards applicability: every key (dotted, same resolution) must
  equal the given value (or be IN it, when a list) for the rule to apply.
  A key ABSENT from the summary means the guard is unmatched (skipped).
- ``when_not`` excludes: the rule is skipped when any key resolves AND
  matches its value (or is IN it, when a list). A key absent from the
  summary excludes nothing — so a rule scoped by exclusion still gates
  streams that never tagged themselves (the fail-closed direction for
  page-severity rules; an inclusion ``when`` would silently un-gate
  them).
- **Transitions** are detections, not violations: a channel's per-feature
  KL crossing ``kl_threshold_nats`` between chunk boundaries is an
  info-plane transition — the β-grid refinement signal the scheduler
  roadmap item needs — emitted as a durable ``transition`` event.

Durability: violations are appended to the run's OWN events.jsonl as
``alert`` events (one per rule per run — re-checking is idempotent), so a
budget violated at 3am outlives the tail session that spotted it and
shows up in ``summarize``/``report`` forever after.
"""

from __future__ import annotations

import json
import math
import os

from dib_tpu.telemetry.events import EventWriter, read_events

__all__ = ["SLOEngine", "TransitionTracker", "check_run",
           "detect_transitions", "evaluate_burn_rates", "evaluate_rules",
           "load_slo", "resolve_metric", "slo_budget", "validate_slo"]

DEFAULT_SLO_PATH = "SLO.json"
SLO_VERSION = 1


# ------------------------------------------------------------------ rules
def load_slo(path: str) -> dict:
    """Parse and validate an SLO file; raises ``ValueError`` on a shape
    problem (naming the offending rule) so a typo'd budget fails the CI
    gate loudly instead of silently never firing."""
    with open(path) as f:
        spec = json.load(f)
    problems = validate_slo(spec)
    if problems:
        raise ValueError(f"{path}: " + "; ".join(problems))
    return spec


def validate_slo(spec) -> list[str]:
    """Schema problems for a parsed SLO spec (empty list = valid). Shared
    with ``scripts/check_run_artifacts.py`` so the committed SLO.json is
    validated in CI with the same rules the loader enforces."""
    problems: list[str] = []
    if not isinstance(spec, dict):
        return ["top level must be an object"]
    rules = spec.get("rules")
    if not isinstance(rules, list) or not rules:
        problems.append("'rules' must be a non-empty list")
        rules = []
    seen: set[str] = set()
    for i, rule in enumerate(rules):
        label = f"rules[{i}]"
        if not isinstance(rule, dict):
            problems.append(f"{label} must be an object")
            continue
        name = rule.get("name")
        if not (isinstance(name, str) and name):
            problems.append(f"{label}: 'name' must be a non-empty string")
        elif name in seen:
            problems.append(f"{label}: duplicate rule name {name!r}")
        else:
            seen.add(name)
            label = f"rule {name!r}"
        if not (isinstance(rule.get("metric"), str) and rule["metric"]):
            problems.append(f"{label}: 'metric' must be a non-empty string")
        bounds = [k for k in ("min", "max") if k in rule]
        if len(bounds) != 1:
            problems.append(f"{label}: exactly one of 'min'/'max' required")
        for k in bounds:
            v = rule[k]
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or not math.isfinite(v):
                problems.append(f"{label}: {k!r} must be a finite number")
        for guard in ("when", "when_not"):
            v = rule.get(guard)
            if v is not None and not isinstance(v, dict):
                problems.append(f"{label}: {guard!r} must be an object")
    transitions = spec.get("transitions")
    if transitions is not None:
        thr = (transitions or {}).get("kl_threshold_nats") \
            if isinstance(transitions, dict) else None
        if not isinstance(transitions, dict) or not isinstance(
                thr, (int, float)) or isinstance(thr, bool) or thr <= 0:
            problems.append("'transitions' must be an object with a "
                            "positive 'kl_threshold_nats'")
    problems.extend(_validate_burn_rates(spec.get("burn_rates"), seen))
    return problems


def _finite_pos(v) -> bool:
    return (isinstance(v, (int, float)) and not isinstance(v, bool)
            and math.isfinite(v) and v > 0)


def _validate_burn_rates(burn, seen_names: set[str]) -> list[str]:
    """Shape problems for the optional ``burn_rates`` section (see
    docs/observability.md "Fleet causality"): windowed error-budget
    burn rules ``telemetry fleet tail --slo`` evaluates over the merged
    fleet timeline. Names share the rule namespace (an alert carries
    only the name)."""
    if burn is None:
        return []
    if not isinstance(burn, list):
        return ["'burn_rates' must be a list"]
    problems: list[str] = []
    for i, rule in enumerate(burn):
        label = f"burn_rates[{i}]"
        if not isinstance(rule, dict):
            problems.append(f"{label} must be an object")
            continue
        name = rule.get("name")
        if not (isinstance(name, str) and name):
            problems.append(f"{label}: 'name' must be a non-empty string")
        elif name in seen_names:
            problems.append(f"{label}: duplicate rule name {name!r}")
        else:
            seen_names.add(name)
            label = f"burn rule {name!r}"
        if not (isinstance(rule.get("bad"), dict) and rule["bad"]):
            problems.append(f"{label}: 'bad' must be a non-empty object "
                            "matcher")
        total = rule.get("total")
        if total is not None and not isinstance(total, dict):
            problems.append(f"{label}: 'total' must be an object matcher")
        budget = rule.get("budget")
        if not _finite_pos(budget) or budget > 1:
            problems.append(f"{label}: 'budget' must be a finite number "
                            "in (0, 1]")
        fast = rule.get("fast_window_s")
        slow = rule.get("slow_window_s")
        if not _finite_pos(fast):
            problems.append(f"{label}: 'fast_window_s' must be a positive "
                            "number")
        if not _finite_pos(slow) or (_finite_pos(fast) and slow <= fast):
            problems.append(f"{label}: 'slow_window_s' must be a positive "
                            "number greater than 'fast_window_s'")
        if not _finite_pos(rule.get("threshold")):
            problems.append(f"{label}: 'threshold' must be a positive "
                            "number")
    return problems


def _entry_matches(matcher: dict, plane: str, record: dict) -> bool:
    """Whether one timeline record matches a burn-rate matcher: every key
    must resolve and match (the ``when``-guard semantics, fail-closed).
    ``plane`` matches the source's plane; any other key dotted-resolves
    into the record itself (``type``, ``kind``, ``severity``, ...)."""
    view = {"plane": plane, **record}
    for key, want in matcher.items():
        if _guard_key_matches(view, key, want) is not True:
            return False
    return True


def evaluate_burn_rates(burn_rules, entries, now: float | None = None
                        ) -> list[dict]:
    """Evaluate burn-rate rules over a merged fleet timeline.

    ``entries`` are fleet timeline entries (``telemetry/fleet.py``):
    dicts with ``plane``, ``t``, and the source ``record``. For each
    rule, the error ratio bad/total inside the fast and the slow
    trailing window (ending at ``now``, default: the newest timestamp
    seen) is divided by the rule's error ``budget`` — the burn rate.
    The rule FIRES only when BOTH windows burn at ``threshold`` or more:
    the fast window catches the cliff, the slow window keeps a brief
    blip from paging (the multiwindow burn-rate idiom). A rule whose
    slow window saw no ``total``-matching records is skipped, not fired
    (no traffic is not an outage verdict).
    """
    rows: list[dict] = []
    stamped = [(float(e.get("t") or 0.0), e.get("plane", ""),
                e.get("record") or {}) for e in entries]
    if now is None:
        now = max((t for t, _, _ in stamped), default=0.0)
    for rule in burn_rules or []:
        bad_m = rule.get("bad") or {}
        total_m = rule.get("total")
        counts = {}
        for label, window in (("fast", rule["fast_window_s"]),
                              ("slow", rule["slow_window_s"])):
            lo = now - float(window)
            bad = total = 0
            for t, plane, record in stamped:
                if t < lo or t > now:
                    continue
                if total_m is None or _entry_matches(total_m, plane, record):
                    total += 1
                if _entry_matches(bad_m, plane, record):
                    bad += 1
            ratio = (bad / total) if total else 0.0
            counts[label] = {"bad": bad, "total": total,
                             "burn": ratio / float(rule["budget"])}
        row = {
            "rule": rule.get("name", "?"),
            "budget": rule.get("budget"),
            "threshold": rule.get("threshold"),
            "windows_s": [rule["fast_window_s"], rule["slow_window_s"]],
            "severity": rule.get("severity", "page"),
            "burn_fast": round(counts["fast"]["burn"], 6),
            "burn_slow": round(counts["slow"]["burn"], 6),
            "bad_fast": counts["fast"]["bad"],
            "total_fast": counts["fast"]["total"],
            "bad_slow": counts["slow"]["bad"],
            "total_slow": counts["slow"]["total"],
        }
        if counts["slow"]["total"] == 0:
            row.update(status="skipped", reason="no matching traffic in "
                                                "the slow window")
        elif (counts["fast"]["burn"] >= rule["threshold"]
                and counts["slow"]["burn"] >= rule["threshold"]):
            row["status"] = "firing"
        else:
            row["status"] = "ok"
        rows.append(row)
    return rows


def slo_budget(rule_name: str, default: float,
               path: str | None = None) -> float:
    """One committed rule's min/max budget, for tools that need the
    NUMBER outside a full check — the loadgen's ``within_slo`` verdicts
    and ``check_run_artifacts``'s artifact gates read it here so they can
    never drift from the rule ``telemetry check`` enforces. Falls back to
    ``default`` when the file or rule is absent/unreadable."""
    if path is None:
        from dib_tpu.telemetry.summary import _default_slo_path

        path = _default_slo_path()
    try:
        with open(path) as f:
            spec = json.load(f)
        for rule in spec.get("rules") or []:
            if rule.get("name") == rule_name:
                return float(rule.get("min", rule.get("max")))
    except (OSError, ValueError, TypeError):
        pass
    return default


def resolve_metric(summary: dict, dotted: str):
    """Resolve a dotted path in a summary record to a gateable number.

    Numbers pass through (bools don't); "NaN"/"Infinity" string spellings
    parse back to floats; numeric lists resolve to their mean; other
    lists to their length. Missing path / unusable value -> None.
    """
    node = summary
    for part in dotted.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    return _scalarize(node)


def _scalarize(v):
    if isinstance(v, bool) or v is None:
        return None
    if isinstance(v, str):
        try:
            return float(v)
        except ValueError:
            return None
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, (list, tuple)):
        nums = [_scalarize(x) for x in v]
        if v and all(isinstance(x, (int, float)) and not isinstance(x, bool)
                     for x in v):
            return sum(nums) / len(nums)
        return float(len(v))
    return None


def _guard_key_matches(summary: dict, key: str, want) -> bool | None:
    """Whether dotted ``key`` resolves in ``summary`` and matches
    ``want`` (membership when a list); None when the key is absent."""
    node = summary
    for part in key.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    if isinstance(want, list):
        return node in want
    return node == want


def _when_applies(rule: dict, summary: dict) -> bool:
    for key, want in (rule.get("when") or {}).items():
        # absent key = guard unmatched: an inclusion guard fails closed
        if _guard_key_matches(summary, key, want) is not True:
            return False
    for key, want in (rule.get("when_not") or {}).items():
        # absent key excludes NOTHING: an exclusion guard keeps untagged
        # streams gated (the page-severity direction)
        if _guard_key_matches(summary, key, want) is True:
            return False
    return True


def evaluate_rules(rules, summary: dict) -> list[dict]:
    """One row per rule: ``{"rule", "metric", "value", "bound", "budget",
    "status": "ok"|"violated"|"skipped", ...}``. Skipped rows carry the
    reason (guard unmatched / metric absent)."""
    rows = []
    for rule in rules:
        bound = "min" if "min" in rule else "max"
        row = {
            "rule": rule.get("name", "?"),
            "metric": rule.get("metric", "?"),
            "bound": bound,
            "budget": rule.get(bound),
            "severity": rule.get("severity", "page"),
        }
        if not _when_applies(rule, summary):
            row.update(status="skipped", reason="when-guard unmatched")
            rows.append(row)
            continue
        value = resolve_metric(summary, rule.get("metric", ""))
        row["value"] = value
        if value is None or not math.isfinite(value):
            if rule.get("required"):
                row.update(status="violated",
                           reason="required metric absent/non-finite")
            else:
                row.update(status="skipped", reason="metric absent")
            rows.append(row)
            continue
        violated = (value < rule["min"] if bound == "min"
                    else value > rule["max"])
        row["status"] = "violated" if violated else "ok"
        rows.append(row)
    return rows


# ------------------------------------------------------------ transitions
class TransitionTracker:
    """Incremental per-channel KL threshold-crossing detector.

    Feed ``chunk`` events in stream order; each call returns the
    transitions that boundary revealed. Operates on the
    ``kl_per_feature`` rows serial/boolean streams carry (sweep streams
    carry per-replica totals — no per-channel signal, no transitions).
    ``direction`` is ``"down"`` when the channel fell through the
    threshold (compressed away by the annealing β) and ``"up"`` when it
    rose through it.
    """

    def __init__(self, threshold_nats: float):
        self.threshold_nats = float(threshold_nats)
        self._prev: dict[int, float] = {}

    def step(self, event: dict) -> list[dict]:
        kl = event.get("kl_per_feature")
        if not isinstance(kl, list):
            return []
        out = []
        for channel, value in enumerate(kl):
            value = _scalarize(value)
            if value is None or not math.isfinite(value):
                continue
            before = self._prev.get(channel)
            if before is not None:
                above_then = before >= self.threshold_nats
                above_now = value >= self.threshold_nats
                if above_then != above_now:
                    record = {
                        "channel": channel,
                        "epoch": event.get("epoch", 0),
                        "direction": "down" if above_then else "up",
                        "kl_before": round(before, 6),
                        "kl_after": round(value, 6),
                    }
                    beta = _scalarize(event.get("beta"))
                    if beta is not None:
                        record["beta"] = round(beta, 6)
                    out.append(record)
            self._prev[channel] = value
        return out


def detect_transitions(chunk_events, threshold_nats: float) -> list[dict]:
    """All info-plane transitions in an ordered chunk-event list (the
    terminal view of :class:`TransitionTracker`)."""
    tracker = TransitionTracker(threshold_nats)
    out: list[dict] = []
    for event in chunk_events:
        out.extend(tracker.step(event))
    return out


# --------------------------------------------------------------- durable
class _AlertSink:
    """Idempotent durable writes of alert/transition events onto a run's
    own stream. Existing events are scanned once so re-checking (CI re-
    runs, a tail reattach) never duplicates a record."""

    def __init__(self, directory: str, run_id: str | None,
                 existing_events=()):
        self._dir = directory
        self.run_id = run_id
        self._writer = None
        self._seen_alerts = set()
        self._seen_transitions = set()
        for e in existing_events:
            self.note_existing(e)

    def note_existing(self, event: dict) -> None:
        # Dedup is per RULE / per CROSSING within the stream: alerts from
        # an earlier check/tail under a different writer id must still
        # suppress re-writes, so the run id is not part of the key.
        if event.get("type") == "alert":
            self._seen_alerts.add(event.get("rule"))
        elif event.get("type") == "transition":
            self._seen_transitions.add(
                (event.get("channel"), event.get("epoch"),
                 event.get("direction")))

    def _ensure_writer(self):
        if self._writer is None:
            self._writer = EventWriter(
                self._dir, run_id=self.run_id, process_index=0,
                tags={"src": "slo"},
            )
        return self._writer

    def alert(self, row: dict, source: str) -> bool:
        key = row["rule"]
        if key in self._seen_alerts:
            return False
        self._seen_alerts.add(key)
        self._ensure_writer().alert(
            rule=row["rule"], metric=row["metric"], value=row.get("value"),
            bound=row["bound"], budget=row["budget"],
            severity=row["severity"], source=source,
            **({"reason": row["reason"]} if row.get("reason") else {}),
        )
        return True

    def burn(self, row: dict, source: str) -> bool:
        """One durable burn-rate alert (same per-rule idempotence as
        :meth:`alert` — the two kinds share the rule namespace)."""
        key = row["rule"]
        if key in self._seen_alerts:
            return False
        self._seen_alerts.add(key)
        self._ensure_writer().alert(
            rule=row["rule"], severity=row["severity"], source=source,
            budget=row.get("budget"), threshold=row.get("threshold"),
            burn_fast=row.get("burn_fast"), burn_slow=row.get("burn_slow"),
            windows_s=row.get("windows_s"),
        )
        return True

    def transition(self, record: dict, threshold_nats: float,
                   source: str) -> bool:
        key = (record["channel"], record["epoch"], record["direction"])
        if key in self._seen_transitions:
            return False
        self._seen_transitions.add(key)
        self._ensure_writer().transition(
            threshold_nats=threshold_nats, source=source, **record)
        return True

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None


# ------------------------------------------------------------------ live
class SLOEngine:
    """Incremental SLO evaluation for ``telemetry tail``.

    ``observe(event)`` feeds stream events as the follower yields them;
    ``flush()`` evaluates the rules against the current live view and
    writes durable ``alert``/``transition`` events (idempotently) onto
    the run's stream. Rules whose metrics only exist terminally
    (``faults.undetected`` needs the full join) are evaluated against
    whatever the live view can resolve and skipped otherwise — the
    terminal ``telemetry check`` is the authoritative gate.
    """

    def __init__(self, spec: dict, directory: str, write: bool = True):
        from dib_tpu.telemetry.live import LiveRunState

        self.spec = spec
        self.rules = spec.get("rules") or []
        self.threshold_nats = (spec.get("transitions") or {}).get(
            "kl_threshold_nats")
        self._state = LiveRunState()
        self._write = write
        self._sink = _AlertSink(directory, run_id=None)
        self._tracker = (TransitionTracker(self.threshold_nats)
                         if self.threshold_nats else None)
        self._pending_transitions: list[dict] = []
        self.alerts: list[dict] = []
        self.transitions: list[dict] = []

    def observe(self, event: dict) -> None:
        self._state.update(event)
        self._sink.note_existing(event)   # replayed alerts never re-write
        if self._sink.run_id is None and event.get("run"):
            self._sink.run_id = event["run"]
        if self._tracker is not None and event.get("type") == "chunk":
            self._pending_transitions.extend(self._tracker.step(event))

    def live_summary(self) -> dict:
        """The live view the rules resolve against — summarize-shaped keys
        from the rolling state."""
        st = self._state
        chunk = st.last_chunk() or {}
        view: dict = {
            "steps_per_s": st.steps_per_s,
            # summarize's steady-state semantics (first chunk per launch
            # excluded): None until a steady chunk landed, so a floor rule
            # SKIPS early instead of writing a durable false alert off the
            # compile-laden first chunk
            "steady_steps_per_s": st.steady_steps_per_s,
            "status": st.status,
        }
        for key in ("device_kind", "device_platform", "config_hash"):
            if key in st.manifest:
                view[key] = st.manifest[key]
        for src, dst in (("loss", "final_loss"),
                         ("val_loss", "final_val_loss")):
            if chunk.get(src) is not None:
                view[dst] = chunk[src]
        mfu = st.mfu() or {}
        if mfu.get("flops_frac_of_peak") is not None:
            view["mfu"] = mfu["flops_frac_of_peak"]
        return view

    def flush(self) -> None:
        rows = evaluate_rules(self.rules, self.live_summary())
        for row in rows:
            if row["status"] != "violated":
                continue
            if not self._write or self._sink.alert(row, source="tail"):
                self.alerts.append(row)
        for record in self._pending_transitions:
            if not self._write or self._sink.transition(
                    record, self.threshold_nats, source="tail"):
                self.transitions.append(record)
        self._pending_transitions = []

    def close(self) -> None:
        self._sink.close()


# -------------------------------------------------------------- terminal
def _load_bench_record(path: str) -> dict | None:
    """A bench.py one-liner as a rule-evaluable view, or None when ``path``
    is not one (run dirs / event streams take the summarize path). The
    view is the embedded telemetry summary (when present) with the bench
    record's own top-level fields — ``stale_seconds``, ``mfu``,
    ``degraded`` — layered on top, so both vocabularies resolve."""
    if not os.path.isfile(path):
        return None
    try:
        with open(path) as f:
            record = json.load(f)
    except (json.JSONDecodeError, UnicodeDecodeError, OSError):
        return None
    if not isinstance(record, dict) or "metric" not in record:
        return None
    if record.get("metric") == "run_telemetry_summary":
        return record
    embedded = record.get("telemetry")
    view = dict(embedded) if isinstance(embedded, dict) else {}
    view.update({k: v for k, v in record.items() if k != "telemetry"})
    return view


def check_run(path: str, slo_path: str = DEFAULT_SLO_PATH, *,
              run_id: str | None = None, process_index: int | None = None,
              write: bool = True) -> dict:
    """Evaluate a finished (or in-flight) run against the SLO file.

    Returns a report dict: per-rule rows, detected transitions, and the
    ``violations`` count; writes durable ``alert``/``transition`` events
    onto the run's stream unless ``write=False`` (a clean run writes
    NOTHING — checking a committed fixture leaves it bit-identical).
    ``telemetry check`` exits 1 when ``violations > 0``, 2 on unusable
    operands — the ``compare`` convention, against absolute budgets.
    """
    from dib_tpu.telemetry.summary import summarize

    spec = load_slo(slo_path)
    bench = _load_bench_record(path)
    if bench is not None:
        # a bench.py one-liner is a valid check operand (the compare
        # convention): rules evaluate against the record's top-level
        # fields (stale_seconds, mfu, degraded...) merged over its
        # embedded telemetry summary. Nothing durable to write to.
        rows = evaluate_rules(spec.get("rules") or [], bench)
        violations = [r for r in rows if r["status"] == "violated"]
        return {
            "slo": os.path.basename(slo_path),
            "run_id": bench.get("run_id"),
            "rules": rows,
            "violations": len(violations),
            "skipped": sum(r["status"] == "skipped" for r in rows),
            "transitions": [],
            "written": {"alerts": 0, "transitions": 0},
        }
    summary = summarize(path, process_index=process_index, run_id=run_id)
    events = list(read_events(path, process_index=process_index))
    if run_id is not None:
        events = [e for e in events if e.get("run") == run_id]

    rows = evaluate_rules(spec.get("rules") or [], summary)
    threshold = (spec.get("transitions") or {}).get("kl_threshold_nats")
    transitions = []
    if threshold:
        chunks = [e for e in events if e.get("type") == "chunk"]
        transitions = detect_transitions(chunks, threshold)

    directory = (path if os.path.isdir(path)
                 else os.path.dirname(path) or ".")
    # the sink's writer tags its events with the run they belong to —
    # fall back to any event's run when the stream never saw a run_start
    sink_run_id = run_id or summary.get("run_id") or next(
        (e.get("run") for e in events if e.get("run")), None)
    sink = _AlertSink(directory, run_id=sink_run_id,
                      existing_events=events)
    written = {"alerts": 0, "transitions": 0}
    try:
        for row in rows:
            if row["status"] == "violated" and write:
                written["alerts"] += sink.alert(row, source="check")
        if write:
            for record in transitions:
                written["transitions"] += sink.transition(
                    record, threshold, source="check")
    finally:
        sink.close()

    violations = [r for r in rows if r["status"] == "violated"]
    return {
        "slo": os.path.basename(slo_path),
        "run_id": summary.get("run_id"),
        "rules": rows,
        "violations": len(violations),
        "skipped": sum(r["status"] == "skipped" for r in rows),
        "transitions": transitions,
        "written": written,
    }
