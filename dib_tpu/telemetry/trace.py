"""Nestable device-truth spans: one name, three sinks.

``PhaseTimer`` (``utils/profiling.py``) gives honest wall-clock for a flat
set of phases; this module generalizes it to a HIERARCHY and fans each
interval out to every consumer that needs it:

  - a ``span`` event on the run's ``events.jsonl`` (schema-versioned, with
    span/parent ids and the full slash path, so offline tools can rebuild
    the tree — ``telemetry report`` renders it as a flame-style breakdown);
  - the same ``PhaseTimer`` accounting the existing intervals/report APIs
    read (the timer key is the span's path);
  - a ``jax.profiler.TraceAnnotation`` around the body, so the SAME name
    appears in a captured XLA trace — the host-side span and the device
    timeline are joined by name, which is what makes the timing
    "device-truth": a span's wall-clock can be attributed to the XLA ops
    that ran under it.

Async-dispatch correctness is inherited from ``PhaseTimer`` semantics:
register the span's device outputs on the yielded handle
(``handle.block_on(...)``) and the span blocks on them before closing, so
the compute lands in the span that launched it rather than in whichever
span happens to fetch first.

Thread safety: span ids are allocated under a lock; the nesting stack is
per-thread (``threading.local``), so concurrent threads (e.g. a checkpoint
writer thread) build independent, correctly-parented subtrees on one
tracer. Span names may contain ``/`` — each segment extends the path, so
``span("sweep/replica3/mi_bounds")`` works with or without enclosing spans.

Plumbing-free instrumentation: ``use_tracer(tracer)`` binds the active
tracer for the current context and the module-level ``span(name)`` uses it,
so deep code (hook adapters, workload internals) can open spans without
threading a tracer through every signature. With no tracer bound, spans
still nest and time (into a process-local fallback timer) but emit nothing.
"""

from __future__ import annotations

import contextlib
import contextvars
import sys
import threading
import time

from dib_tpu.utils.profiling import PhaseTimer

__all__ = ["SpanHandle", "SpannedHook", "Tracer", "current_tracer", "span",
           "use_tracer"]


class SpanHandle:
    """What a ``span(...)`` block sees: output registration + late tags."""

    def __init__(self):
        self._outputs: list = []
        self._fields: dict = {}

    def block_on(self, *arrays):
        """Register device outputs produced inside the span; the span blocks
        on them at exit so their compute time lands here (PhaseTimer
        semantics)."""
        self._outputs.extend(arrays)
        return arrays[0] if len(arrays) == 1 else arrays

    def annotate(self, **fields) -> None:
        """Attach fields to the span's event that are only known mid-span
        (e.g. the epoch a chunk ended on)."""
        self._fields.update(fields)


def _trace_annotation(path: str):
    """``jax.profiler.TraceAnnotation`` for ``path`` — but ONLY when jax is
    demonstrably live in this process: host-only consumers (``dib_tpu
    telemetry``, the watchdog supervisor) must not pay the jax import."""
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return contextlib.nullcontext()
    try:
        return jax_mod.profiler.TraceAnnotation(path)
    except Exception:
        return contextlib.nullcontext()


class Tracer:
    """Span factory bound to an optional ``EventWriter`` and ``PhaseTimer``.

    ``telemetry=None`` keeps spans timing into the timer (duration-only);
    ``timer=None`` creates a private one. One tracer serves a whole run —
    share it between the fit recorder and every hook so ids/parentage are
    consistent across the stream.
    """

    def __init__(self, telemetry=None, timer: PhaseTimer | None = None):
        self.telemetry = telemetry
        self.timer = timer if timer is not None else PhaseTimer()
        self._local = threading.local()
        self._lock = threading.Lock()
        self._next_id = 0

    def _stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _new_id(self) -> int:
        with self._lock:
            sid = self._next_id
            self._next_id += 1
        return sid

    @contextlib.contextmanager
    def span(self, name: str, **tags):
        """Open a nested span; yields a :class:`SpanHandle`."""
        stack = self._stack()
        span_id = self._new_id()
        parent_id = stack[-1][1] if stack else None
        prefix = stack[-1][0] + "/" if stack else ""
        path = prefix + name
        handle = SpanHandle()
        stack.append((path, span_id))
        start = time.perf_counter()
        try:
            with _trace_annotation(path):
                yield handle
        finally:
            # async dispatch defers device errors to the block — the span
            # must still pop and record even when block_until_ready raises
            # (a corrupted thread stack would mis-parent every later span)
            try:
                if handle._outputs:
                    import jax

                    jax.block_until_ready(handle._outputs)
            finally:
                elapsed = time.perf_counter() - start
                stack.pop()
                self._record(name, path, span_id, parent_id, elapsed,
                             {**tags, **handle._fields})

    def add(self, name: str, seconds: float, **tags) -> None:
        """Record an externally measured interval as a span — for callers
        whose boundaries are hook invocations rather than ``with`` blocks
        (``ChunkPhaseHooks``). Parented under the current span, if any."""
        stack = self._stack()
        parent_id = stack[-1][1] if stack else None
        prefix = stack[-1][0] + "/" if stack else ""
        self._record(name, prefix + name, self._new_id(), parent_id,
                     seconds, tags)

    def begin(self, name: str, **tags) -> tuple:
        """Open a span whose close is a separate call site (hook-pair
        boundaries: ``ChunkPhaseHooks.pre`` opens the instrumentation span,
        ``post`` closes it) — spans opened in between parent under it, so
        hook work nests instead of double-counting as siblings. Returns an
        opaque token for :meth:`end`."""
        stack = self._stack()
        span_id = self._new_id()
        parent_id = stack[-1][1] if stack else None
        prefix = stack[-1][0] + "/" if stack else ""
        path = prefix + name
        stack.append((path, span_id))
        return (name, path, span_id, parent_id, time.perf_counter(), tags)

    def end(self, token: tuple, **fields) -> None:
        """Close a :meth:`begin` span; tolerates a stack disturbed by an
        exception in between (removes this span's entry wherever it is)."""
        name, path, span_id, parent_id, start, tags = token
        stack = self._stack()
        entry = (path, span_id)
        if entry in stack:
            del stack[stack.index(entry):]   # also drop abandoned children
        self._record(name, path, span_id, parent_id,
                     time.perf_counter() - start, {**tags, **fields})

    def _record(self, name, path, span_id, parent_id, seconds, fields):
        self.timer.add(path, seconds)
        if self.telemetry is not None:
            self.telemetry.span(
                name=name, path=path, span_id=span_id, parent_id=parent_id,
                seconds=seconds, **fields,
            )


# --------------------------------------------------------------- active tracer
# A context-local binding so instrumentation deep in the call tree (hook
# adapters, workload internals) can open spans without signature plumbing.
_ACTIVE: contextvars.ContextVar[Tracer | None] = contextvars.ContextVar(
    "dib_tpu_active_tracer", default=None
)
_FALLBACK = Tracer()   # duration-only, process-local: span() never fails


def current_tracer() -> Tracer:
    """The tracer bound by the innermost ``use_tracer``, else a process-local
    duration-only fallback (spans still nest and time, nothing is emitted)."""
    return _ACTIVE.get() or _FALLBACK


@contextlib.contextmanager
def use_tracer(tracer: Tracer | None):
    """Bind ``tracer`` as the context's active tracer (None = no-op)."""
    if tracer is None:
        yield
        return
    token = _ACTIVE.set(tracer)
    try:
        yield
    finally:
        _ACTIVE.reset(token)


def span(name: str, **tags):
    """``current_tracer().span(name, **tags)`` — the plumbing-free spelling."""
    return current_tracer().span(name, **tags)


class SpannedHook:
    """Wraps a fit hook so each firing runs inside a named span.

    Like ``train.hooks.TimedHook`` but emitting into the span hierarchy of
    the ACTIVE tracer (``use_tracer``), so hook work nests under whatever
    span encloses the fit loop. Cadence-gated hooks (anything exposing
    ``fires_at``) that skip an epoch produce no phantom span; attribute
    access falls through to the inner hook.
    """

    def __init__(self, name: str, hook):
        self.name = name
        self.hook = hook

    def fires_at(self, epoch: int) -> bool:
        fires_at = getattr(self.hook, "fires_at", None)
        return fires_at(epoch) if fires_at is not None else True

    def __call__(self, trainer, state, epoch: int):
        fires_at = getattr(self.hook, "fires_at", None)
        if fires_at is not None and not fires_at(epoch):
            return
        with span(self.name, epoch=int(epoch)):
            self.hook(trainer, state, epoch)

    def __getattr__(self, attr):
        if attr in ("hook", "name") or attr.startswith("__"):
            raise AttributeError(attr)
        return getattr(self.hook, attr)
