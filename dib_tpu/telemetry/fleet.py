"""Fleet-wide causal tracing: merge many planes into ONE timeline.

``python -m dib_tpu telemetry fleet tail|summarize|report <roots...>``
attaches to any number of run directories (or whole runs roots) and
incrementally merges every plane's append-only stream it finds there —
``events.jsonl`` (run plane), ``journal.jsonl`` (sched), ``study.jsonl``
(study), ``publishes.jsonl`` (stream), ``deploys.jsonl`` (deploy) — into
one causally-ordered fleet timeline:

  - **Sources** are discovered by filename under each root and followed
    with the same incremental torn-line-tolerant reader ``telemetry
    tail`` uses (:class:`~dib_tpu.telemetry.live.StreamFollower`): a
    final line still being appended is buffered, a torn line mid-file
    is skipped and counted.
  - **Ordering** is deterministic under clock skew: entries sort by
    ``(t, source, n)`` where ``n`` is the per-source record index —
    within one source, FILE ORDER is authoritative (two records a
    skewed clock stamped identically never reorder), and across
    sources ties break on the stable source id. The durable timeline
    (``--out``) is append-only by ARRIVAL; the merged view is the
    sorted projection, so the merge digest is independent of poll
    batching — kill the aggregator mid-merge, re-attach, and the
    merged timeline is bit-identical (``timeline_digest``).
  - **Causality** comes from the ``ctx`` envelope
    (``telemetry/context.py``): every record's ``ctx.parent`` names the
    record that caused it (``study:<id>``, ``sched:job:<id>``, ...).
    A parent no merged source defines is an **orphan** — surfaced
    loudly in the summary (and a nonzero ``telemetry fleet summarize``
    exit code), never dropped: an orphan means a plane is missing from
    the merge or a producer broke the propagation contract.
  - **Burn-rate SLOs** (``SLO.json`` ``burn_rates``,
    ``telemetry/slo.py``): ``fleet tail --slo`` evaluates fast/slow
    windowed error-budget burn over the merged view and lands durable
    ``alert`` events on the originating run's OWN stream — the existing
    ``check``/``compare`` gates see them with no new machinery.

Resume contract (``--out``): the durable ``timeline.jsonl`` is itself
the cursor. On re-attach the aggregator replays it, seals a torn final
line, counts how many records of each source were already consumed, and
skips exactly that many on the first polls — zero duplicate, zero lost
entries, chaos-drilled by ``scripts/fleet_drill.py``.

Everything here is host-side file analysis: this module never imports
jax.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time

from dib_tpu.telemetry.live import StreamFollower

__all__ = ["FleetAggregator", "TIMELINE_FILENAME", "discover_sources",
           "fleet_main", "fleet_prometheus", "merge_key", "render_fleet",
           "timeline_digest", "write_fleet_report"]

TIMELINE_FILENAME = "timeline.jsonl"

# plane by filename: which append-only streams a root can contribute
PLANE_BY_FILENAME = {
    "events.jsonl": "run",
    "journal.jsonl": "sched",
    "study.jsonl": "study",
    "publishes.jsonl": "stream",
    "deploys.jsonl": "deploy",
}


# --------------------------------------------------------------- discovery
def discover_sources(roots) -> list[dict]:
    """Every known plane file under each root (recursive, deterministic
    order): ``{"source", "plane", "path", "root"}`` rows. The source id
    is ``<root-label>/<relative-path>`` with ``/`` separators — stable
    across polls and across processes looking at the same tree, which
    is what makes the merge order and the resume cursor portable."""
    sources: list[dict] = []
    labels: dict[str, str] = {}
    for i, root in enumerate(roots):
        root = os.path.normpath(root)
        label = os.path.basename(root) or "root"
        if label in labels and labels[label] != root:
            label = f"{label}#{i}"
        labels[label] = root
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames
                                 if not d.startswith("."))
            for name in sorted(filenames):
                plane = PLANE_BY_FILENAME.get(name)
                if plane is None:
                    continue
                path = os.path.join(dirpath, name)
                rel = os.path.relpath(path, root).replace(os.sep, "/")
                sources.append({
                    "source": f"{label}/{rel}",
                    "plane": plane,
                    "path": path,
                    "root": root,
                })
    return sources


def merge_key(entry: dict):
    """The deterministic fleet order: wall-clock first (the causal
    approximation), then source id, then the per-source file index —
    within one source file order is authoritative, so skewed clocks can
    never reorder one writer against itself."""
    return (float(entry.get("t") or 0.0), entry.get("source", ""),
            int(entry.get("n") or 0))


def timeline_digest(entries) -> str:
    """SHA-256 over the canonically-serialized MERGED order — the
    batching-independent identity of a fleet timeline (the chaos
    drill's bit-identical invariant)."""
    h = hashlib.sha256()
    for entry in sorted(entries, key=merge_key):
        h.update(json.dumps(entry, sort_keys=True,
                            separators=(",", ":")).encode())
        h.update(b"\n")
    return h.hexdigest()


def _read_jsonl(path: str) -> list[dict]:
    """All parseable records of a JSONL file, file order, torn lines
    skipped (the journal replay contract, locally — the sched package
    must not become a telemetry dependency)."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return []
    out: list[dict] = []
    for line in raw.split(b"\n"):
        if not line.strip():
            continue
        try:
            record = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(record, dict):
            out.append(record)
    return out


# -------------------------------------------------------------- aggregator
class FleetAggregator:
    """Incremental multi-plane merge over any number of roots.

    Thread-safe: ``poll()`` may run on a background thread while a
    renderer reads ``merged()``/``summary()`` — every access to the
    shared timeline goes through the instance lock (the EventWriter
    discipline; dib-lint's thread-shared-state pass pins this).
    """

    def __init__(self, roots, out_dir: str | None = None):
        self.roots = [os.path.normpath(r) for r in roots]
        self._lock = threading.Lock()
        self._followers: dict[str, StreamFollower] = {}
        self._sources: dict[str, dict] = {}
        self._consumed: dict[str, int] = {}
        self._skip: dict[str, int] = {}
        self._entries: list[dict] = []
        self._fd = None
        self.out_path = None
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
            self.out_path = os.path.join(out_dir, TIMELINE_FILENAME)
            self._resume()
        self._discover()

    # -- durable timeline -------------------------------------------------
    def _resume(self) -> None:
        """Replay the durable timeline into the in-memory view and derive
        the per-source consumed counts — the resume cursor IS the output
        file, so a SIGKILLed aggregator re-attaches with zero duplicate
        and zero lost entries."""
        for entry in _read_jsonl(self.out_path):
            if not isinstance(entry.get("source"), str):
                continue
            self._entries.append(entry)
            sid = entry["source"]
            self._skip[sid] = self._skip.get(sid, 0) + 1
            self._consumed[sid] = self._skip[sid]
        self._fd = os.open(self.out_path,
                           os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
        # seal a torn final line (aggregator killed mid-append): the torn
        # bytes were not replayed above, so the entry re-appends whole
        try:
            size = os.fstat(self._fd).st_size
            if size > 0:
                with open(self.out_path, "rb") as f:
                    f.seek(size - 1)
                    if f.read(1) != b"\n":
                        os.write(self._fd, b"\n")
        except OSError:
            pass

    def _discover(self) -> None:
        for src in discover_sources(self.roots):
            sid = src["source"]
            if sid in self._followers:
                continue
            self._followers[sid] = StreamFollower(src["path"])
            self._sources[sid] = src
            self._consumed.setdefault(sid, 0)
            self._skip.setdefault(sid, 0)

    # -- polling ----------------------------------------------------------
    def poll(self) -> list[dict]:
        """Consume whatever every source appended since the last call;
        returns the NEW timeline entries (arrival order)."""
        self._discover()
        fresh: list[dict] = []
        for sid in sorted(self._followers):
            follower = self._followers[sid]
            plane = self._sources[sid]["plane"]
            for record in follower.poll():
                with self._lock:
                    if self._skip[sid] > 0:
                        # already durable from a previous attach — the
                        # replay set _consumed past this prefix, so the
                        # numbering must NOT advance here or every later
                        # entry's n (and the merged order) would shift
                        self._skip[sid] -= 1
                        continue
                    n = self._consumed[sid]
                    self._consumed[sid] = n + 1
                    entry = {"source": sid, "plane": plane, "n": n,
                             "t": record.get("t"), "record": record}
                    self._entries.append(entry)
                if self._fd is not None:
                    # allow_nan stays on: a source record that smuggled a
                    # NaN through json.loads must not crash the merge
                    line = json.dumps(entry) + "\n"
                    # one write per line on an O_APPEND fd: a kill tears
                    # at most the final line (the journal contract)
                    os.write(self._fd, line.encode())
                fresh.append(entry)
        return fresh

    @property
    def torn(self) -> int:
        return sum(f.torn for f in self._followers.values())

    def merged(self) -> list[dict]:
        """The full timeline in deterministic fleet order."""
        with self._lock:
            snapshot = list(self._entries)
        return sorted(snapshot, key=merge_key)

    def entries(self) -> list[dict]:
        with self._lock:
            return list(self._entries)

    # -- causality --------------------------------------------------------
    def _defined_refs(self, entries) -> set[str]:
        """Every ``plane:record_ref`` some merged source DEFINES — the
        resolution set orphan detection checks ``ctx.parent`` against."""
        defined: set[str] = set()
        for entry in entries:
            record = entry.get("record") or {}
            plane = entry.get("plane")
            if plane == "run" and record.get("run"):
                defined.add(f"run:{record['run']}")
            kind = record.get("kind")
            if plane == "sched":
                if kind == "job" and record.get("job_id"):
                    defined.add(f"sched:job:{record['job_id']}")
                elif kind == "unit" and record.get("unit_id"):
                    defined.add(f"sched:unit:{record['unit_id']}")
            elif plane == "stream":
                if kind == "publish" and record.get("publish_id"):
                    defined.add(f"publish:{record['publish_id']}")
                elif kind == "drift" and record.get("round") is not None:
                    defined.add(f"drift:{record['round']}")
            elif plane == "study":
                # the study directory IS the study id (controller
                # contract: study_id = basename of the study dir)
                sid = entry.get("source", "")
                parts = sid.split("/")
                if len(parts) >= 2:
                    defined.add(f"study:{parts[-2]}")
            if record.get("study_id"):
                defined.add(f"study:{record['study_id']}")
        return defined

    def analyze(self) -> dict:
        """Causal analysis of the merged timeline: per-trace rollups and
        the orphan list (records whose ``ctx.parent`` resolves to no
        record any merged source contains)."""
        entries = self.merged()
        defined = self._defined_refs(entries)
        orphans: list[dict] = []
        traces: dict[str, dict] = {}
        plane_counts: dict[str, int] = {}
        for entry in entries:
            record = entry.get("record") or {}
            plane = entry.get("plane", "?")
            plane_counts[plane] = plane_counts.get(plane, 0) + 1
            ctx = record.get("ctx")
            if not isinstance(ctx, dict) or not ctx.get("trace_id"):
                continue
            tid = ctx["trace_id"]
            row = traces.setdefault(tid, {
                "trace_id": tid, "records": 0, "planes": set(),
                "origins": set(), "sched_units": 0, "run_events": 0,
                "orphans": 0,
            })
            row["records"] += 1
            row["planes"].add(plane)
            row["origins"].update(ctx.get("origin") or ())
            if plane == "sched" and record.get("kind") == "unit":
                row["sched_units"] += 1
            if plane == "run":
                row["run_events"] += 1
            parent = ctx.get("parent")
            if parent and parent not in defined:
                row["orphans"] += 1
                orphans.append({
                    "source": entry.get("source"),
                    "plane": plane,
                    "n": entry.get("n"),
                    "parent": parent,
                    "type": record.get("type") or record.get("kind"),
                    "trace_id": tid,
                })
        for row in traces.values():
            row["planes"] = sorted(row["planes"])
            row["origins"] = sorted(row["origins"])
        sched_units_total = sum(
            1 for e in entries
            if e.get("plane") == "sched"
            and (e.get("record") or {}).get("kind") == "unit")
        run_events_total = plane_counts.get("run", 0)
        return {
            "entries": len(entries),
            "planes": plane_counts,
            "defined_refs": len(defined),
            "orphans": orphans,
            "traces": sorted(traces.values(),
                             key=lambda r: -r["records"]),
            "sched_units_total": sched_units_total,
            "run_events_total": run_events_total,
        }

    def summary(self) -> dict:
        """The fleet view as a bench-record-shaped dict (``metric:
        fleet_trace``) — directly evaluable by ``telemetry check`` /
        ``check_run_artifacts`` against the committed SLO rows."""
        analysis = self.analyze()
        sources = [{
            "source": sid,
            "plane": self._sources[sid]["plane"],
            "records": self._consumed.get(sid, 0),
            "torn": self._followers[sid].torn,
        } for sid in sorted(self._sources)]
        return {
            "metric": "fleet_trace",
            "unit": "events",
            "value": analysis["entries"],
            "roots": [os.path.abspath(r) for r in self.roots],
            "sources": sources,
            "planes": analysis["planes"],
            "torn": self.torn,
            "defined_refs": analysis["defined_refs"],
            "orphan_events": len(analysis["orphans"]),
            "orphans": analysis["orphans"],
            "traces": analysis["traces"],
            "sched_units_total": analysis["sched_units_total"],
            "run_events_total": analysis["run_events_total"],
            "digest": timeline_digest(self.entries()),
        }

    def close(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None


# -------------------------------------------------------------- prometheus
def fleet_prometheus(agg: FleetAggregator, prefix: str = "dib") -> str:
    """Fleet-wide Prometheus exposition: the LAST ``metrics`` rollup of
    every run-plane source, aggregated — counters summed across workers
    (the prefork-supervisor view, pids and all, collapses into fleet
    totals), gauges last-write-wins in fleet order, histograms merged on
    their mergeable stats (count/sum/min/max plus the fixed-bound
    ``le_*`` bucket counts, which sum exactly because every worker
    buckets against the same fleet-wide BUCKET_BOUNDS; windowed
    percentiles do not merge and are dropped — the merged ``_bucket``
    series carry the fleet quantiles instead) — plus the aggregator's
    own meta-gauges (sources, entries, torn lines, orphans)."""
    from dib_tpu.telemetry.metrics import prometheus_text

    counters: dict[str, float] = {}
    gauges: dict[str, float] = {}
    hists: dict[str, dict] = {}
    for entry in agg.merged():
        record = entry.get("record") or {}
        if entry.get("plane") != "run" or record.get("type") != "metrics":
            continue
        for snap in record.get("snapshots") or []:
            if not isinstance(snap, dict):
                continue
            for key, value in snap.items():
                if not isinstance(value, (int, float)) \
                        or isinstance(value, bool):
                    continue
                group, _, rest = key.partition(".")
                if group == "counters" and rest:
                    counters[rest] = counters.get(rest, 0.0) + float(value)
                elif group == "gauges" and rest:
                    gauges[rest] = float(value)   # fleet-order last wins
                elif group == "histograms" and rest:
                    name, _, stat = rest.rpartition(".")
                    if not name:
                        continue
                    h = hists.setdefault(name, {})
                    if stat in ("count", "sum") \
                            or stat.startswith("le_"):
                        h[stat] = h.get(stat, 0.0) + float(value)
                    elif stat == "min":
                        h[stat] = min(h.get(stat, float(value)),
                                      float(value))
                    elif stat == "max":
                        h[stat] = max(h.get(stat, float(value)),
                                      float(value))
    analysis = agg.analyze()
    gauges["fleet.sources"] = float(len(agg._sources))
    gauges["fleet.entries"] = float(analysis["entries"])
    gauges["fleet.torn_lines"] = float(agg.torn)
    gauges["fleet.orphan_events"] = float(len(analysis["orphans"]))
    gauges["fleet.traces"] = float(len(analysis["traces"]))
    snapshot = {"counters": counters, "gauges": gauges,
                "histograms": hists}
    return prometheus_text(snapshot, prefix=prefix)


# ------------------------------------------------------------ html report
def _trace_edges(entries) -> dict[str, dict]:
    """Per-trace parent→children adjacency over DEFINED entity refs (the
    study→units→publish DAG the mission-control page renders)."""
    graphs: dict[str, dict] = {}
    for entry in entries:
        record = entry.get("record") or {}
        ctx = record.get("ctx")
        if not isinstance(ctx, dict) or not ctx.get("trace_id"):
            continue
        parent = ctx.get("parent")
        if not parent:
            continue
        plane = entry.get("plane")
        kind = record.get("kind")
        child = None
        if plane == "sched" and kind == "job" and record.get("job_id"):
            child = f"sched:job:{record['job_id']}"
        elif plane == "sched" and kind == "unit" and record.get("unit_id"):
            child = f"sched:unit:{record['unit_id']}"
        elif plane == "run" and record.get("run"):
            child = f"run:{record['run']}"
        elif plane == "stream" and kind == "publish" \
                and record.get("publish_id"):
            child = f"publish:{record['publish_id']}"
        if child is None or child == parent:
            continue
        graph = graphs.setdefault(ctx["trace_id"],
                                  {"edges": {}, "nodes": set()})
        graph["nodes"].update((parent, child))
        graph["edges"].setdefault(parent, set()).add(child)
    return graphs


def _render_dag(graph: dict, esc) -> str:
    """One trace's DAG as a nested list, roots first (a node that is
    never a child is a root — the study, usually)."""
    children = graph["edges"]
    all_children = {c for kids in children.values() for c in kids}
    roots = sorted(n for n in graph["nodes"] if n not in all_children)

    def render(node: str, seen: frozenset) -> str:
        kids = sorted(children.get(node, ()))
        inner = ""
        if kids and node not in seen:
            seen = seen | {node}
            inner = "<ul>" + "".join(
                render(k, seen) for k in kids) + "</ul>"
        return f"<li><code>{esc(node)}</code>{inner}</li>"

    if not roots:
        return '<p class="note">no resolvable edges</p>'
    return "<ul>" + "".join(render(r, frozenset()) for r in roots) + "</ul>"


def render_fleet(agg: FleetAggregator) -> str:
    """The fleet mission-control page: per-plane health tiles, the
    per-trace causal DAG, and the orphan ledger — same self-contained
    HTML contract as the per-run report (inline CSS, no external
    assets)."""
    from dib_tpu.telemetry.report import _CSS, _esc

    entries = agg.merged()
    analysis = agg.analyze()
    summary = agg.summary()

    tiles = []
    for plane in ("study", "sched", "run", "stream", "deploy"):
        count = analysis["planes"].get(plane, 0)
        n_sources = sum(1 for s in agg._sources.values()
                        if s["plane"] == plane)
        torn = sum(agg._followers[sid].torn for sid, s
                   in agg._sources.items() if s["plane"] == plane)
        plane_orphans = sum(1 for o in analysis["orphans"]
                            if o["plane"] == plane)
        ok = n_sources > 0 and torn == 0 and plane_orphans == 0
        tiles.append(
            f'<div class="tile"><h3>{_esc(plane)}</h3>'
            f"<p>{'✅' if ok else ('—' if n_sources == 0 else '⚠')} "
            f"{n_sources} source(s) · {count} record(s)"
            + (f" · {torn} torn" if torn else "")
            + (f" · {plane_orphans} orphan(s)" if plane_orphans else "")
            + "</p></div>")

    graphs = _trace_edges(entries)
    trace_html = []
    for row in analysis["traces"]:
        tid = row["trace_id"]
        graph = graphs.get(tid)
        dag = (_render_dag(graph, _esc) if graph
               else '<p class="note">no resolvable edges</p>')
        trace_html.append(
            f"<h3><code>{_esc(tid)}</code></h3>"
            f"<p class=\"note\">{row['records']} record(s) across "
            f"{', '.join(row['planes'])} · origins "
            f"{' → '.join(row['origins']) or '—'}"
            + (f" · ⚠ {row['orphans']} orphan(s)" if row["orphans"]
               else "")
            + f"</p>{dag}")
    orphan_rows = "".join(
        "<tr>"
        f"<td><code>{_esc(o.get('parent', ''))}</code></td>"
        f"<td>{_esc(o.get('plane', ''))}</td>"
        f"<td>{_esc(str(o.get('type', '')))}</td>"
        f"<td><code>{_esc(o.get('source', ''))}</code>:{o.get('n')}</td>"
        "</tr>" for o in analysis["orphans"])
    orphans_html = (
        "<table><thead><tr><th>unresolved parent</th><th>plane</th>"
        "<th>record</th><th>source:n</th></tr></thead>"
        f"<tbody>{orphan_rows}</tbody></table>" if orphan_rows else
        '<p class="note">none — every ctx.parent resolves to a merged '
        "record.</p>")

    roots = " · ".join(f"<code>{_esc(r)}</code>" for r in summary["roots"])
    return f"""<!DOCTYPE html>
<html lang="en"><head><meta charset="utf-8">
<meta name="viewport" content="width=device-width, initial-scale=1">
<title>dib-tpu fleet mission control</title>
<style>{_CSS}
.tiles {{ display: flex; flex-wrap: wrap; gap: 0.6rem; }}
.tile {{ border: 1px solid var(--border, #ccc); border-radius: 6px;
         padding: 0.4rem 0.8rem; min-width: 10rem; }}
.tile h3 {{ margin: 0.2rem 0; }}</style></head>
<body>
<h1>dib-tpu fleet mission control</h1>
<p class="sub">{roots}
 · {summary['value']} merged record(s) from {len(summary['sources'])}
 source(s) · {len(summary['traces'])} trace(s)
 · digest <code>{_esc(summary['digest'][:16])}…</code></p>
<h2>Plane health</h2>
<div class="tiles">{''.join(tiles)}</div>
<h2>Causal DAG</h2>
<p class="note">One tree per trace_id: every edge is a record whose
<code>ctx.parent</code> names the parent entity
(docs/observability.md "Fleet causality").</p>
{''.join(trace_html) or '<p class="note">no traced records yet.</p>'}
<h2>Orphan events</h2>
{orphans_html}
</body></html>
"""


def write_fleet_report(roots, out: str) -> str:
    agg = FleetAggregator(roots)
    agg.poll()
    try:
        html_text = render_fleet(agg)
    finally:
        agg.close()
    with open(out, "w") as f:
        f.write(html_text)
    return out


# ------------------------------------------------------------ burn alerts
class _BurnAlerter:
    """Routes firing burn-rate rules to the ORIGINATING run's own event
    stream (durably, idempotently — the ``_AlertSink`` contract): for
    each root that contributed bad-matching records, the alert lands in
    that root's run-plane directory, where the existing ``telemetry
    check``/``compare`` gates already look."""

    def __init__(self, agg: FleetAggregator):
        self._agg = agg
        self._sinks: dict[str, object] = {}
        self.written: list[dict] = []

    def _sink_for(self, directory: str):
        from dib_tpu.telemetry.events import read_events
        from dib_tpu.telemetry.slo import _AlertSink

        sink = self._sinks.get(directory)
        if sink is None:
            sink = _AlertSink(directory, run_id=None,
                              existing_events=read_events(directory))
            self._sinks[directory] = sink
        return sink

    def _origin_dirs(self, rule: dict, now: float) -> list[str]:
        from dib_tpu.telemetry.slo import _entry_matches

        lo = now - float(rule["slow_window_s"])
        roots: set[str] = set()
        for entry in self._agg.entries():
            t = float(entry.get("t") or 0.0)
            if t < lo or t > now:
                continue
            if _entry_matches(rule.get("bad") or {}, entry.get("plane", ""),
                              entry.get("record") or {}):
                src = self._agg._sources.get(entry.get("source"))
                if src:
                    roots.add(src["root"])
        dirs = []
        for root in sorted(roots):
            run_dirs = sorted(
                os.path.dirname(s["path"])
                for s in self._agg._sources.values()
                if s["root"] == root and s["plane"] == "run")
            if run_dirs:
                dirs.append(run_dirs[0])
        return dirs

    def land(self, rules_by_name: dict, rows, now: float) -> None:
        for row in rows:
            if row.get("status") != "firing":
                continue
            rule = rules_by_name.get(row["rule"])
            if rule is None:
                continue
            for directory in self._origin_dirs(rule, now):
                if self._sink_for(directory).burn(row, source="fleet"):
                    self.written.append({"rule": row["rule"],
                                         "dir": directory})

    def close(self) -> None:
        for sink in self._sinks.values():
            sink.close()
        self._sinks = {}


# -------------------------------------------------------------------- CLI
def build_fleet_parser():
    import argparse

    parser = argparse.ArgumentParser(
        prog="dib_tpu telemetry fleet",
        description="Merge many runs' planes into one causally-ordered "
                    "fleet timeline (docs/observability.md 'Fleet "
                    "causality').")
    sub = parser.add_subparsers(dest="action", required=True)

    def add_common(p):
        p.add_argument("roots", nargs="+",
                       help="Run directories or runs roots to merge "
                            "(searched recursively for events.jsonl / "
                            "journal.jsonl / study.jsonl / "
                            "publishes.jsonl / deploys.jsonl).")

    p_tail = sub.add_parser(
        "tail", help="Follow the fleet live; --out makes the merge "
                     "durable and resumable, --slo evaluates burn-rate "
                     "rules.")
    add_common(p_tail)
    p_tail.add_argument("--out", default=None,
                        help="Durable timeline directory (timeline.jsonl; "
                             "re-attaching resumes with zero duplicate / "
                             "zero lost entries).")
    p_tail.add_argument("--slo", default=None,
                        help="SLO.json with burn_rates rules to evaluate "
                             "each refresh; firing rules land durable "
                             "alert events on the originating run's "
                             "stream.")
    p_tail.add_argument("--refresh-s", type=float, default=1.0,
                        dest="refresh_s")
    p_tail.add_argument("--duration-s", type=float, default=None,
                        dest="duration_s",
                        help="Stop after this long (default: until the "
                             "sources go quiet when --once, else until "
                             "interrupted).")
    p_tail.add_argument("--once", action="store_true",
                        help="One poll cycle, then exit (scripting).")

    p_sum = sub.add_parser(
        "summarize", help="One-shot merge: print the fleet summary "
                          "record (metric: fleet_trace); exits 1 when "
                          "any orphan events exist.")
    add_common(p_sum)
    p_sum.add_argument("--out", default=None,
                       help="Also write the summary record to this path.")

    p_rep = sub.add_parser(
        "report", help="Render the fleet mission-control HTML page.")
    add_common(p_rep)
    p_rep.add_argument("--out", default="fleet_report.html",
                       help="HTML output path.")

    p_prom = sub.add_parser(
        "prometheus", help="Print the fleet-aggregated Prometheus "
                           "exposition (per-worker metrics rollups "
                           "summed).")
    add_common(p_prom)
    return parser


def _tail_main(args) -> int:
    agg = FleetAggregator(args.roots, out_dir=args.out)
    spec = None
    alerter = None
    burn_rows: list[dict] = []
    if args.slo:
        from dib_tpu.telemetry.slo import load_slo

        spec = load_slo(args.slo)
        alerter = _BurnAlerter(agg)
    deadline = (time.monotonic() + args.duration_s
                if args.duration_s else None)
    try:
        while True:
            fresh = agg.poll()
            if spec is not None:
                entries = agg.entries()
                now = max((float(e.get("t") or 0.0) for e in entries),
                          default=0.0)
                from dib_tpu.telemetry.slo import evaluate_burn_rates

                burn = spec.get("burn_rates") or []
                burn_rows = evaluate_burn_rates(burn, entries, now=now)
                alerter.land({r.get("name"): r for r in burn},
                             burn_rows, now)
            if fresh or args.once:
                firing = [r["rule"] for r in burn_rows
                          if r.get("status") == "firing"]
                print(json.dumps({
                    "entries": len(agg.entries()),
                    "new": len(fresh), "torn": agg.torn,
                    "firing": firing,
                }), flush=True)
            if args.once:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(args.refresh_s)
    except KeyboardInterrupt:
        pass
    finally:
        if alerter is not None:
            alerter.close()
        agg.close()
    summary = agg.summary()
    out = {"entries": summary["value"], "torn": summary["torn"],
           "orphan_events": summary["orphan_events"],
           "digest": summary["digest"]}
    if burn_rows:
        out["burn_rates"] = burn_rows
    if alerter is not None:
        out["alerts_written"] = alerter.written
    print(json.dumps(out))
    return 0


def _summarize_main(args) -> int:
    import sys

    agg = FleetAggregator(args.roots)
    agg.poll()
    try:
        summary = agg.summary()
    finally:
        agg.close()
    if args.out:
        with open(args.out, "w") as f:
            f.write(json.dumps(summary, indent=1) + "\n")
    print(json.dumps(summary, indent=1))
    for orphan in summary["orphans"]:
        print(f"fleet: ORPHAN {orphan['parent']!r} claimed by "
              f"{orphan['source']}:{orphan['n']} "
              f"({orphan['plane']}/{orphan['type']}) — no merged source "
              "defines it", file=sys.stderr)
    return 1 if summary["orphan_events"] else 0


def fleet_main(argv) -> int:
    args = build_fleet_parser().parse_args(list(argv))
    if args.action == "tail":
        return _tail_main(args)
    if args.action == "summarize":
        return _summarize_main(args)
    if args.action == "prometheus":
        agg = FleetAggregator(args.roots)
        agg.poll()
        try:
            print(fleet_prometheus(agg), end="")
        finally:
            agg.close()
        return 0
    path = write_fleet_report(args.roots, args.out)
    print(json.dumps({"html": path}))
    return 0
