"""Fleet run registry: an append-only index of every run under a runs root.

A single run's events.jsonl answers "what happened in THIS run"; nothing
so far answered "what runs exist, and how has performance moved across
them" — the cross-run trajectory the MFU push and the serving scale-up
campaigns gate against. This module maintains ``<runs_root>/index.jsonl``:
one JSON line per registration, append-only with the same one-``os.write``
durability contract as the event stream. A run registered twice (resumed,
re-summarized) is superseded by its LATEST line — readers fold by
``run_id``, so the file never needs rewriting.

Entry kinds:

  - ``run``   — a training/serving run directory: ``run_id``, status
    (incl. ``preempted``/``incomplete``), headline metrics at run_end
    (steps/s, finals, MFU, serving p99, mitigation/alert counts), and
    provenance (git SHA, device, config hash).
  - ``bench`` — one ``bench.py`` invocation's headline numbers (projected
    minutes, steps/s, MFU, ``vs_baseline``); ``telemetry runs trajectory``
    renders these as the fleet's perf trajectory, and the index report
    charts them.

CLI surface (``python -m dib_tpu telemetry runs ...``)::

    telemetry runs list   [--runs-root R]          # latest entry per run
    telemetry runs show   <run_id> [--runs-root R] # full entry (+history)
    telemetry runs trajectory [--runs-root R]      # bench perf trajectory
    telemetry report --index  [--runs-root R]      # multi-run HTML page

The runs root resolves from ``--runs-root``, else ``DIB_RUNS_ROOT``, else
``./runs`` — the repo's committed runs directory, whose ``index.jsonl``
seeds the trajectory from the committed BENCH_* history.

Host-side file analysis only: this module never imports jax.
"""

from __future__ import annotations

import json
import os
import sys
import time

__all__ = ["INDEX_FILENAME", "RunRegistry", "bench_entry",
           "register_drill_record", "register_run", "resolve_runs_root",
           "run_entry", "runs_main", "validate_index_entry"]

INDEX_FILENAME = "index.jsonl"
INDEX_VERSION = 1

_RUN_METRIC_KEYS = (
    "steps_per_s", "steady_steps_per_s", "final_loss", "final_val_loss",
    "final_total_kl", "final_mi_lower_bits_mean", "mfu", "wall_clock_s",
    "total_steps", "launches", "mitigations_total", "heartbeat_max_gap_s",
)
_PROVENANCE_KEYS = ("git_sha", "device_kind", "device_platform",
                    "device_count", "process_count", "config_hash")


def resolve_runs_root(root: str | None = None) -> str | None:
    """``--runs-root`` flag > ``DIB_RUNS_ROOT`` env > ``./runs``. An empty
    string at any level disables registration (returns None)."""
    if root is None:
        root = os.environ.get("DIB_RUNS_ROOT")
    if root is None:
        root = "runs"
    return root or None


class RunRegistry:
    """The append-only ``index.jsonl`` under one runs root."""

    def __init__(self, root: str):
        self.root = root
        self.path = os.path.join(root, INDEX_FILENAME)

    # ------------------------------------------------------------- write
    def append(self, entry: dict) -> dict:
        """Append one entry (one durable ``os.write``); stamps the index
        schema version and the registration time."""
        record = {"v": INDEX_VERSION,
                  "t": round(time.time(), 3),   # timing-ok: registration
                  # timestamp, not a measured interval
                  **entry}
        os.makedirs(self.root, exist_ok=True)
        line = json.dumps(record, default=str, allow_nan=False) + "\n"
        fd = os.open(self.path, os.O_APPEND | os.O_CREAT | os.O_WRONLY,
                     0o644)
        try:
            os.write(fd, line.encode())
        finally:
            os.close(fd)
        return record

    # -------------------------------------------------------------- read
    def entries(self) -> list[dict]:
        """All parseable entries, file order. A torn final line (writer
        killed mid-append) is skipped, same as the event stream."""
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except OSError:
            return []
        out = []
        for line in raw.split(b"\n"):
            if not line.strip():
                continue
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(parsed, dict):
                out.append(parsed)
        return out

    def latest(self) -> dict[str, dict]:
        """run_id -> the LATEST entry for it (append-only supersede)."""
        out: dict[str, dict] = {}
        for entry in self.entries():
            if entry.get("kind") == "run" and entry.get("run_id"):
                out[entry["run_id"]] = entry
        return out

    def history(self, run_id: str) -> list[dict]:
        return [e for e in self.entries() if e.get("run_id") == run_id]

    def bench_history(self) -> list[dict]:
        """Bench entries in file order — the fleet's perf trajectory."""
        return [e for e in self.entries() if e.get("kind") == "bench"]


# ----------------------------------------------------------------- entries
def _lineage_from_stream(run_dir: str) -> dict | None:
    """The run's causal lineage — the first ``ctx`` envelope its event
    stream carries (``telemetry/context.py``): ``{"trace_id", "parent",
    "origin"}``. None for pre-tracing streams (absence stays absent —
    the registry never invents lineage)."""
    from dib_tpu.telemetry.events import read_events

    try:
        for event in read_events(run_dir):
            ctx = event.get("ctx")
            if isinstance(ctx, dict) and ctx.get("trace_id"):
                return {k: ctx[k] for k in ("trace_id", "parent", "origin")
                        if k in ctx}
    except OSError:
        pass
    return None


def run_entry(run_dir: str, summary: dict | None = None,
              extra: dict | None = None) -> dict:
    """Registry entry for a run directory, from its stream's summary."""
    if summary is None:
        from dib_tpu.telemetry.summary import summarize

        summary = summarize(run_dir)
    metrics = {k: summary[k] for k in _RUN_METRIC_KEYS if k in summary
               and summary[k] is not None}
    serving = summary.get("serving") or {}
    if serving.get("request_p99_ms") is not None:
        metrics["serving_p99_ms"] = serving["request_p99_ms"]
        metrics["requests_per_s"] = serving.get("requests_per_s")
    alerts = summary.get("alerts") or {}
    if alerts.get("count"):
        metrics["alerts"] = alerts["count"]
    transitions = summary.get("transitions") or {}
    if transitions.get("count"):
        metrics["transitions"] = transitions["count"]
    faults = summary.get("faults") or {}
    if faults.get("injected"):
        metrics["faults_injected"] = faults["injected"]
        metrics["faults_undetected"] = len(faults.get("undetected") or [])
    entry = {
        "kind": "run",
        "run_id": summary.get("run_id") or os.path.basename(
            os.path.normpath(run_dir)),
        "run_dir": run_dir,
        "status": summary.get("status", "incomplete"),
        "metrics": metrics,
        "provenance": {k: summary[k] for k in _PROVENANCE_KEYS
                       if k in summary},
    }
    lineage = _lineage_from_stream(run_dir)
    if lineage:
        entry["lineage"] = lineage
    if extra:
        entry.update(extra)
    return entry


def register_run(run_dir: str, root: str | None = None,
                 summary: dict | None = None,
                 extra: dict | None = None) -> dict | None:
    """Summarize ``run_dir`` and append its entry under the runs root.

    Returns the appended record, or None when registration is disabled
    (empty root) or the run dir has no readable stream — a missing
    registry must never fail the run it was meant to record, so errors
    degrade to a warning.
    """
    root = resolve_runs_root(root)
    if not root:
        return None
    import warnings

    try:
        entry = run_entry(run_dir, summary=summary, extra=extra)
        return RunRegistry(root).append(entry)
    except (OSError, ValueError) as exc:
        warnings.warn(f"run registry: could not register {run_dir!r} "
                      f"under {root!r}: {exc}")
        return None


def bench_entry(record: dict, extra: dict | None = None) -> dict:
    """Registry entry from a ``bench.py`` JSON line (fresh or degraded)."""
    entry = {
        "kind": "bench",
        "metric": record.get("metric"),
        "value": record.get("value"),
        "unit": record.get("unit"),
    }
    for key in ("vs_baseline", "steps_per_s", "mfu", "achieved_tflops",
                "device_kind", "compile_cache", "degraded", "measured_at",
                "stale_seconds", "cache_measured_at"):
        if record.get(key) is not None:
            entry[key] = record[key]
    telemetry = record.get("telemetry") or {}
    if telemetry.get("run_id"):
        entry["run_id"] = telemetry["run_id"]
    if extra:
        entry.update(extra)
    return entry


def register_drill_record(record: dict, root: str | None = None,
                          extra: dict | None = None) -> dict | None:
    """Register a drill-matrix record (fault_drill / chaos_suite) as a
    bench entry, so ``telemetry runs trajectory`` carries the robustness
    history alongside the perf history. Only under an EXPLICIT root
    (``root`` argument or ``DIB_RUNS_ROOT``) — never the ``./runs``
    default, because ad-hoc local drill runs must not grow the committed
    index. Returns the appended entry, or None when no explicit root is
    configured."""
    root = root or os.environ.get("DIB_RUNS_ROOT")
    if not root:
        return None
    entry = bench_entry(record, extra={
        "total": record.get("total"),
        "all_passed": record.get("all_passed"),
        **(extra or {}),
    })
    return RunRegistry(root).append(entry)


# -------------------------------------------------------------- validation
def validate_index_entry(entry) -> list[str]:
    """Schema problems for one index line (``scripts/check_run_artifacts``
    runs this over the committed ``runs/index.jsonl``)."""
    problems: list[str] = []
    if not isinstance(entry, dict):
        return ["entry must be an object"]
    if entry.get("v") != INDEX_VERSION:
        problems.append(f"'v' must be {INDEX_VERSION}")
    t = entry.get("t")
    if not isinstance(t, (int, float)) or isinstance(t, bool) or t != t:
        problems.append("'t' must be a finite unix timestamp")
    kind = entry.get("kind")
    if kind == "run":
        if not (isinstance(entry.get("run_id"), str) and entry["run_id"]):
            problems.append("run entry: 'run_id' must be a non-empty string")
        if not (isinstance(entry.get("status"), str) and entry["status"]):
            problems.append("run entry: 'status' must be a non-empty string")
        if not isinstance(entry.get("metrics"), dict):
            problems.append("run entry: 'metrics' must be an object")
    elif kind == "bench":
        if not (isinstance(entry.get("metric"), str) and entry["metric"]):
            problems.append("bench entry: 'metric' must be a non-empty "
                            "string")
        value = entry.get("value")
        ok_value = (isinstance(value, (int, float))
                    and not isinstance(value, bool) and value == value)
        if not ok_value and not entry.get("degraded"):
            problems.append("bench entry: 'value' must be a finite number "
                            "(or the entry marked 'degraded')")
    else:
        problems.append(f"unknown entry kind {kind!r} "
                        "(expected 'run' or 'bench')")
    return problems


# --------------------------------------------------------------------- CLI
def _fmt(v, width: int | None = None) -> str:
    if v is None:
        s = "—"
    elif isinstance(v, float):
        s = f"{v:.4g}"
    else:
        s = str(v)
    return s if width is None else s[:width].ljust(width)


def runs_main(args) -> int:
    """``telemetry runs list|show|trajectory`` (parsed args from
    summary.telemetry_main)."""
    root = resolve_runs_root(args.runs_root)
    if not root:
        print("telemetry runs: no runs root (set --runs-root or "
              "DIB_RUNS_ROOT)", flush=True)
        return 2
    registry = RunRegistry(root)
    if args.runs_action == "list":
        latest = registry.latest()
        if not latest:
            print(f"no runs registered under {registry.path}")
            return 0
        print(f"{'run_id':32} {'status':11} {'device':14} "
              f"{'steps/s':>9} {'mfu':>7} {'alerts':>6} "
              f"{'lineage':22}  run_dir")
        for run_id, entry in sorted(
                latest.items(), key=lambda kv: kv[1].get("t", 0.0)):
            metrics = entry.get("metrics") or {}
            prov = entry.get("provenance") or {}
            lineage = entry.get("lineage") or {}
            # the trace_id, or the parent study when one is named — the
            # cross-plane join key `telemetry fleet` merges on
            trace = lineage.get("parent") or lineage.get("trace_id")
            print(f"{_fmt(run_id, 32)} {_fmt(entry.get('status'), 11)} "
                  f"{_fmt(prov.get('device_kind'), 14)} "
                  f"{_fmt(metrics.get('steps_per_s')):>9} "
                  f"{_fmt(metrics.get('mfu')):>7} "
                  f"{_fmt(metrics.get('alerts', 0)):>6} "
                  f"{_fmt(trace, 22)}  "
                  f"{entry.get('run_dir', '—')}")
        return 0
    if args.runs_action == "show":
        history = registry.history(args.run_id)
        if not history:
            print(f"telemetry runs show: no entry for {args.run_id!r} "
                  f"in {registry.path}", flush=True)
            return 2
        latest_entry = history[-1]
        lineage = latest_entry.get("lineage") or {}
        if lineage.get("trace_id"):
            # the human-readable origin chain rides stderr: stdout stays
            # pure JSON (the entry itself carries the lineage block) so
            # `runs show <id> | jq` keeps working on traced runs
            origin = " → ".join(lineage.get("origin") or ()) or "—"
            print(f"lineage: trace {lineage['trace_id']}  "
                  f"parent {lineage.get('parent') or '—'}  "
                  f"origin {origin}", file=sys.stderr)
        print(json.dumps(latest_entry if not args.full_history else history,
                         indent=1))
        return 0
    # trajectory
    bench = registry.bench_history()
    if not bench:
        print(f"no bench entries under {registry.path} — run bench.py "
              "(it registers every invocation) or seed from committed "
              "artifacts")
        return 0
    print(f"{'#':>3} {'measured_at':20} {'value':>9} {'unit':9} "
          f"{'steps/s':>9} {'mfu':>8} {'vs_baseline':>11} {'stale':>9}  "
          "device")
    for i, entry in enumerate(bench):
        # stale_seconds: how old the SERVED value was at emission time — a
        # cached value rides degraded records (BENCH_r05 served a 59,446 s
        # stale number); fresh measurements have none and print "—". The
        # committed SLO caps accepted staleness (SLO.json
        # bench_cache_staleness_ceiling).
        stale = entry.get("stale_seconds")
        stale_s = "—" if stale is None else f"{int(stale)}s"
        print(f"{i:>3} {_fmt(entry.get('measured_at'), 20)} "
              f"{_fmt(entry.get('value')):>9} "
              f"{_fmt(entry.get('unit'), 9)} "
              f"{_fmt(entry.get('steps_per_s')):>9} "
              f"{_fmt(entry.get('mfu')):>8} "
              f"{_fmt(entry.get('vs_baseline')):>11} "
              f"{stale_s:>9}  "
              f"{_fmt(entry.get('device_kind'))}"
              + ("  [degraded]" if entry.get("degraded") else ""))
    return 0
