"""Fit-hook adapters that feed the event stream and a ``PhaseTimer``.

:class:`ChunkPhaseHooks` replaces the private per-script timers the
instrumented drivers used to carry (``scripts/northstar_run.py``'s deleted
``_CheckpointPhaseTimer``): its ``pre`` hook runs FIRST in the fit hook
list, blocks on the chunk's outputs, and closes the "chunk" phase — so the
interval is the true train-chunk wall-clock; ``post`` runs LAST and closes
the "instrumentation" phase covering everything the other hooks did in
between. Per-interval series live on ``timer.intervals`` and, when an
:class:`~dib_tpu.telemetry.events.EventWriter` is attached, each chunk also
lands as a ``chunk`` event with steps/s and device memory.
"""

from __future__ import annotations

import contextlib
import time

from dib_tpu.telemetry.events import device_memory_stats
from dib_tpu.utils.profiling import PhaseTimer

__all__ = ["ChunkPhaseHooks", "FitRecorder"]


class _NullPhase:
    """Stand-in for a PhaseTimer phase when telemetry is off: never blocks,
    so dispatch keeps pipelining across chunks."""

    def block_on(self, tree) -> None:
        pass


class FitRecorder:
    """The per-chunk instrumentation shared by ``DIBTrainer.fit`` and
    ``BetaSweepTrainer.fit``: a ``PhaseTimer`` around each ``run_chunk``
    (blocking on its outputs so the interval is true wall-clock), one
    ``chunk`` event per boundary, step/epoch counters and the chunk-seconds
    histogram, and the end-of-fit ``metrics`` rollup. With ``telemetry``
    None every method is a cheap no-op and nothing blocks.

    ``steps_per_epoch`` is the run's TOTAL steps per epoch — a sweep passes
    ``base.steps_per_epoch * num_replicas`` (the bench.py steps/s
    convention of counting every replica's steps).
    """

    def __init__(self, telemetry, *, steps_per_epoch: int):
        self.telemetry = telemetry
        self.steps_per_epoch = int(steps_per_epoch)
        self.timer = self.registry = None
        if telemetry is not None:
            from dib_tpu.telemetry.metrics import MetricsRegistry

            self.timer = PhaseTimer()
            self.registry = MetricsRegistry()

    @contextlib.contextmanager
    def chunk_phase(self):
        """Wrap one ``run_chunk`` call; ``.block_on(outputs)`` inside."""
        if self.timer is None:
            yield _NullPhase()
        else:
            with self.timer.phase("chunk") as ph:
                yield ph

    def record_chunk(self, *, epoch: int, chunk_epochs: int,
                     **fields) -> None:
        """One ``chunk`` event from the just-timed chunk plus the metric
        updates. ``fields`` carry the already-fetched history row (scalars
        for a serial fit, [R] lists for a sweep)."""
        if self.telemetry is None:
            return
        seconds = self.timer.intervals["chunk"][-1]
        steps = chunk_epochs * self.steps_per_epoch
        self.telemetry.chunk(
            epoch=epoch, steps=steps, seconds=seconds,
            memory=device_memory_stats(), **fields,
        )
        self.registry.counter("steps").inc(steps)
        self.registry.histogram("chunk_s").record(seconds)
        self.registry.gauge("epoch").set(epoch)

    def finish(self) -> None:
        """End-of-fit rollup: chunk wall-clock distribution + totals as one
        ``metrics`` event (multihost: process 0 writes the gather)."""
        if self.telemetry is None:
            return
        from dib_tpu.telemetry.metrics import write_metrics

        write_metrics(self.registry, self.telemetry)


class ChunkPhaseHooks:
    """pre/post hook pair splitting checkpoint wall-clock into phases.

    Usage (the north-star pattern)::

        timer = PhaseTimer()
        phases = ChunkPhaseHooks(timer, telemetry=writer, steps_per_epoch=50)
        hooks = [phases.pre, *instrumentation_hooks, phases.post]
        phases.start()
        sweep.fit(keys, hooks=hooks, hook_every=chunk_epochs)
        timer.intervals["chunk"]            # per-checkpoint train seconds
        timer.intervals["instrumentation"]  # per-checkpoint hook seconds
    """

    def __init__(self, timer: PhaseTimer | None = None, telemetry=None,
                 steps_per_epoch: int = 0, baseline_known: bool = True):
        self.timer = timer or PhaseTimer()
        self.telemetry = telemetry
        self.steps_per_epoch = steps_per_epoch
        self._t = time.perf_counter()
        self._last_epoch = 0
        # ``baseline_known=False``: the run may resume from a checkpoint at
        # an epoch the caller cannot know before fitting, so the FIRST
        # interval's step count is unattributable — it is timed but not
        # emitted as a chunk event (an epoch-0 baseline would inflate the
        # gated steps/s by counting the pre-restore epochs as trained).
        self._baseline_known = baseline_known

    def start(self, epoch: int | None = None) -> None:
        """Re-anchor the clock at fit start so the first chunk interval
        excludes setup the caller doesn't want attributed to training.
        Passing ``epoch`` (e.g. the restore epoch of a resumed run) also
        anchors the step baseline and marks it known."""
        self._t = time.perf_counter()
        if epoch is not None:
            self._last_epoch = epoch
            self._baseline_known = True

    def pre(self, trainer, states, epoch: int) -> None:
        import jax

        jax.block_until_ready(
            states.params if hasattr(states, "params") else states
        )
        now = time.perf_counter()
        elapsed = now - self._t
        self._t = now
        self.timer.add("chunk", elapsed)
        if self.telemetry is not None and self._baseline_known:
            steps = max(epoch - self._last_epoch, 0) * self.steps_per_epoch
            self.telemetry.chunk(
                epoch=epoch, steps=steps, seconds=elapsed,
                memory=device_memory_stats(),
            )
        self._baseline_known = True  # subsequent deltas are real
        self._last_epoch = epoch

    def post(self, trainer, states, epoch: int) -> None:
        now = time.perf_counter()
        elapsed = now - self._t
        self._t = now
        self.timer.add("instrumentation", elapsed)
        if self.telemetry is not None:
            self.telemetry.hook(
                name="checkpoint_instrumentation", epoch=epoch,
                seconds=elapsed,
            )
