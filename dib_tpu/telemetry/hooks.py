"""Fit-hook adapters that feed the event stream, spans, and a ``PhaseTimer``.

:class:`ChunkPhaseHooks` replaces the private per-script timers the
instrumented drivers used to carry (``scripts/northstar_run.py``'s deleted
``_CheckpointPhaseTimer``): its ``pre`` hook runs FIRST in the fit hook
list, blocks on the chunk's outputs, and closes the "chunk" phase — so the
interval is the true train-chunk wall-clock; ``post`` runs LAST and closes
the "instrumentation" phase covering everything the other hooks did in
between. Per-interval series live on ``timer.intervals`` and, when an
:class:`~dib_tpu.telemetry.events.EventWriter` is attached, each chunk also
lands as a ``chunk`` event with steps/s and device+host memory, plus a
``span`` event in the run's trace hierarchy (``telemetry/trace.py``).

:class:`FitRecorder` additionally owns the per-fit XLA cost-analysis step
(``telemetry/xla_stats.py``): ``record_compile`` runs
``lower().compile().cost_analysis()`` on a jitted callable once, emits a
``compile`` event carrying FLOPs/bytes, counts the persistent-cache status
into hit/miss counters, and from then on every recorded chunk updates
achieved-FLOP/s / achieved-bandwidth gauges in the ``MetricsRegistry`` —
the live roofline position of the training program.

It also owns the run's **heartbeat** stream (docs/observability.md):
``recorder.heartbeats()`` wraps the fit loop, emitting a ``boundary``
beat at every recorded chunk (trailing inter-boundary intervals — the
same stall clock ``train/watchdog.py`` consumes) plus mid-chunk ``chunk``
beats from a daemon thread at a bounded wall-clock interval
(``DIB_HEARTBEAT_S``, default 10 s), so a live reader — ``telemetry
tail``, the watchdog — can tell "long chunk, process alive" from "hung
run" while the main thread is blocked on the device.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time

from dib_tpu.telemetry.events import device_memory_stats, host_memory_stats
from dib_tpu.telemetry.trace import Tracer
from dib_tpu.utils.profiling import PhaseTimer

__all__ = ["ChunkPhaseHooks", "FitRecorder", "heartbeat_interval_s"]

# How many trailing inter-boundary intervals a boundary beat carries (the
# watchdog's trailing-median stall clock; mirrors HeartbeatHook.keep).
_KEEP_INTERVALS = 32


def heartbeat_interval_s() -> float:
    """The configured mid-chunk heartbeat bound: ``DIB_HEARTBEAT_S``
    seconds (default 10.0; ``0`` disables the mid-chunk daemon thread —
    boundary beats still land with every chunk event)."""
    try:
        return float(os.environ.get("DIB_HEARTBEAT_S", "10"))
    except ValueError:
        return 10.0


class _NullPhase:
    """Stand-in for a span handle when telemetry is off: never blocks, so
    dispatch keeps pipelining across chunks."""

    def block_on(self, tree) -> None:
        pass

    def annotate(self, **fields) -> None:
        pass


class FitRecorder:
    """The per-chunk instrumentation shared by ``DIBTrainer.fit``,
    ``BetaSweepTrainer.fit`` and ``BooleanTrainer.fit``: a span around each
    ``run_chunk`` (blocking on its outputs so the interval is true
    wall-clock, named in captured XLA traces, and emitted as a ``span``
    event), one ``chunk`` event per boundary, step/epoch counters and the
    chunk-seconds histogram, utilization gauges when a compiled callable was
    cost-analyzed, and the end-of-fit ``metrics`` rollup. With ``telemetry``
    None every method is a cheap no-op and nothing blocks.

    ``steps_per_epoch`` is the run's TOTAL steps per epoch — a sweep passes
    ``base.steps_per_epoch * num_replicas`` (the bench.py steps/s
    convention of counting every replica's steps).
    """

    def __init__(self, telemetry, *, steps_per_epoch: int):
        self.telemetry = telemetry
        self.steps_per_epoch = int(steps_per_epoch)
        self.timer = self.registry = self.tracer = None
        self._costs: dict[str, dict] = {}
        self._peaks = None
        # heartbeat state (shared between the fit thread and the mid-chunk
        # daemon thread; the counter is guarded so beat numbers stay
        # strictly increasing across both emitters)
        self._hb_lock = threading.Lock()
        self._beats = 0
        self._boundary_intervals: list[float] = []
        # anchored at fit start so the FIRST inter-boundary interval is the
        # compile-laden one, matching HeartbeatHook's convention (the
        # watchdog's steady median starts at intervals_s[1])
        # timing-ok: inter-beat anchor, not a measured jitted interval
        self._last_boundary_t: float | None = time.perf_counter()
        self._last_epoch = 0
        self._chunk_t0: float | None = None
        if telemetry is not None:
            from dib_tpu.telemetry.metrics import MetricsRegistry

            self.timer = PhaseTimer()
            self.registry = MetricsRegistry()
            self.tracer = Tracer(telemetry, timer=self.timer)

    @contextlib.contextmanager
    def chunk_phase(self, **tags):
        """Wrap one ``run_chunk`` call; ``.block_on(outputs)`` inside."""
        if self.tracer is None:
            yield _NullPhase()
        else:
            # timing-ok: chunk-in-flight marker for the heartbeat thread,
            # not a measured interval (the span below owns the timing)
            self._chunk_t0 = time.perf_counter()
            try:
                with self.tracer.span("chunk", **tags) as handle:
                    yield handle
            finally:
                self._chunk_t0 = None

    def _emit_heartbeat(self, phase: str, **fields) -> None:
        if self.telemetry is None:
            return
        with self._hb_lock:
            self._beats += 1
            beat = self._beats
        self.telemetry.heartbeat(beat=beat, epoch=self._last_epoch,
                                 phase=phase, **fields)

    @contextlib.contextmanager
    def heartbeats(self, interval_s: float | None = None):
        """Run the fit loop under a bounded-interval heartbeat: a daemon
        thread emits a ``chunk``-phase beat every ``interval_s`` (default
        ``DIB_HEARTBEAT_S``) while the fit is in flight — including while
        the main thread is blocked inside ``run_chunk`` — so a live
        reader can distinguish a long chunk from a hung run. Boundary
        beats are emitted by :meth:`record_chunk` regardless. No-op when
        telemetry is off or the interval is 0."""
        interval = (heartbeat_interval_s() if interval_s is None
                    else float(interval_s))
        if self.telemetry is None or interval <= 0:
            yield
            return
        stop = threading.Event()

        def _beat_loop():
            while not stop.wait(interval):
                t0 = self._chunk_t0
                fields = {"interval_s": interval}
                if t0 is not None:
                    # timing-ok: elapsed-in-chunk is reporting, not a
                    # performance interval (the chunk span owns timing)
                    fields["phase_elapsed_s"] = round(
                        time.perf_counter() - t0, 3)
                self._emit_heartbeat(
                    "chunk" if t0 is not None else "host", **fields)

        thread = threading.Thread(target=_beat_loop, name="dib-heartbeat",
                                  daemon=True)
        thread.start()
        try:
            yield
        finally:
            stop.set()
            thread.join(timeout=max(1.0, interval))

    def span(self, name: str, **tags):
        """A named span under this fit's tracer (no-op handle when off) —
        for the measurement phases between chunks (MI bounds, evals)."""
        if self.tracer is None:
            return contextlib.nullcontext(_NullPhase())
        return self.tracer.span(name, **tags)

    # NOTE: overlapped-measurement span accounting lives in
    # dib_tpu/train/overlap.py (begin_overlapped / collect_overlapped) —
    # the dispatch captures the bound tracer (this recorder's, via the
    # fit loop's use_tracer), so collection emits on the run's stream
    # even when it happens after the loop.

    def record_compile(self, name: str, jitfn, *args,
                       epochs: int | None = None, **kwargs) -> dict | None:
        """Cost-analyze ``jitfn`` at this call signature, once per ``name``.

        Emits a ``compile`` event with FLOPs/bytes fields (duration-only on
        backends without a cost model), bumps the persistent-cache hit/miss
        counters, and — when ``epochs`` is given (the chunk program's
        static epoch count) — arms per-chunk utilization gauges scaled by
        each chunk's actual epoch count. Returns the cost dict or None.
        """
        if self.telemetry is None or name in self._costs:
            return None
        from dib_tpu.telemetry import xla_stats
        from dib_tpu.utils.compile_cache import current_status

        cache = current_status()
        self.registry.counter(
            "compile_cache.hits" if cache == "warm" else "compile_cache.misses"
        ).inc()
        cost = xla_stats.record_compile_event(
            self.telemetry, name, jitfn, args, kwargs, cache=cache,
            # the chunk program's static epoch count rides the event so a
            # live reader (telemetry tail) can scale the program FLOPs to
            # each chunk's actual epochs for its MFU gauge
            **({"epochs": epochs} if epochs else {}),
        )
        self._costs[name] = {
            "cost": cost,
            "per_epoch": (
                {k: v / epochs for k, v in cost.items()}
                if cost and epochs else None
            ),
        }
        if self._peaks is None:
            import jax

            self._peaks = xla_stats.backend_peaks(
                jax.devices()[0].device_kind
            ) or {}
        return cost

    def _utilization_gauges(self, name: str, chunk_epochs: int,
                            seconds: float) -> None:
        """Achieved FLOP/s / bandwidth of the chunk that just ran, from its
        cost-analyzed per-epoch FLOPs scaled to this chunk's epoch count."""
        from dib_tpu.telemetry import xla_stats

        entry = self._costs.get(name)
        if entry is None or entry["per_epoch"] is None:
            return
        per_epoch = entry["per_epoch"]
        rates = xla_stats.achieved(
            seconds,
            flops=per_epoch.get("flops", 0) * chunk_epochs,
            bytes_accessed=per_epoch.get("bytes_accessed", 0) * chunk_epochs,
            peaks=self._peaks,
        )
        for key, value in rates.items():
            self.registry.gauge(f"{key}.{name}").set(value)

    def record_chunk(self, *, epoch: int, chunk_epochs: int,
                     **fields) -> None:
        """One ``chunk`` event from the just-timed chunk plus the metric
        updates. ``fields`` carry the already-fetched history row (scalars
        for a serial fit, [R] lists for a sweep)."""
        if self.telemetry is None:
            return
        seconds = self.timer.intervals["chunk"][-1]
        steps = chunk_epochs * self.steps_per_epoch
        self.telemetry.chunk(
            epoch=epoch, steps=steps, seconds=seconds, epochs=chunk_epochs,
            memory=device_memory_stats(), host_memory=host_memory_stats(),
            **fields,
        )
        self.registry.counter("steps").inc(steps)
        self.registry.histogram("chunk_s").record(seconds)
        self.registry.gauge("epoch").set(epoch)
        self._utilization_gauges("run_chunk", chunk_epochs, seconds)
        # boundary heartbeat: device progress proven (the chunk event above
        # was emitted AFTER blocking on the chunk's outputs). Trailing
        # inter-boundary intervals are the watchdog's stall clock.
        self._last_epoch = int(epoch)
        now = time.perf_counter()   # timing-ok: inter-beat wall-clock,
        # measured across an already-blocked boundary (same contract as
        # train/watchdog.py HeartbeatHook)
        if self._last_boundary_t is not None:
            self._boundary_intervals.append(
                round(now - self._last_boundary_t, 2))
            del self._boundary_intervals[:-_KEEP_INTERVALS]
        self._last_boundary_t = now
        self._emit_heartbeat("boundary",
                             intervals_s=list(self._boundary_intervals))

    def finish(self) -> None:
        """End-of-fit rollup: chunk wall-clock distribution + totals as one
        ``metrics`` event (multihost: process 0 writes the gather)."""
        if self.telemetry is None:
            return
        from dib_tpu.telemetry.metrics import write_metrics

        write_metrics(self.registry, self.telemetry)


class ChunkPhaseHooks:
    """pre/post hook pair splitting checkpoint wall-clock into phases.

    Usage (the north-star pattern)::

        timer = PhaseTimer()
        phases = ChunkPhaseHooks(timer, telemetry=writer, steps_per_epoch=50)
        hooks = [phases.pre, *instrumentation_hooks, phases.post]
        phases.start()
        sweep.fit(keys, hooks=hooks, hook_every=chunk_epochs)
        timer.intervals["chunk"]            # per-checkpoint train seconds
        timer.intervals["instrumentation"]  # per-checkpoint hook seconds

    With a ``tracer`` (``telemetry/trace.py``) each interval additionally
    lands as a ``span`` event ("chunk"/"instrumentation"), so the driver's
    checkpoint cycle shows up in the run report's span breakdown — the
    tracer's timer should be this hooks' timer (pass one or the other).
    """

    def __init__(self, timer: PhaseTimer | None = None, telemetry=None,
                 steps_per_epoch: int = 0, baseline_known: bool = True,
                 tracer: Tracer | None = None):
        if tracer is not None and timer is None:
            timer = tracer.timer
        self.timer = timer or PhaseTimer()
        self.telemetry = telemetry
        self.tracer = tracer
        self.steps_per_epoch = steps_per_epoch
        self._t = time.perf_counter()
        self._open = None    # the in-flight instrumentation span token
        self._last_epoch = 0
        # ``baseline_known=False``: the run may resume from a checkpoint at
        # an epoch the caller cannot know before fitting, so the FIRST
        # interval's step count is unattributable — it is timed but not
        # emitted as a chunk event (an epoch-0 baseline would inflate the
        # gated steps/s by counting the pre-restore epochs as trained).
        self._baseline_known = baseline_known

    def _add(self, name: str, elapsed: float, **tags) -> None:
        if self.tracer is not None:
            self.tracer.add(name, elapsed, **tags)
        else:
            self.timer.add(name, elapsed)

    def start(self, epoch: int | None = None) -> None:
        """Re-anchor the clock at fit start so the first chunk interval
        excludes setup the caller doesn't want attributed to training.
        Passing ``epoch`` (e.g. the restore epoch of a resumed run) also
        anchors the step baseline and marks it known."""
        self._t = time.perf_counter()
        if epoch is not None:
            self._last_epoch = epoch
            self._baseline_known = True

    def pre(self, trainer, states, epoch: int) -> None:
        import jax

        jax.block_until_ready(
            states.params if hasattr(states, "params") else states
        )
        now = time.perf_counter()
        elapsed = now - self._t
        self._t = now
        self._add("chunk", elapsed, epoch=int(epoch))
        if self.telemetry is not None and self._baseline_known:
            steps = max(epoch - self._last_epoch, 0) * self.steps_per_epoch
            self.telemetry.chunk(
                epoch=epoch, steps=steps, seconds=elapsed,
                memory=device_memory_stats(),
                host_memory=host_memory_stats(),
            )
        self._baseline_known = True  # subsequent deltas are real
        self._last_epoch = epoch
        if self.tracer is not None:
            # open the instrumentation span NOW so the hooks that run
            # between pre and post (SpannedHook-wrapped measurement/pull
            # work) parent under it instead of double-counting as siblings
            self._open = self.tracer.begin("instrumentation",
                                           epoch=int(epoch))

    def post(self, trainer, states, epoch: int) -> None:
        now = time.perf_counter()
        elapsed = now - self._t
        self._t = now
        if self.tracer is not None and self._open is not None:
            self.tracer.end(self._open)
            self._open = None
        else:
            self.timer.add("instrumentation", elapsed)
        if self.telemetry is not None:
            self.telemetry.hook(
                name="checkpoint_instrumentation", epoch=epoch,
                seconds=elapsed,
            )
