"""XLA cost analysis, per-backend peaks, and utilization arithmetic.

The span layer (``telemetry/trace.py``) answers *where the wall-clock went*;
this module answers *what the device was asked to do in that time*:

  - :func:`compiled_cost_stats` runs ``.lower(...).compile().cost_analysis()``
    on a jitted callable at its real call signature and returns the compiled
    program's FLOPs / bytes-accessed (None on backends without a cost model
    — everything downstream degrades to duration-only).
  - :data:`BACKEND_PEAKS` is the one per-backend capability table (peak bf16
    matmul TFLOP/s and HBM GB/s from public specs) that ``bench.py``,
    ``scripts/profile_sweep.py`` and the report all read — previously each
    carried its own copy.
  - :func:`achieved` combines a program's FLOPs/bytes with a measured span
    duration into achieved-FLOP/s and achieved-bandwidth (and, when the
    backend is in the table, fractions of peak) — the roofline coordinates
    of one kernel.

Caveat, recorded here because it bit earlier rounds (VERDICT round 3 item
7): on some backends ``cost_analysis`` undercounts whole-program flops
dramatically. The numbers are recorded as ``compile`` event FIELDS tagged
with their source, never silently substituted for the analytic model-FLOPs
MFU that headlines ``bench.py``.

This module never imports jax at module level — the summary/report side
(``dib_tpu telemetry``) is host-only and must stay backend-free.
"""

from __future__ import annotations

import os
import time

__all__ = [
    "BACKEND_PEAKS",
    "achieved",
    "backend_peaks",
    "compiled_cost_stats",
    "cost_analysis_enabled",
    "executable_cost_stats",
    "record_compile_event",
]

# Public per-chip specs; ordered so the first substring match wins
# (v5p before v5 — "v5 lite"/v5e matches "v5"). bf16 matmul peak and HBM
# bandwidth; CPUs and unlisted kinds resolve to None (utilization gauges
# then report absolute achieved numbers with no peak fraction).
BACKEND_PEAKS: tuple[tuple[str, dict], ...] = (
    ("v6", {"bf16_tflops": 918.0, "hbm_gbps": 1640.0}),
    ("v5p", {"bf16_tflops": 459.0, "hbm_gbps": 2765.0}),
    ("v5", {"bf16_tflops": 197.0, "hbm_gbps": 819.0}),
    ("v4", {"bf16_tflops": 275.0, "hbm_gbps": 1228.0}),
    ("v3", {"bf16_tflops": 123.0, "hbm_gbps": 900.0}),
    ("v2", {"bf16_tflops": 45.0, "hbm_gbps": 700.0}),
)


def backend_peaks(device_kind: str | None) -> dict | None:
    """Peak capability row for a ``device_kind`` string, or None."""
    if not device_kind:
        return None
    kind = device_kind.lower()
    for key, peaks in BACKEND_PEAKS:
        if key in kind:
            return dict(peaks)
    return None


def cost_analysis_enabled() -> bool:
    """Cost analysis costs one extra ``lower().compile()`` per instrumented
    callable (cheap next to training, but real); ``DIB_XLA_COST_ANALYSIS=0``
    opts a run out."""
    return os.environ.get("DIB_XLA_COST_ANALYSIS", "1") != "0"


def compiled_cost_stats(jitfn, *args, **kwargs) -> dict | None:
    """``{"flops", "bytes_accessed", "transcendentals"(?)}`` of the program
    ``jitfn(*args, **kwargs)`` compiles to, or None.

    None covers every degraded case the same way: backends whose runtime
    exposes no ``cost_analysis`` (or returns nothing usable), lowering
    failures, and non-finite counts. Lowering only READS the arguments'
    shapes/dtypes — donated buffers are not consumed, so it is safe to call
    right before the first real invocation.
    """
    try:
        compiled = jitfn.lower(*args, **kwargs).compile()
    except Exception:
        return None
    return executable_cost_stats(compiled)


def executable_cost_stats(compiled) -> dict | None:
    """Cost stats of an ALREADY-compiled executable (the serve engine's AOT
    path, which must not pay a second ``lower().compile()`` just to read
    the numbers). Same degraded-to-None contract as
    :func:`compiled_cost_stats`."""
    try:
        analysis = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if not isinstance(analysis, dict):
        return None
    out = {}
    for key, field in (("flops", "flops"),
                       ("bytes accessed", "bytes_accessed"),
                       ("transcendentals", "transcendentals")):
        value = analysis.get(key)
        if isinstance(value, (int, float)) and value == value and value >= 0:
            out[field] = float(value)
    return out if out.get("flops") or out.get("bytes_accessed") else None


def achieved(seconds: float, flops: float | None = None,
             bytes_accessed: float | None = None,
             peaks: dict | None = None) -> dict:
    """Roofline coordinates of one execution: achieved GFLOP/s and GB/s,
    plus fractions of the backend peaks when known."""
    out: dict = {}
    if not seconds or seconds <= 0:
        return out
    if flops:
        out["achieved_gflops"] = flops / seconds / 1e9
        if peaks and peaks.get("bf16_tflops"):
            out["flops_frac_of_peak"] = (
                out["achieved_gflops"] / 1e3 / peaks["bf16_tflops"]
            )
    if bytes_accessed:
        out["achieved_gbps"] = bytes_accessed / seconds / 1e9
        if peaks and peaks.get("hbm_gbps"):
            out["bandwidth_frac_of_peak"] = (
                out["achieved_gbps"] / peaks["hbm_gbps"]
            )
    if flops and bytes_accessed:
        out["arithmetic_intensity"] = flops / bytes_accessed
    return out


def record_compile_event(telemetry, name: str, jitfn, args=(), kwargs=None,
                         cache: str | None = None, **fields) -> dict | None:
    """Cost-analyze ``jitfn`` at this signature and emit one ``compile``
    event carrying the numbers (plus how long the analysis itself took).

    Returns the cost dict (None on degraded backends — the event is still
    emitted, duration-only, so the stream records that analysis was
    attempted). ``cache`` defaults to the persistent-cache status of this
    process (``utils/compile_cache.py``).
    """
    if cache is None:
        from dib_tpu.utils.compile_cache import current_status

        cache = current_status()
    t0 = time.perf_counter()
    cost = (compiled_cost_stats(jitfn, *args, **(kwargs or {}))
            if cost_analysis_enabled() else None)
    seconds = time.perf_counter() - t0
    if telemetry is not None:
        telemetry.compile(name=name, seconds=seconds, cache=cache,
                          cost_source="xla_cost_analysis" if cost else None,
                          **(cost or {}), **fields)
    return cost
