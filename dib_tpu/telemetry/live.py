"""Live run monitoring: follow a growing events.jsonl, render a dashboard.

``python -m dib_tpu telemetry tail <run-dir>`` attaches to a run IN FLIGHT
and renders a refreshing terminal dashboard from the same event stream the
post-hoc tools (``summarize``/``report``) read after the fact:

  - throughput: recent steps/s (trailing window of ``chunk`` events) and
    the run's cumulative average;
  - quality: last loss / val_loss and the per-channel KL row — the
    info-plane position, live;
  - **live MFU gauge**: the chunk program's cost-analyzed FLOPs (from its
    ``compile`` event, scaled to each chunk's actual epoch count) divided
    by chunk wall-clock, against the per-backend peak table
    (``telemetry/xla_stats.py``) — the roofline position while the run
    still has time to be fixed;
  - span hotspots (self-time, same arithmetic as ``summarize``);
  - a mitigation / fault / alert / transition ticker (most recent last);
  - liveness: heartbeat staleness — "chunk in flight, beat 2 s ago"
    vs "SILENT for 40 s", the mid-chunk distinction the boundary-only
    telemetry could not make.

The follower (:class:`StreamFollower`) is incremental and torn-line
tolerant: a final line still being appended is buffered until its
newline arrives (never mis-parsed), and a torn line mid-file (killed
writer) is skipped and counted — the same durability contract
``events.read_events`` honors, applied to a file that is still growing.

Everything here is host-side file analysis: this module never imports jax.
"""

from __future__ import annotations

import json
import os
import sys
import time
from collections import deque

from dib_tpu.telemetry.events import resolve_events_path

__all__ = ["LiveRunState", "StreamFollower", "liveness", "render_dashboard",
           "tail"]


class StreamFollower:
    """Incremental reader over a (possibly still growing) events.jsonl.

    ``poll()`` returns the complete, parseable events appended since the
    last call. The bytes after the final newline are an in-progress append
    and stay buffered — a torn FINAL line is never mis-read, it is simply
    not ready yet. A complete line that does not parse (a writer killed
    mid-append earlier in the file) is skipped and counted in ``torn``.

    A file that does not exist yet polls as empty (attach before the run
    starts); a file that SHRANK (rotated/truncated) resets the follower to
    the top rather than reading garbage from a stale offset.
    """

    def __init__(self, path: str):
        # resolved lazily each poll: attaching BEFORE the run dir exists
        # must re-resolve once the run creates it as a directory
        self._given = path
        self._offset = 0
        self._buf = b""
        self.torn = 0
        self.events_read = 0

    @property
    def path(self) -> str:
        return resolve_events_path(self._given)

    def poll(self) -> list[dict]:
        try:
            size = os.stat(self.path).st_size
        except OSError:
            return []
        if size < self._offset:   # truncated/rotated under us: start over
            self._offset = 0
            self._buf = b""
        if size == self._offset and not self._buf:
            return []
        with open(self.path, "rb") as f:
            f.seek(self._offset)
            data = f.read()
        self._offset += len(data)
        data = self._buf + data
        lines = data.split(b"\n")
        self._buf = lines.pop()   # bytes after the last newline: in flight
        out = []
        for line in lines:
            if not line.strip():
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                self.torn += 1
        self.events_read += len(out)
        return out


def liveness(state: "LiveRunState", now: float | None = None) -> dict:
    """The shared staleness verdict (dashboard, watchdog, drills agree):

    - ``silent_s``: wall-clock since the last heartbeat (any phase) — the
      process-liveness clock; None before the first beat.
    - ``progress_s``: since the last chunk boundary (``chunk`` event or
      boundary beat) — the device-progress clock.
    - ``silent``: no beat within 3x the configured heartbeat interval —
      the emitting process is presumed hung or dead (a merely LONG chunk
      keeps beating mid-chunk).
    - ``in_chunk``: the last beat reported a chunk in flight.
    """
    now = time.time() if now is None else now   # timing-ok: staleness vs
    # event wall-clock stamps, no jitted work in this module
    out = {
        "silent_s": (round(now - state.last_beat_t, 1)
                     if state.last_beat_t else None),
        "progress_s": (round(now - state.last_progress_t, 1)
                       if state.last_progress_t else None),
        "in_chunk": state.in_chunk,
        "silent": False,
    }
    if state.heartbeat_interval_s and state.last_beat_t:
        out["silent"] = (now - state.last_beat_t
                         > 3.0 * state.heartbeat_interval_s)
    return out


class LiveRunState:
    """Incremental rollup of a run's event stream for the dashboard.

    Feed events in file order via :meth:`update`; read the rendered view
    off the attributes (or :func:`render_dashboard`). Keeps bounded
    windows only — following a week-long run must not grow without bound.
    """

    def __init__(self, window: int = 64, ticker: int = 8):
        self.run_id = None
        self.manifest: dict = {}
        self.status = "waiting"       # no run_start seen yet
        self.launches = 0
        self.chunks = deque(maxlen=window)     # recent chunk events
        self.total_steps = 0
        self.total_chunk_s = 0.0
        self.num_chunks = 0
        # steady-state totals mirror summarize: each launch's FIRST chunk
        # (compile-laden) is excluded, so a live SLO floor on
        # steady_steps_per_s sees the same metric the budget was written
        # against instead of false-firing on the compile chunk
        self.steady_steps = 0
        self.steady_s = 0.0
        self._awaiting_first_chunk = True
        self.compiles: dict[str, dict] = {}    # name -> compile event
        self.span_totals: dict[str, list] = {}  # path -> [total_s, count]
        self.ticker = deque(maxlen=ticker)     # mitigation/fault/alert rows
        self.counts = {"mitigation": 0, "fault": 0, "alert": 0,
                       "transition": 0}
        self.last_beat_t = None
        self.last_progress_t = None
        self.in_chunk = False
        self.heartbeat_interval_s = None
        self.last_mi: dict | None = None
        self._lead_proc = None
        # β-grid scheduler queue view (dib_tpu/sched): unit -> status,
        # folded from job/lease events; bounded by the job's unit count
        self.sched_submitted = 0
        self.sched_units: dict[str, str] = {}
        self.sched_workers: set = set()
        self.sched_stolen = 0
        self.sched_rejected = 0
        # multi-tenant fleet view (docs/scheduling.md): per-tenant unit
        # outcomes + queue waits folded from tenant-tagged job/lease
        # events; the shed floor tracks load_shed mitigations
        self.sched_tenants: dict[str, dict] = {}
        self.sched_shed_floor = None

    # ------------------------------------------------------------- update
    def update(self, event: dict) -> None:
        etype = event.get("type")
        proc = event.get("proc", 0)
        if self.run_id is None and event.get("run"):
            self.run_id = event["run"]
        # multihost streams: mirror summarize's convention — per-run
        # rollups come from the lowest process index seen emitting chunks
        if etype == "chunk":
            if self._lead_proc is None or proc < self._lead_proc:
                self._lead_proc = proc
            if proc != self._lead_proc:
                return
        if etype == "run_start":
            self.launches += 1
            self.run_id = event.get("run")
            self.manifest = event.get("manifest") or {}
            self.status = "running"
            self._awaiting_first_chunk = True
        elif etype == "chunk":
            self.chunks.append(event)
            self.total_steps += event.get("steps") or 0
            self.total_chunk_s += event.get("seconds") or 0.0
            self.num_chunks += 1
            self.last_progress_t = event.get("t")
            if self._awaiting_first_chunk:
                self._awaiting_first_chunk = False
            else:
                self.steady_steps += event.get("steps") or 0
                self.steady_s += event.get("seconds") or 0.0
        elif etype == "compile":
            self.compiles[event.get("name", "?")] = event
        elif etype == "span":
            path = event.get("path") or event.get("name") or "?"
            entry = self.span_totals.setdefault(path, [0.0, 0])
            entry[0] += event.get("seconds") or 0.0
            entry[1] += 1
        elif etype == "heartbeat":
            self.last_beat_t = event.get("t")
            self.in_chunk = event.get("phase") == "chunk"
            if event.get("intervals_s") is not None:
                self.last_progress_t = event.get("t")
                self.in_chunk = False
            if event.get("interval_s"):
                self.heartbeat_interval_s = event["interval_s"]
        elif etype == "mi_bounds":
            self.last_mi = event
        elif etype in ("mitigation", "fault", "alert", "transition"):
            self.counts[etype] += 1
            self.ticker.append(self._ticker_row(etype, event))
            if etype == "mitigation":
                mtype = event.get("mtype")
                if mtype == "load_shed":
                    self.sched_shed_floor = event.get("floor")
                elif mtype == "load_shed_cleared":
                    self.sched_shed_floor = None
        elif etype == "job":
            action = event.get("action")
            tenant = event.get("tenant")
            if action == "submitted":
                self.sched_submitted += event.get("units") or 0
                if tenant:
                    self._tenant_row(tenant)["units"] += \
                        event.get("units") or 0
            elif action == "unit_done":
                self.sched_units[event.get("unit", "?")] = "done"
                if tenant:
                    self._tenant_row(tenant)["done"] += 1
            elif action == "unit_failed":
                # requeued: pending again (a later grant re-leases it)
                self.sched_units.pop(event.get("unit", "?"), None)
            elif action == "failed" and event.get("unit"):
                self.sched_units[event["unit"]] = "failed"
            elif action == "rejected" and tenant:
                self._tenant_row(tenant)["rejected"] += 1
        elif etype == "lease":
            action = event.get("action")
            unit = event.get("unit", "?")
            if action == "granted":
                self.sched_units[unit] = "leased"
                if event.get("worker"):
                    self.sched_workers.add(event["worker"])
                if (event.get("tenant")
                        and isinstance(event.get("queue_wait_s"),
                                       (int, float))):
                    waits = self._tenant_row(event["tenant"])["waits"]
                    waits.append(float(event["queue_wait_s"]))
                    del waits[:-256]   # bounded: a tail is a dashboard
            elif action in ("released", "expired"):
                if self.sched_units.get(unit) == "leased":
                    self.sched_units.pop(unit, None)
                if action == "expired":
                    self.sched_stolen += 1
            elif action == "rejected":
                self.sched_rejected += 1
        elif etype == "run_end":
            self.status = event.get("status", "?")

    def _tenant_row(self, name: str) -> dict:
        return self.sched_tenants.setdefault(
            name, {"units": 0, "done": 0, "rejected": 0, "waits": []})

    @staticmethod
    def _ticker_row(etype: str, event: dict) -> str:
        stamp = time.strftime("%H:%M:%S", time.localtime(event.get("t", 0)))
        if etype == "mitigation":
            what = event.get("mtype", "?")
        elif etype == "fault":
            what = f"fault {event.get('kind', '?')}"
        elif etype == "alert":
            what = (f"ALERT {event.get('rule', '?')}: "
                    f"{event.get('value')} vs {event.get('budget')}")
        else:
            what = (f"transition ch{event.get('channel', '?')} "
                    f"{event.get('direction', '?')} @ "
                    f"epoch {event.get('epoch', '?')}")
        extra = ""
        if etype in ("mitigation", "fault") and event.get("epoch") is not None:
            extra = f" @ epoch {event['epoch']}"
        return f"{stamp}  {what}{extra}"

    # ------------------------------------------------------------ derived
    @property
    def recent_steps_per_s(self) -> float | None:
        steps = sum(c.get("steps") or 0 for c in self.chunks)
        secs = sum(c.get("seconds") or 0.0 for c in self.chunks)
        return steps / secs if secs > 0 else None

    @property
    def steps_per_s(self) -> float | None:
        return (self.total_steps / self.total_chunk_s
                if self.total_chunk_s > 0 else None)

    @property
    def steady_steps_per_s(self) -> float | None:
        """summarize's steady-state metric, live: None until a launch has
        produced a chunk BEYOND its compile-laden first one."""
        return (self.steady_steps / self.steady_s
                if self.steady_s > 0 else None)

    def last_chunk(self) -> dict | None:
        return self.chunks[-1] if self.chunks else None

    def mfu(self) -> dict | None:
        """Live roofline gauge from the chunk program's cost-analyzed
        FLOPs (``compile`` event, per-epoch scaled) over the last chunk's
        wall-clock, vs the backend peak table. None until both a
        cost-analyzed compile event and a chunk have landed."""
        from dib_tpu.telemetry.xla_stats import achieved, backend_peaks

        chunk = self.last_chunk()
        compile_event = self.compiles.get("run_chunk") \
            or self.compiles.get("sweep_chunk")
        if chunk is None or compile_event is None:
            return None
        flops = compile_event.get("flops")
        nbytes = compile_event.get("bytes_accessed")
        seconds = chunk.get("seconds")
        if not seconds or not (flops or nbytes):
            return None
        compiled_epochs = compile_event.get("epochs")
        chunk_epochs = chunk.get("epochs")
        scale = 1.0
        if compiled_epochs and chunk_epochs:
            scale = chunk_epochs / compiled_epochs
        peaks = backend_peaks(self.manifest.get("device_kind"))
        out = achieved(seconds,
                       flops=flops * scale if flops else None,
                       bytes_accessed=nbytes * scale if nbytes else None,
                       peaks=peaks)
        if peaks:
            out["peaks"] = peaks
        return out or None

    def hotspots(self, n: int = 3) -> list[dict]:
        from dib_tpu.telemetry.summary import (
            _normalize_span_path,
            span_hotspots,
        )

        rollup: dict[str, dict] = {}
        for path, (total, count) in self.span_totals.items():
            norm = _normalize_span_path(path)
            entry = rollup.setdefault(norm, {"total_s": 0.0, "count": 0})
            entry["total_s"] += total
            entry["count"] += count
        return span_hotspots(rollup, n)


# ------------------------------------------------------------- rendering
_BAR_WIDTH = 24


def _bar(frac: float | None, width: int = _BAR_WIDTH) -> str:
    if frac is None:
        return "·" * width
    frac = max(0.0, min(1.0, frac))
    filled = round(frac * width)
    return "█" * filled + "·" * (width - filled)


def _fmt(v, fmt="{:.3g}") -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        return fmt.format(v)
    return str(v)


def render_dashboard(state: LiveRunState, now: float | None = None,
                     width: int = 78) -> str:
    """One dashboard frame as plain text (no ANSI — the tail loop owns
    screen control), so tests and logs can consume frames verbatim."""
    man = state.manifest
    live = liveness(state, now)
    lines = []
    device = (f"{man.get('device_kind', '?')} ×{man.get('device_count', '?')}"
              if man else "?")
    head = (f"run {state.run_id or '?'}  ·  {state.status}  ·  {device}"
            + (f"  ·  launch {state.launches}" if state.launches > 1 else ""))
    lines.append(head[:width])
    lines.append("─" * min(width, len(head) + 8))

    chunk = state.last_chunk()
    epoch = chunk.get("epoch") if chunk else None
    lines.append(
        f"steps/s   recent {_fmt(state.recent_steps_per_s, '{:.1f}')}"
        f"   run {_fmt(state.steps_per_s, '{:.1f}')}"
        f"   steps {state.total_steps}"
        + (f"   epoch {epoch}" if epoch is not None else ""))

    if chunk is not None:
        loss = chunk.get("loss")
        val = chunk.get("val_loss")
        if isinstance(loss, list):
            loss = sum(loss) / len(loss) if loss else None
        if isinstance(val, list):
            val = sum(val) / len(val) if val else None
        lines.append(f"loss      {_fmt(loss, '{:.5g}')}"
                     f"   val_loss {_fmt(val, '{:.5g}')}")
        kl = chunk.get("kl_per_feature")
        if isinstance(kl, list) and kl:
            vals = [v for v in kl if isinstance(v, (int, float))]
            if vals:
                peak = max(max(vals), 1e-12)
                cells = "".join(
                    " ▁▂▃▄▅▆▇█"[min(int(v / peak * 8), 8)] if v > 0 else " "
                    for v in vals[:48])
                lines.append(f"KL/chan   [{cells}]  Σ {sum(vals):.4g} nats"
                             f"  ({len(vals)} channels)")
        elif isinstance(chunk.get("kl_total"), list):
            tot = [v for v in chunk["kl_total"]
                   if isinstance(v, (int, float))]
            if tot:
                lines.append(f"KL total  [{', '.join(f'{v:.3g}' for v in tot[:8])}"
                             + ("…]" if len(tot) > 8 else "]")
                             + f"  ({len(tot)} replicas)")

    mfu = state.mfu()
    if mfu:
        frac = mfu.get("flops_frac_of_peak")
        gflops = mfu.get("achieved_gflops")
        peak = (mfu.get("peaks") or {}).get("bf16_tflops")
        lines.append(
            f"MFU       {_bar(frac)} "
            + (f"{frac * 100:.2f}% of {peak:g} TF/s peak"
               if frac is not None and peak else
               f"{_fmt(gflops, '{:.1f}')} GFLOP/s (no peak table row)"))
        bw = mfu.get("bandwidth_frac_of_peak")
        if bw is not None:
            lines.append(f"HBM       {_bar(bw)} {bw * 100:.2f}% of "
                         f"{mfu['peaks']['hbm_gbps']:g} GB/s peak")

    hot = state.hotspots()
    if hot:
        tops = "  ".join(f"{h['path']} {h['self_s']:.2f}s" for h in hot)
        lines.append(f"hotspots  {tops}"[:width])

    if state.sched_submitted or state.sched_units:
        # `submitted` counts come from the job's `submitted` event; a job
        # submitted by a separate `sched submit` process (journal-only)
        # has none, so pending is derivable only once units are seen —
        # the leased/done/failed counts stay exact either way
        statuses = list(state.sched_units.values())
        done = statuses.count("done")
        leased = statuses.count("leased")
        failed = statuses.count("failed")
        pending = max(state.sched_submitted - done - leased - failed, 0)
        queue = (f"queue     {pending} pending / {leased} leased / "
                 f"{done} done / {failed} failed"
                 f" · {len(state.sched_workers)} workers")
        if state.sched_stolen:
            queue += f" · {state.sched_stolen} stolen"
        if state.sched_rejected:
            queue += f" · {state.sched_rejected} stale-rejected"
        if state.sched_shed_floor is not None:
            queue += f" · SHED floor={state.sched_shed_floor}"
        lines.append(queue[:width])
        # per-tenant fair-share rows (only when the fleet is actually
        # multi-tenant or admission control rejected something)
        if (len(state.sched_tenants) > 1
                or any(t["rejected"]
                       for t in state.sched_tenants.values())):
            for name in sorted(state.sched_tenants):
                row = state.sched_tenants[name]
                waits = sorted(row["waits"])
                line = (f"  tenant  {name:<12} {row['units']} units / "
                        f"{row['done']} done")
                if waits:
                    p50 = waits[int(0.5 * (len(waits) - 1))]
                    p99 = waits[int(0.99 * (len(waits) - 1))]
                    line += f" · wait p50 {p50:.2f}s p99 {p99:.2f}s"
                if row["rejected"]:
                    line += f" · {row['rejected']} admission-rejected"
                lines.append(line[:width])

    beat = ("no heartbeat yet" if live["silent_s"] is None else
            f"beat {live['silent_s']}s ago"
            + (", chunk in flight" if live["in_chunk"] else ""))
    if live["silent"]:
        beat = f"SILENT for {live['silent_s']}s — run presumed hung"
    prog = (f"   boundary {live['progress_s']}s ago"
            if live["progress_s"] is not None else "")
    lines.append(f"liveness  {beat}{prog}")

    if state.counts["alert"] or state.counts["transition"] \
            or state.counts["mitigation"] or state.counts["fault"]:
        lines.append(
            f"events    {state.counts['mitigation']} mitigations, "
            f"{state.counts['fault']} faults, "
            f"{state.counts['alert']} alerts, "
            f"{state.counts['transition']} transitions")
    for row in state.ticker:
        lines.append(f"  {row}"[:width])
    return "\n".join(lines)


def tail(path: str, *, slo=None, refresh_s: float = 1.0,
         duration_s: float | None = None, follow_after_end: bool = False,
         out=None, ansi: bool | None = None,
         max_frames: int | None = None) -> LiveRunState:
    """Follow ``path`` (run dir or events.jsonl), rendering a refreshing
    dashboard until the run ends (or ``duration_s`` elapses).

    ``slo`` is an optional :class:`dib_tpu.telemetry.slo.SLOEngine`; when
    given, every poll feeds it the new events and violations/transitions
    are written DURABLY onto the run's own stream (and show in the
    ticker on the next poll). Returns the final :class:`LiveRunState`.
    """
    out = sys.stdout if out is None else out
    if ansi is None:
        ansi = hasattr(out, "isatty") and out.isatty()
    follower = StreamFollower(path)
    state = LiveRunState()
    deadline = (time.time() + duration_s) if duration_s else None
    # timing-ok: host-side poll pacing; no jitted work in this module
    frames = 0
    while True:
        for event in follower.poll():
            state.update(event)
            if slo is not None:
                slo.observe(event)
        if slo is not None:
            slo.flush()
        frame = render_dashboard(state)
        if ansi:
            out.write("\x1b[2J\x1b[H" + frame + "\n")
        else:
            out.write(frame + "\n\n")
        out.flush()
        frames += 1
        ended = state.status not in ("waiting", "running")
        if ended and not follow_after_end:
            break
        if deadline is not None and time.time() >= deadline:
            break   # timing-ok: poll pacing
        if max_frames is not None and frames >= max_frames:
            break
        time.sleep(refresh_s)   # timing-ok: poll pacing
    return state
