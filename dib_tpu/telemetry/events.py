"""Structured run-event stream: append-only JSONL, one file per run dir.

The paper's central claim is that the training trajectory IS the scientific
product ("the fruits of training are signals that map out the information in
the data", reference README.md:6) — yet before this module every run
recorded itself through ad-hoc schema-less JSON at the repo root. Here every
run appends typed, schema-versioned events to ``<run_dir>/events.jsonl``:

  - ``run_start``  provenance manifest (git SHA, jax/flax/optax versions,
                   device kind + count, mesh shape, resolved config hash)
  - ``chunk``      per-fit-chunk training signal: epoch, steps, wall-clock
                   and steps/s (``PhaseTimer``-measured), loss, beta,
                   per-feature KL from the fetched history row, device
                   memory stats
  - ``compile``    executable name, compile seconds, persistent-cache
                   status from ``utils/compile_cache.py``
  - ``mitigation`` a self-healing action: watchdog kill/restart (mirroring
                   ``watchdog.mitigations``), divergence rollback,
                   checkpoint fallback, serve-replica ejection/re-admission
  - ``fault``      one DELIBERATE fault injection (``dib_tpu/faults``):
                   kind, plan spec, where it fired — drills are auditable
                   because every injection is on the same stream as the
                   mitigation it provoked
  - ``hook``       host-hook wall-clock per invocation
  - ``span``       one closed trace span (``telemetry/trace.py``): name,
                   full slash path, span/parent ids, blocked wall-clock
  - ``mi_bounds``  MI sandwich-bound measurements (sweep/boolean hooks)
  - ``heartbeat``  bounded-interval liveness beat (``telemetry/hooks.py``
                   FitRecorder): ``boundary`` beats at chunk boundaries
                   carry trailing inter-boundary intervals (the watchdog's
                   stall clock); ``chunk`` beats land mid-chunk from a
                   daemon thread so a live reader can tell "long chunk"
                   from "hung run" while the main thread is blocked on
                   the device
  - ``alert``      one SLO rule violation (``telemetry/slo.py``): rule
                   name, observed value vs budget — durable, so a violated
                   budget outlives the tail session that spotted it
  - ``transition`` an info-plane transition: a channel's KL crossing the
                   configured threshold between chunk boundaries
                   (``telemetry/slo.py``)
  - ``metrics``    counter/gauge/histogram snapshots (``telemetry.metrics``)
  - ``run_end``    terminal status + total wall-clock

Envelope (every line): ``v`` schema version, ``run`` run id, ``proc``
``jax.process_index()``, ``seq`` per-writer sequence number, ``t`` unix
time, ``mono`` monotonic clock, ``type``, then the record's fields.

Durability contract: each event is ONE ``os.write`` of one ``\\n``-terminated
line on an ``O_APPEND`` fd — concurrent writers (worker + watchdog
supervisor) never interleave bytes, and a killed writer can leave at most
one torn line per kill (possibly mid-file, since survivors keep appending
after it), which :func:`read_events` skips with a warning. Instrumentation
stays off the hot path: emission happens only at chunk boundaries on
already-fetched arrays (see ``train/loop.py``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import subprocess
import sys
import threading
import time
import uuid
import weakref

SCHEMA_VERSION = 1
EVENTS_FILENAME = "events.jsonl"


@dataclasses.dataclass(frozen=True)
class EventKindSpec:
    """One row of the declarative event-schema registry: the field
    vocabulary of one event kind. ``required`` fields are what every
    record of the kind must carry (the typed ``EventWriter`` helpers bind
    them by signature); ``optional`` is the full documented detail-field
    vocabulary. The static-analysis drift pass
    (``dib_tpu/analysis/passes/event_schema.py``) checks every emit call
    site in the tree against these rows, and checks the rows against the
    docs/observability.md record-type table — so code, schema, and docs
    cannot diverge silently. A NEW field starts here: add it to the row,
    document it, then emit it."""

    required: tuple[str, ...]
    optional: tuple[str, ...] = ()
    doc: str = ""


#: Envelope fields every record carries (written by :meth:`EventWriter.emit`
#: itself, never passed by callers). ``ctx`` is the cross-plane trace
#: context (``telemetry/context.py``): ``trace_id`` / ``parent`` /
#: ``origin``, stamped on every record of a traced writer so the fleet
#: aggregator can join planes causally (docs/observability.md "Fleet
#: causality").
ENVELOPE_FIELDS = ("v", "run", "proc", "seq", "t", "mono", "type", "tags",
                   "ctx")

#: Closed vocabulary for the request span's ``phases`` field, in wire
#: order. Every key the HTTP server stamps (serve/server.py) must come
#: from this tuple — the event-schema lint pass checks it against the
#: docs/observability.md phase table, and per-phase metric names are
#: derived as ``serve.phase.<name>``. A request carries only the phases
#: it actually traversed (cached hits skip queue/batch; quota/shed
#: rejections skip queue/batch/dispatch).
REQUEST_PHASES = ("read", "parse", "admission", "queue", "batch",
                  "dispatch", "serialize", "write")

#: kind -> field vocabulary; one row per documented record type
#: (docs/observability.md "Record types and their payloads").
EVENT_SCHEMA: dict[str, EventKindSpec] = {
    "run_start": EventKindSpec(
        required=("manifest",),
        doc="provenance manifest at (re)launch"),
    "chunk": EventKindSpec(
        required=("epoch", "steps", "seconds"),
        optional=("steps_per_s", "epochs", "loss", "val_loss", "beta",
                  "kl_per_feature", "metric", "val_metric", "memory",
                  "host_memory", "beta_ends", "replica"),
        doc="per-fit-chunk training signal (sweeps carry [R] lists)"),
    "compile": EventKindSpec(
        required=("name", "seconds", "cache"),
        optional=("flops", "bytes_accessed", "optimal_seconds", "epochs",
                  "op", "bucket", "error", "cost_source"),
        doc="executable compile: seconds, cache status, cost analysis"),
    "mitigation": EventKindSpec(
        required=("mtype",),
        optional=("epoch", "chunk", "step", "action", "reason", "error",
                  "restored_epoch", "loss", "val_loss", "kl_per_feature",
                  "replica", "beta_end", "scope", "members", "deleted",
                  "detail", "host", "hosts", "expected", "observed",
                  "launches", "uptime_s", "worker_alive_s", "surface",
                  "skipped_steps", "timeout_s", "grace_s", "exit_code",
                  "signal", "run_id", "replicas", "consecutive_failures",
                  "healthy", "ejected", "batchers_dead",
                  "checkpoint_saved", "grace_remaining_s", "model",
                  "saved_width", "restored_width", "saved_mesh_axes",
                  "mesh_axes", "quarantined", "floor", "parked",
                  "launch", "at_s", "returncode"),
        doc="one self-healing action (watchdog, rollback, serve health; "
            "sweep_reshard / member_backfill carry the mesh-portability "
            "fields: saved/restored sweep widths and mesh axis sizes; "
            "checkpoint_fallback carries `quarantined` — the quarantine "
            "path of the corrupt step, or false when it was kept)"),
    "anomaly": EventKindSpec(
        required=("epoch", "channel", "kind"),
        optional=("value", "zscore", "threshold", "phase", "replica",
                  "beta_end", "action"),
        doc="one boundary anomaly verdict from the β-aware detector "
            "(train/anomaly.py): a non-finite or robust-z-spiking "
            "boundary metric (kind nonfinite/spike) on `channel` "
            "(loss / val_loss / kl/<i> / param_norm), conditioned on "
            "the β-annealing `phase` — emitted BEFORE the rollback/"
            "ejection mitigation it provokes"),
    "quarantine": EventKindSpec(
        required=("step", "reason"),
        optional=("path", "source", "error", "scope", "epoch",
                  "directory", "replica"),
        doc="one checkpoint step moved into its directory's quarantine/ "
            "subdir (train/checkpoint.py): corrupt at restore, flagged "
            "by `ckpt scrub`, or written during an anomalous window — "
            "the step's bytes stay inspectable but no restore or "
            "rollback path can ever select it again"),
    "fault": EventKindSpec(
        required=("kind",),
        optional=("spec", "chunk", "epoch", "replica", "op", "host",
                  "stale_chunk", "detail", "step", "via"),
        doc="one deliberate injection (dib_tpu/faults), pre-execution"),
    "hook": EventKindSpec(
        required=("name", "epoch", "seconds"),
        doc="host-hook wall-clock per invocation"),
    "span": EventKindSpec(
        required=("name", "path", "span", "parent", "seconds"),
        optional=("epoch", "replica", "beta_end", "op", "bucket",
                  "status", "rows", "fill", "queued_s", "padded_rows",
                  "overlapped", "tenant", "cached", "model", "phases"),
        doc="one closed trace span (serving emits request/batch spans; "
            "request spans may carry the tenant label, cached=true for "
            "response-cache hits, the zoo model name, and `phases` — "
            "the per-phase latency anatomy {name: seconds} keyed by "
            "REQUEST_PHASES, whose values sum to `seconds` exactly; "
            "overlapped=true marks a measurement that rode the async "
            "queue — seconds is then the EXPOSED wait, queued_s the "
            "dispatch→ready window)"),
    "mi_bounds": EventKindSpec(
        required=("epoch",),
        optional=("lower_bits", "upper_bits", "beta", "replica",
                  "beta_end", "per_feature", "feature"),
        doc="MI sandwich-bound measurements"),
    "heartbeat": EventKindSpec(
        required=("beat", "epoch", "phase"),
        optional=("intervals_s", "interval_s", "phase_elapsed_s"),
        doc="bounded-interval liveness beat (boundary / chunk / host)"),
    "alert": EventKindSpec(
        required=("rule",),
        optional=("metric", "value", "bound", "budget", "severity",
                  "source", "when", "burn_fast", "burn_slow", "windows_s",
                  "threshold", "reason"),
        doc="one durable SLO violation (telemetry/slo.py); burn-rate "
            "alerts carry the fast/slow window evidence "
            "(telemetry/fleet.py)"),
    "transition": EventKindSpec(
        required=("channel", "epoch", "direction"),
        optional=("kl_before", "kl_after", "beta", "threshold_nats",
                  "replica"),
        doc="info-plane transition: per-channel KL threshold crossing"),
    "job": EventKindSpec(
        required=("job_id", "action"),
        optional=("unit", "units", "betas", "seeds", "beta", "seed",
                  "worker", "retries", "retry_budget", "backoff_s",
                  "reason", "error", "status", "tenant", "study",
                  "priority", "retry_after_s"),
        doc="one β-grid scheduler job transition (dib_tpu/sched): "
            "submitted / unit_done / unit_failed / done / failed / "
            "rejected (admission control: the fleet queue bound refused "
            "the submit; carries tenant + retry_after_s); submitted "
            "jobs carry their fleet identity (tenant / study / "
            "priority)"),
    "lease": EventKindSpec(
        required=("unit", "action"),
        optional=("job_id", "worker", "lease", "expires_s",
                  "queue_wait_s", "attempt", "reason", "tenant"),
        doc="one work-unit lease transition (dib_tpu/sched): granted / "
            "renewed / released / expired / rejected; grants carry the "
            "tenant they bill to under fair-share scheduling"),
    "publish": EventKindSpec(
        required=("publish_id", "step"),
        optional=("path", "round", "beta", "epoch", "seconds"),
        doc="one chunk-aligned checkpoint published by the streaming "
            "trainer (dib_tpu/stream): staged, fsynced, renamed, then "
            "journaled — the record lands only after the checkpoint is "
            "fully durable under its final path"),
    "deploy": EventKindSpec(
        required=("publish_id", "action"),
        optional=("model", "step", "index", "latency_s", "canary_s",
                  "error"),
        doc="one deployer decision on a published checkpoint "
            "(dib_tpu/stream): promoted (canary passed, hot-swapped via "
            "ModelZoo.reload) or rolled_back (canary/restore failed; the "
            "previous checkpoint keeps answering); latency_s is the "
            "publish→serve window the streaming SLO gates"),
    "study": EventKindSpec(
        required=("study_id", "action"),
        optional=("round", "job_id", "betas", "seeds", "units",
                  "estimates", "deltas_decades", "band_nats",
                  "budget_spent", "budget_max", "max_rounds", "verdict",
                  "reason", "tenant", "fleet", "retry_after_s"),
        doc="one closed-loop study-controller transition (dib_tpu/study): "
            "`submit` (a round's job handed to the scheduler — exactly "
            "once, by decided-set replay), `round` (a round's results "
            "collected: per-channel transition-β `estimates`, their "
            "round-over-round `deltas_decades`, the ensemble "
            "`band_nats`, budget spent), and the terminal verdict "
            "actions `converged` / `unconverged` / `no_transitions`; "
            "submit-only rounds carry `fleet` (the shared scheduler "
            "directory) and `tenant`, and an admission-rejected submit "
            "retries after `retry_after_s` (action `admission_wait`)"),
    "drift": EventKindSpec(
        required=("round", "detector"),
        optional=("shift", "threshold", "action", "epoch",
                  "rewind_epoch", "schedule_study"),
        doc="one detected input-distribution drift on the training "
            "stream (dib_tpu/stream): the normalized shift, the "
            "threshold it crossed, and the β response (reanneal/hold); "
            "a re-anneal under an autopilot-applied schedule carries "
            "`rewind_epoch` (the targeted restart point the refreshed "
            "transition-β floor maps to) and `schedule_study` (the "
            "study that produced it)"),
    "autopilot": EventKindSpec(
        required=("action", "round"),
        optional=("study_id", "reason", "verdict", "estimates",
                  "centers", "seed_publish", "schedule",
                  "drift_to_apply_s", "budget_max", "last_study_round"),
        doc="one drift-autopilot decision on a stream drift round "
            "(dib_tpu/autopilot): `intent` (a targeted mini-study "
            "minted for the drift, watch-seeded `centers`), "
            "`submitted` (its config journaled through the study "
            "controller under `budget_max` units), `verdict` (the "
            "study's outcome + refreshed `estimates`), `applied` (the "
            "re-anneal `schedule` + routing metadata durably written; "
            "`drift_to_apply_s` is the drift→apply latency the SLO "
            "gates), `apply_skip`, and `skip` (debounce/breaker/"
            "poison gates; `reason` says which)"),
    "breaker": EventKindSpec(
        required=("action",),
        optional=("consecutive", "threshold", "round", "via", "detail",
                  "job_id", "tenant", "unit", "until"),
        doc="one circuit-breaker transition: `trip` after `consecutive` "
            "failures reached `threshold`, `probe` (one half-open "
            "attempt let through), `reset` (closed again, `via` "
            "probe/operator). The autopilot breaker (dib_tpu/autopilot) "
            "gates drift studies by `round`; the scheduler's per-job "
            "breaker (dib_tpu/sched) quarantines a repeatedly-failing "
            "job — carrying `job_id`/`tenant`/`unit`/`until` — instead "
            "of burning the shared retry budget"),
    "link": EventKindSpec(
        required=("target",),
        optional=("relation", "plane", "source_ref", "detail"),
        doc="one cross-plane causal edge (telemetry/context.py): this "
            "stream's work was caused-by / gated-by / adopted-from the "
            "record named by `target` (plane:record_ref grammar — e.g. "
            "study:<id>, sched:unit:<unit_id>, publish:<publish_id>); "
            "`relation` names the edge kind, `plane` the target's plane, "
            "`source_ref` this side's own record ref — the explicit edges "
            "the fleet aggregator joins beyond the ctx envelope"),
    "metrics": EventKindSpec(
        required=("snapshots",),
        doc="counter/gauge/histogram snapshots"),
    "run_end": EventKindSpec(
        required=("status",),
        optional=("error", "seconds", "epoch", "aborted_chunk",
                  "steps_per_s", "requests", "ejected_replicas",
                  "final_val_loss", "resumed_from_epoch", "minutes"),
        doc="terminal status"),
}


def _strict() -> bool:
    """``DIB_TELEMETRY_STRICT=1``: emit() rejects kinds outside
    EVENT_SCHEMA instead of durably writing a record nothing downstream
    understands. Off by default — a production run must never die on a
    telemetry typo; CI and the drills turn it on."""
    return os.environ.get("DIB_TELEMETRY_STRICT") == "1"


__all__ = [
    "SCHEMA_VERSION",
    "EVENTS_FILENAME",
    "ENVELOPE_FIELDS",
    "EVENT_SCHEMA",
    "REQUEST_PHASES",
    "EventKindSpec",
    "EventWriter",
    "config_fingerprint",
    "device_memory_stats",
    "finalize_crashed",
    "finalize_open_writers",
    "host_memory_stats",
    "open_writer",
    "read_events",
    "resolve_events_path",
    "runtime_manifest",
    "shared_run_id",
]


def open_writer(dir_arg: str | None, default_dir: str | None,
                **kwargs) -> "EventWriter | None":
    """The CLI `--telemetry-dir` convention, in one place: ``None`` means
    "default into ``default_dir``", an empty string disables, anything
    else is the explicit directory. Returns ``None`` when disabled (also
    when the default itself is unset)."""
    directory = default_dir if dir_arg is None else dir_arg
    if not directory:
        return None
    return EventWriter(directory, **kwargs)


def shared_run_id() -> str:
    """One run = one run id across every process that writes its stream.

    Precedence: the ``DIB_TELEMETRY_RUN_ID`` environment variable (the
    watchdog supervisor pins it so the supervisor's mitigation events and
    every worker relaunch share the run id — otherwise run_id-scoped
    summaries would silently drop the mitigations the reliability gate
    counts); else process 0 generates an id and a host broadcast shares it
    SPMD-wide. Falls back to a locally generated id when jax isn't up or
    the broadcast fails (single process, tests)."""
    pinned = os.environ.get("DIB_TELEMETRY_RUN_ID")
    if pinned:
        return pinned
    rid = _new_run_id()
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return rid
    try:
        if jax_mod.process_count() <= 1:
            return rid
        import numpy as np
        from jax.experimental import multihost_utils

        payload = np.frombuffer(rid.encode().ljust(64), dtype=np.uint8)
        shared = multihost_utils.broadcast_one_to_all(payload)
        return bytes(bytearray(np.asarray(shared).tolist())).decode().strip()
    except Exception:
        return rid


def _new_run_id() -> str:
    return (time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
            + "-" + uuid.uuid4().hex[:8])

# Open writers, for terminal-record insurance on crash paths: an entry
# point's top-level except clause calls finalize_open_writers() so a
# crashed run's stream ends with run_end(status="error") instead of a
# dangling chunk event (a SIGKILLed worker still can't — that case is
# covered by the supervisor's mitigation events).
_OPEN_WRITERS: "weakref.WeakSet[EventWriter]" = weakref.WeakSet()


def finalize_open_writers(error: str | None = None) -> list[str]:
    """Emit ``run_end(status="error")`` on every writer whose run started
    but never ended, then close it. Returns the paths of the streams a
    terminal record was actually appended to — callers log them so crash
    forensics are discoverable; a writer that never emitted run_start is
    closed silently (there is nothing to find at its path). Safe to call
    when nothing is open (no-op)."""
    paths = []
    for writer in list(_OPEN_WRITERS):
        if writer._fd is None:
            continue
        if writer._started and not writer._ended:
            writer.run_end(status="error", error=error)
            paths.append(writer.path)
        writer.close()
    return paths


def finalize_crashed(exc: BaseException, log=None) -> list[str]:
    """The entry-point except-clause idiom, in one place: finalize open
    writers with the exception as the terminal error and log where the
    crash forensics landed. ``log`` is a one-string callable (stderr
    print, bench's log); None skips logging."""
    paths = finalize_open_writers(error=f"{type(exc).__name__}: {exc}")
    if log is not None:
        for path in paths:
            log(f"telemetry: crash terminal record appended to {path}")
    return paths


def config_fingerprint(config) -> str:
    """Stable short hash of a config dataclass/dict — the run_start manifest
    records it so two runs are comparable iff their fingerprints match."""
    if dataclasses.is_dataclass(config) and not isinstance(config, type):
        config = dataclasses.asdict(config)
    blob = json.dumps(config, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _git_sha() -> str | None:
    """SHA of the checkout THIS code runs from; None for site-packages
    installs. Never cwd's repo — a run launched from inside an unrelated
    project must not record that project's HEAD as its provenance."""
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=here, capture_output=True, text=True, timeout=5,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    return out.stdout.strip() if out.returncode == 0 else None


def _package_version(name: str) -> str | None:
    try:
        import importlib

        return getattr(importlib.import_module(name), "__version__", None)
    except Exception:
        return None


def runtime_manifest(
    config=None,
    mesh_shape: dict | None = None,
    device_info: bool = True,
    extra: dict | None = None,
) -> dict:
    """Provenance manifest for a ``run_start`` event.

    ``device_info=False`` skips everything that would initialize a JAX
    backend — for processes (watchdog supervisor, bench parent) that must
    never touch the accelerator.
    """
    manifest: dict = {
        "git_sha": _git_sha(),
        "python": sys.version.split()[0],
        "versions": {
            name: _package_version(name)
            for name in ("jax", "flax", "optax", "numpy")
        },
        "argv": list(sys.argv),
    }
    if device_info:
        import jax

        devices = jax.devices()
        manifest["device_kind"] = devices[0].device_kind
        manifest["device_platform"] = devices[0].platform
        manifest["device_count"] = len(devices)
        manifest["process_count"] = jax.process_count()
    if mesh_shape is not None:
        manifest["mesh_shape"] = dict(mesh_shape)
    if config is not None:
        if dataclasses.is_dataclass(config) and not isinstance(config, type):
            manifest["config"] = dataclasses.asdict(config)
        elif isinstance(config, dict):
            manifest["config"] = dict(config)
        manifest["config_hash"] = config_fingerprint(config)
    if extra:
        manifest.update(extra)
    return manifest


def device_memory_stats(device=None) -> dict | None:
    """Compact ``device.memory_stats()`` view; None when the backend has
    none (CPU) or the call fails."""
    try:
        import jax

        device = device or jax.devices()[0]
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    keep = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
            "largest_alloc_size")
    out = {k: int(stats[k]) for k in keep if k in stats}
    return out or None


def host_memory_stats() -> dict | None:
    """Host RSS from ``/proc/self/status``: ``{"rss_bytes", "peak_rss_bytes"}``.

    The CPU backend has no ``device.memory_stats()``, so CI/CPU runs would
    carry no memory signal at all without this fallback — it is emitted
    ALONGSIDE device stats on every chunk (VmHWM is the process high-water
    mark, which is what the run report's memory section keys on). None on
    non-Linux hosts or when /proc is unreadable."""
    try:
        with open("/proc/self/status") as f:
            lines = f.read().splitlines()
    except OSError:
        return None
    out = {}
    keys = {"VmRSS": "rss_bytes", "VmHWM": "peak_rss_bytes"}
    for line in lines:
        name, _, rest = line.partition(":")
        if name in keys:
            parts = rest.split()
            if parts and parts[0].isdigit():
                out[keys[name]] = int(parts[0]) * 1024   # kB -> bytes
    return out or None


class EventWriter:
    """Appends schema-versioned events to ``<directory>/events.jsonl``.

    ``process_index=None`` resolves via ``jax.process_index()`` ONLY if the
    jax backend is demonstrably safe to touch (jax already imported);
    processes that must stay backend-free (watchdog supervisor, bench
    parent) pass an explicit index (normally 0). ``tags`` ride every
    envelope — e.g. ``{"src": "supervisor"}``.
    """

    def __init__(
        self,
        directory: str,
        run_id: str | None = None,
        process_index: int | None = None,
        tags: dict | None = None,
        filename: str = EVENTS_FILENAME,
        ctx=None,
    ):
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, filename)
        self.run_id = run_id or _new_run_id()
        # the cross-plane trace context (telemetry/context.py): None means
        # untraced; unset means "inherit whatever a parent process pinned"
        # — the DIB_TELEMETRY_RUN_ID idiom, extended to lineage
        if ctx is None:
            from dib_tpu.telemetry.context import from_env

            ctx = from_env()
        self.ctx = ctx
        if process_index is None:
            process_index = 0
            if "jax" in sys.modules:
                try:
                    process_index = sys.modules["jax"].process_index()
                except Exception:
                    process_index = 0
        self.process_index = int(process_index)
        self.tags = dict(tags or {})
        self._seq = 0
        self._started = False
        self._ended = False
        # The heartbeat emitter (telemetry/hooks.py) writes from a daemon
        # thread while the main thread is blocked on the device; the lock
        # keeps seq gapless and the record/write pairing consistent.
        self._lock = threading.Lock()
        self._fd = os.open(
            self.path, os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644
        )
        _OPEN_WRITERS.add(self)

    # ----------------------------------------------------------- low level
    def emit(self, event_type: str, **data) -> dict:
        """Append one event; returns the full record as written.

        A writer another thread already closed (preemption grace-abort,
        shutdown racing a heartbeat) drops the event instead of crashing
        the emitting thread. Under ``DIB_TELEMETRY_STRICT=1`` an
        ``event_type`` outside :data:`EVENT_SCHEMA` raises instead of
        writing a record no reader understands."""
        if _strict() and event_type not in EVENT_SCHEMA:
            raise ValueError(
                f"unknown event kind {event_type!r} "
                f"(DIB_TELEMETRY_STRICT=1; known kinds: "
                f"{sorted(EVENT_SCHEMA)}) — add a row to "
                "telemetry/events.py EVENT_SCHEMA and document it in "
                "docs/observability.md first"
            )
        with self._lock:
            if self._fd is None:
                return {}
            record = {
                "v": SCHEMA_VERSION,
                "run": self.run_id,
                "proc": self.process_index,
                "seq": self._seq,
                "t": time.time(),
                "mono": time.perf_counter(),
                "type": event_type,
            }
            if self.tags:
                record["tags"] = self.tags
            if self.ctx is not None:
                record["ctx"] = self.ctx.to_dict()
            record.update(data)
            self._seq += 1
            # allow_nan=False: a diverged run's loss=NaN must not write a
            # bare NaN token nothing downstream can parse — non-finite
            # floats are encoded as the strings "NaN"/"Infinity"/
            # "-Infinity" instead (read back by summarize; a non-finite
            # candidate REGRESSES in compare). The sanitize walk runs only
            # on the rare bad event.
            try:
                line = json.dumps(record, default=_json_default,
                                  allow_nan=False) + "\n"
            except ValueError:
                record = _sanitize_nonfinite(record)
                line = json.dumps(record, default=_json_default,
                                  allow_nan=False) + "\n"
            # one write() per line on an O_APPEND fd: concurrent writers
            # cannot interleave, a kill can only truncate the final line
            os.write(self._fd, line.encode())
        return record

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None
        _OPEN_WRITERS.discard(self)

    def __enter__(self) -> "EventWriter":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        # terminal-record insurance: a run that started inside this context
        # and died on an exception still gets a run_end on its stream
        if (exc_type is not None and self._started and not self._ended
                and self._fd is not None):
            self.run_end(status="error",
                         error=f"{exc_type.__name__}: {exc}")
        self.close()

    # -------------------------------------------------------- typed events
    def run_start(self, manifest: dict) -> dict:
        self._started = True
        return self.emit("run_start", manifest=manifest)

    def chunk(self, *, epoch: int, steps: int, seconds: float, **fields) -> dict:
        steps_per_s = steps / seconds if seconds > 0 else None
        return self.emit(
            "chunk", epoch=int(epoch), steps=int(steps),
            seconds=round(float(seconds), 6),
            steps_per_s=round(steps_per_s, 3) if steps_per_s else None,
            **fields,
        )

    def compile(self, *, name: str, seconds: float, cache: str, **fields) -> dict:
        """``cache`` is the ``utils/compile_cache.py`` status ("warm" =
        persistent-cache hit, "cold-populating" = miss being written,
        "off") or a backend-specific hit/miss string."""
        return self.emit(
            "compile", name=name, seconds=round(float(seconds), 4),
            cache=cache, **fields,
        )

    def mitigation(self, *, mtype: str, **fields) -> dict:
        return self.emit("mitigation", mtype=mtype, **fields)

    def fault(self, *, kind: str, **fields) -> dict:
        """One deliberate fault injection (``dib_tpu/faults``). Emitted
        BEFORE the fault executes — a SIGKILL fault still leaves its
        record (one O_APPEND write, already durable when the signal
        lands)."""
        return self.emit("fault", kind=kind, **fields)

    def hook(self, *, name: str, epoch: int, seconds: float, **fields) -> dict:
        return self.emit(
            "hook", name=name, epoch=int(epoch),
            seconds=round(float(seconds), 6), **fields,
        )

    def mi_bounds(self, *, epoch: int, **fields) -> dict:
        return self.emit("mi_bounds", epoch=int(epoch), **fields)

    def heartbeat(self, *, beat: int, epoch: int, phase: str,
                  **fields) -> dict:
        """One liveness beat (telemetry/hooks.py FitRecorder). ``phase``
        is ``"boundary"`` (chunk boundary, main thread — carries trailing
        ``intervals_s``, the watchdog's stall clock) or ``"chunk"``
        (mid-chunk daemon thread — carries ``interval_s`` and
        ``phase_elapsed_s``; between chunks the same thread beats with
        phase ``"host"``)."""
        return self.emit("heartbeat", beat=int(beat), epoch=int(epoch),
                         phase=phase, **fields)

    def alert(self, *, rule: str, **fields) -> dict:
        """One durable SLO violation (``telemetry/slo.py``): the rule
        name plus the observed value vs its budget."""
        return self.emit("alert", rule=rule, **fields)

    def anomaly(self, *, epoch: int, channel: str, kind: str,
                **fields) -> dict:
        """One boundary anomaly verdict (``train/anomaly.py``): a
        non-finite or robust-z-spiking boundary metric, emitted before
        the rollback/ejection mitigation it provokes."""
        return self.emit("anomaly", epoch=int(epoch), channel=channel,
                         kind=kind, **fields)

    def quarantine(self, *, step: int, reason: str, **fields) -> dict:
        """One checkpoint step moved into ``quarantine/``
        (``train/checkpoint.py``): corrupt bytes, or a step written
        during an anomalous window — never restorable again."""
        return self.emit("quarantine", step=int(step), reason=reason,
                         **fields)

    def transition(self, *, channel: int, epoch: int, direction: str,
                   **fields) -> dict:
        """One info-plane transition: channel ``channel``'s KL crossed
        the configured threshold between chunk boundaries (``direction``
        ``"up"``/``"down"``)."""
        return self.emit("transition", channel=int(channel),
                         epoch=int(epoch), direction=direction, **fields)

    def span(self, *, name: str, path: str, span_id: int,
             parent_id: int | None, seconds: float, **fields) -> dict:
        """One closed span (``telemetry/trace.py``): ``span``/``parent`` ids
        rebuild the tree, ``path`` is the full slash path (also the name
        under which the interval appears in captured XLA traces)."""
        if _strict() and "phases" in fields:
            bad = set(fields["phases"]) - set(REQUEST_PHASES)
            if bad:
                raise ValueError(
                    f"span phases outside REQUEST_PHASES: {sorted(bad)}")
        return self.emit(
            "span", name=name, path=path, span=int(span_id),
            parent=parent_id if parent_id is None else int(parent_id),
            seconds=round(float(seconds), 6), **fields,
        )

    def job(self, *, job_id: str, action: str, **fields) -> dict:
        """One β-grid scheduler job transition (``dib_tpu/sched``):
        ``action`` is ``submitted`` / ``unit_done`` / ``unit_failed`` /
        ``done`` / ``failed``."""
        return self.emit("job", job_id=job_id, action=action, **fields)

    def lease(self, *, unit: str, action: str, **fields) -> dict:
        """One work-unit lease transition (``dib_tpu/sched``): ``action``
        is ``granted`` / ``renewed`` / ``released`` / ``expired`` /
        ``rejected`` (a superseded lease's completion or renewal — the
        double-execution guard firing)."""
        return self.emit("lease", unit=unit, action=action, **fields)

    def publish(self, *, publish_id: str, step: int, **fields) -> dict:
        """One published streaming checkpoint (``dib_tpu/stream``):
        emitted after the atomic stage→fsync→rename→journal protocol
        completed, so the event mirrors a durable ``publishes.jsonl``
        record."""
        return self.emit("publish", publish_id=publish_id, step=int(step),
                         **fields)

    def deploy(self, *, publish_id: str, action: str, **fields) -> dict:
        """One deployer decision (``dib_tpu/stream``): ``action`` is
        ``promoted`` (hot-swapped into the fleet) or ``rolled_back``
        (canary/restore failure; previous checkpoint keeps serving)."""
        return self.emit("deploy", publish_id=publish_id, action=action,
                         **fields)

    def study(self, *, study_id: str, action: str, **fields) -> dict:
        """One study-controller transition (``dib_tpu/study``):
        ``action`` is ``submit`` (round job handed to the scheduler,
        exactly-once), ``round`` (round results collected: transition-β
        estimates + deltas + ensemble band), or a terminal verdict —
        ``converged`` / ``unconverged`` / ``no_transitions``."""
        return self.emit("study", study_id=study_id, action=action,
                         **fields)

    def drift(self, *, round: int, detector: str, **fields) -> dict:
        """One detected training-stream drift (``dib_tpu/stream``)."""
        return self.emit("drift", round=int(round), detector=detector,
                         **fields)

    def autopilot(self, *, action: str, round: int, **fields) -> dict:
        """One drift-autopilot decision (``dib_tpu/autopilot``):
        ``action`` is ``intent`` / ``submitted`` / ``verdict`` /
        ``applied`` / ``apply_skip`` / ``skip`` on drift round
        ``round`` — the event mirror of the durable ``autopilot.jsonl``
        chain."""
        return self.emit("autopilot", action=action, round=int(round),
                         **fields)

    def breaker(self, *, action: str, **fields) -> dict:
        """One autopilot circuit-breaker transition
        (``dib_tpu/autopilot``): ``trip`` / ``probe`` / ``reset``."""
        return self.emit("breaker", action=action, **fields)

    def link(self, *, target: str, **fields) -> dict:
        """One cross-plane causal edge (``telemetry/context.py``):
        ``target`` names the record this stream's work was caused by /
        gated by (``plane:record_ref`` grammar) — the explicit DAG edge
        the fleet aggregator joins beyond the ``ctx`` envelope."""
        return self.emit("link", target=target, **fields)

    def metrics(self, snapshots) -> dict:
        return self.emit("metrics", snapshots=snapshots)

    def run_end(self, *, status: str = "ok", **fields) -> dict:
        self._ended = True
        return self.emit("run_end", status=status, **fields)


def _json_default(x):
    """Arrays/np scalars -> lists/floats so emit() never throws mid-run."""
    if hasattr(x, "tolist"):
        return x.tolist()
    if hasattr(x, "item"):
        return x.item()
    return str(x)


def _sanitize_nonfinite(x):
    """Non-finite floats -> their float()-parseable string spellings."""
    if hasattr(x, "tolist"):
        x = x.tolist()
    elif hasattr(x, "item"):
        x = x.item()
    if isinstance(x, float) and x != x:
        return "NaN"
    if isinstance(x, float) and x in (float("inf"), float("-inf")):
        return "Infinity" if x > 0 else "-Infinity"
    if isinstance(x, dict):
        return {k: _sanitize_nonfinite(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_sanitize_nonfinite(v) for v in x]
    return x


def resolve_events_path(path: str) -> str:
    """Accept a run dir or a direct events file path."""
    if os.path.isdir(path):
        return os.path.join(path, EVENTS_FILENAME)
    return path


def read_events(
    path: str,
    process_index: int | None = None,
    types=None,
):
    """Yield events from an events.jsonl, oldest first.

    Tolerates torn lines ANYWHERE, with a warning: each event is one
    ``os.write``, so under the append contract the only source of a
    non-parsing line is a writer killed mid-write — and a kill is NOT
    guaranteed to be the last word in the file, because the watchdog
    supervisor (and the relaunched worker) keep appending to the same
    stream after it. A torn line glued to a later complete line must not
    make the recovered run's history unreadable. ``process_index``
    filters to one process's events; ``types`` to a set of event types.
    """
    path = resolve_events_path(path)
    if types is not None:
        types = set(types)
    with open(path, "rb") as f:
        raw = f.read()
    torn = 0
    for i, line in enumerate(raw.split(b"\n")):
        if not line.strip():
            continue
        try:
            event = json.loads(line)
        except json.JSONDecodeError:
            torn += 1
            continue
        if process_index is not None and event.get("proc") != process_index:
            continue
        if types is not None and event.get("type") not in types:
            continue
        yield event
    if torn:
        import warnings

        warnings.warn(
            f"{path}: skipped {torn} torn event line(s) — a writer was "
            f"killed mid-append (expected under watchdog kills; anything "
            f"else violates the one-write-per-line contract)"
        )
