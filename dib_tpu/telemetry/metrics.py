"""Lightweight counters / gauges / histograms with multihost aggregation.

Host-side metric plumbing for run telemetry — NOT a time-series database.
Everything is in-process and cheap (a dict update per observation); the
values reach disk only when :func:`write_metrics` snapshots the registry
into a ``metrics`` event on the run's event stream.

Multihost contract (mirrors the event-stream convention): in a
multi-controller run every process maintains its own registry with the SAME
metric names (SPMD — all hosts run the same program). ``write_metrics``
tag-and-forwards: every process contributes its snapshot through a
process allgather, and only process 0 writes the merged ``metrics`` event.
Non-zero processes return without touching the file.
"""

from __future__ import annotations

import math
import re
import threading
from bisect import bisect_left
from collections import deque

__all__ = [
    "BUCKET_BOUNDS",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "bucket_counts",
    "bucket_index",
    "bucket_quantile",
    "gather_snapshots",
    "prometheus_text",
    "write_metrics",
]

# Fleet-wide histogram bucket bounds, in seconds: log-spaced, 8 buckets per
# decade from 1 µs to 100 s. FIXED across every process and every release —
# N prefork workers' ``/metrics`` merge by plain per-bucket addition only
# because every worker buckets identically. Changing these bounds is a
# telemetry schema change (old and new workers would stop being mergeable).
BUCKET_BOUNDS = tuple(10.0 ** (-6.0 + i / 8.0) for i in range(65))

# Observations above the last bound land in the overflow bucket at this
# index (``le_inf`` in snapshots, ``le="+Inf"`` in Prometheus text).
_OVERFLOW = len(BUCKET_BOUNDS)


def bucket_index(value: float) -> int:
    """Dense bucket index for ``value``: smallest i with
    ``value <= BUCKET_BOUNDS[i]``, or the overflow index past the end.
    Pure function of the fixed bounds — every worker agrees."""
    return bisect_left(BUCKET_BOUNDS, float(value))


def bucket_counts(hist_snapshot: dict) -> list:
    """Dense per-bucket counts (len ``len(BUCKET_BOUNDS)+1``) from a
    histogram snapshot's sparse ``le_NNN``/``le_inf`` keys.

    Snapshots store only nonzero buckets; this re-densifies them so
    merged fleets can be summed index-wise and fed to
    :func:`bucket_quantile`. Tolerates snapshots whose numeric values
    were floated in flight (``_flatten``, JSON round-trips)."""
    dense = [0] * (_OVERFLOW + 1)
    for key, value in hist_snapshot.items():
        if not key.startswith("le_"):
            continue
        tail = key[3:]
        idx = _OVERFLOW if tail == "inf" else int(tail)
        dense[idx] += int(round(float(value)))
    return dense

def bucket_quantile(counts, q: float):
    """Nearest-rank quantile estimate from dense per-bucket counts:
    the upper bound of the bucket holding the rank-``ceil(q*total)``
    observation (overflow reports the last finite bound).

    Deterministic pure function of the counts — merging two workers'
    buckets by addition then calling this gives bit-identical results
    to bucketing the combined stream, which is the whole point of
    fixed fleet-wide bounds. Returns None when the histogram is empty."""
    total = sum(counts)
    if total <= 0:
        return None
    rank = max(1, math.ceil(q * total))
    seen = 0
    for i, c in enumerate(counts):
        seen += c
        if seen >= rank:
            return BUCKET_BOUNDS[min(i, _OVERFLOW - 1)]
    return BUCKET_BOUNDS[-1]


class Counter:
    """Monotonically increasing count (events, steps, mitigations).

    Updates are locked: the serving path (``dib_tpu/serve``) increments
    from many batcher/HTTP threads at once, and an unlocked ``+=`` is a
    read-modify-write that drops counts under contention. The training
    path is single-threaded; an uncontended lock costs ~100 ns.
    """

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"Counter increments must be >= 0, got {amount}")
        with self._lock:
            self.value += amount


class Gauge:
    """Last-write-wins instantaneous value (memory bytes, current beta)."""

    def __init__(self):
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)   # single store: atomic under the GIL


class Histogram:
    """Streaming distribution summary over a bounded window.

    Tracks exact count/sum/min/max over the full stream, percentiles
    over the trailing ``window`` observations, and exact per-bucket
    counts over the full stream against the fixed fleet-wide
    :data:`BUCKET_BOUNDS` — the windowed percentiles answer "what is
    this worker doing right now", the buckets make N workers'
    snapshots mergeable by addition. ``record``/``snapshot`` are
    locked (see Counter).
    """

    def __init__(self, window: int = 4096):
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None
        self._buckets = [0] * (_OVERFLOW + 1)
        self._window = deque(maxlen=window)
        self._lock = threading.Lock()

    def record(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self.count += 1
            self.sum += value
            self.min = value if self.min is None else min(self.min, value)
            self.max = value if self.max is None else max(self.max, value)
            self._buckets[bucket_index(value)] += 1
            self._window.append(value)

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "count": self.count,
                "sum": self.sum,
                "min": self.min if self.min is not None else 0.0,
                "max": self.max if self.max is not None else 0.0,
                "mean": self.sum / self.count if self.count else 0.0,
            }
            # Sparse flat keys, not a nested dict: snapshot leaves must
            # stay one level deep so _flatten / fleet merging see
            # "histograms.<name>.le_NNN" and sum them like any stat.
            for i, c in enumerate(self._buckets):
                if c:
                    key = "le_inf" if i == _OVERFLOW else f"le_{i:03d}"
                    out[key] = c
            window = list(self._window)
        if window:
            ordered = sorted(window)
            for name, q in (("p50", 0.5), ("p90", 0.9), ("p99", 0.99)):
                out[name] = ordered[min(int(q * len(ordered)), len(ordered) - 1)]
        return out


class MetricsRegistry:
    """Named metric store: ``registry.counter("steps").inc(50)``."""

    def __init__(self):
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        return self._counters.setdefault(name, Counter())

    def gauge(self, name: str) -> Gauge:
        return self._gauges.setdefault(name, Gauge())

    def histogram(self, name: str, window: int = 4096) -> Histogram:
        return self._histograms.setdefault(name, Histogram(window))

    def snapshot(self) -> dict:
        """Nested JSON-ready view of every metric's current value."""
        return {
            "counters": {k: c.value for k, c in self._counters.items()},
            "gauges": {k: g.value for k, g in self._gauges.items()},
            "histograms": {
                k: h.snapshot() for k, h in self._histograms.items()
            },
        }


_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str, prefix: str) -> str:
    """Prometheus metric name: dotted registry names become underscored,
    everything outside [a-zA-Z0-9_:] sanitized, ``prefix_`` prepended."""
    return f"{prefix}_{_PROM_BAD.sub('_', name)}"


def prometheus_text(snapshot: dict, prefix: str = "dib") -> str:
    """Render a :meth:`MetricsRegistry.snapshot` in Prometheus text
    exposition format (version 0.0.4 — what every Prometheus scraper and
    most collectors speak).

    Counters map to ``counter``, gauges to ``gauge``; histograms map to
    TWO families. The legacy ``summary`` (``_count``/``_sum`` plus
    ``quantile``-labelled samples from the windowed p50/p90/p99) with
    ``_min``/``_max`` gauges is kept for back-compat — per-worker
    quantiles are honest but mathematically impossible to aggregate
    across a prefork fleet. The native ``{name}_hist`` ``histogram``
    family renders the fixed fleet-wide :data:`BUCKET_BOUNDS` as
    cumulative ``_bucket{le=...}`` samples (the ``+Inf`` bucket is
    ALWAYS emitted, so ``histogram_quantile()`` works even on an empty
    or bucket-less snapshot) with matching ``_hist_sum``/``_hist_count``
    — those merge across workers by plain addition. The serving
    ``/metrics`` endpoint returns this under content negotiation
    (docs/serving.md)."""
    lines: list[str] = []

    def sample(name: str, value, labels: str = "") -> None:
        v = float(value)
        if v != v:   # NaN never reaches a scraper
            return
        # shortest round-trip repr, never '%g': a 7-digit request counter
        # must not be exposed as 1.23457e+06 (rate()/increase() over
        # scrapes would drift from truth)
        text = str(int(v)) if v == int(v) and abs(v) < 1e15 else repr(v)
        lines.append(f"{name}{labels} {text}")

    for name, value in sorted((snapshot.get("counters") or {}).items()):
        prom = _prom_name(name, prefix)
        lines.append(f"# TYPE {prom} counter")
        sample(prom, value)
    for name, value in sorted((snapshot.get("gauges") or {}).items()):
        prom = _prom_name(name, prefix)
        lines.append(f"# TYPE {prom} gauge")
        sample(prom, value)
    for name, hist in sorted((snapshot.get("histograms") or {}).items()):
        prom = _prom_name(name, prefix)
        lines.append(f"# TYPE {prom} summary")
        for label, key in (("0.5", "p50"), ("0.9", "p90"), ("0.99", "p99")):
            if key in hist:
                sample(prom, hist[key], labels='{quantile="%s"}' % label)
        sample(f"{prom}_sum", hist.get("sum", 0.0))
        sample(f"{prom}_count", hist.get("count", 0))
        for edge in ("min", "max"):
            lines.append(f"# TYPE {prom}_{edge} gauge")
            sample(f"{prom}_{edge}", hist.get(edge) or 0.0)
        # Native histogram family: cumulative buckets against the fixed
        # fleet-wide bounds. Only populated buckets get a finite-le line
        # (keeps the exposition compact; a missing le series scrapes as
        # zero), but +Inf is unconditional and _hist_count == _hist_sum's
        # companion always equals the +Inf bucket — the consistency
        # histogram_quantile() and rate() arithmetic rely on.
        dense = bucket_counts(hist)
        lines.append(f"# TYPE {prom}_hist histogram")
        cumulative = 0
        for i, c in enumerate(dense[:_OVERFLOW]):
            cumulative += c
            if c:
                le = f"{BUCKET_BOUNDS[i]:.6g}"
                sample(f"{prom}_hist_bucket", cumulative,
                       labels='{le="%s"}' % le)
        sample(f"{prom}_hist_bucket", hist.get("count", 0),
               labels='{le="+Inf"}')
        sample(f"{prom}_hist_sum", hist.get("sum", 0.0))
        sample(f"{prom}_hist_count", hist.get("count", 0))
    return "\n".join(lines) + "\n"


def _flatten(tree: dict, prefix: str = "") -> dict:
    out = {}
    for key in sorted(tree):
        value = tree[key]
        if isinstance(value, dict):
            out.update(_flatten(value, f"{prefix}{key}."))
        else:
            out[prefix + key] = float(value)
    return out


def gather_snapshots(registry: MetricsRegistry) -> list[dict]:
    """Per-process flat snapshots, one dict per process, ``proc`` tagged.

    Single process: just the local snapshot. Multi-process: the flattened
    numeric values ride a ``process_allgather`` (names are identical across
    processes by the SPMD contract, so only values travel); every process
    receives all snapshots, but by convention only process 0 writes them.
    """
    import jax

    local = _flatten(registry.snapshot())
    local_tagged = {"proc": jax.process_index(), **local}
    if jax.process_count() == 1:
        return [local_tagged]

    import numpy as np
    from jax.experimental import multihost_utils

    keys = list(local.keys())
    values = np.asarray([local[k] for k in keys], np.float64)
    gathered = np.asarray(
        multihost_utils.process_allgather(values)
    ).reshape(jax.process_count(), -1)
    return [
        {"proc": p, **{k: float(v) for k, v in zip(keys, gathered[p])}}
        for p in range(jax.process_count())
    ]


def write_metrics(registry: MetricsRegistry, writer) -> bool:
    """Snapshot ``registry`` into a ``metrics`` event on ``writer``.

    Returns True iff this process wrote (process 0); non-zero processes
    contribute through the gather and return False without writing.
    """
    import jax

    snapshots = gather_snapshots(registry)
    if jax.process_index() != 0:
        return False
    writer.metrics(snapshots)
    return True
