"""Cross-plane trace context: WHO caused WHAT, fleet-wide.

Every control plane (study controller, β-grid scheduler, unit runs,
streaming trainer, deployer, serving zoo) writes its own durable file —
events.jsonl, journal.jsonl, study.jsonl, publishes.jsonl. Before this
module those files shared no identity: joining "which drift event caused
this study, which units did round 2 submit, and which publish did the
result gate?" meant hand-matching five files by wall clock. A
:class:`TraceContext` is the shared identity: a ``trace_id`` minted once
at the causal ROOT (a study submit, a sched job, a stream drift, a
deploy publish), a ``parent`` ref naming the record that caused this one
(``plane:record_ref`` grammar, below), and the human-readable ``origin``
chain of entry points the context passed through.

The context rides as the ``ctx`` ENVELOPE field on every telemetry
event (:class:`~dib_tpu.telemetry.events.EventWriter` stamps it, like
``tags``) and as a ``ctx`` field on sched/study journal records — so the
fleet aggregator (``telemetry/fleet.py``) can reconstruct the whole
study→units→publish DAG from the files alone.

Parent-ref grammar (``plane:record_ref``)::

    study:<study_id>          the study plane's root record
    sched:job:<job_id>        a scheduler job record
    sched:unit:<unit_id>      one (β, seed) work unit
    run:<run_id>              a telemetry run (its run_start)
    publish:<publish_id>      a streaming publish record
    drift:<round>             a drift detection on a stream

Cross-process inheritance mirrors the ``DIB_TELEMETRY_RUN_ID`` pinning
idiom: :meth:`TraceContext.activate` exports ``DIB_TRACE_ID`` /
``DIB_TRACE_PARENT`` / ``DIB_TRACE_ORIGIN`` so run-pool workers, prefork
serve workers, and watchdog relaunches inherit the lineage of whatever
spawned them; :func:`from_env` reads it back on the far side.
"""

from __future__ import annotations

import dataclasses
import os
import uuid

__all__ = [
    "TRACE_ENV",
    "TRACE_ORIGIN_ENV",
    "TRACE_PARENT_ENV",
    "TraceContext",
    "child_context",
    "ensure_context",
    "from_env",
    "mint",
    "parse_parent_ref",
]

#: The env-inheritance triple (the ``DIB_TELEMETRY_RUN_ID`` idiom):
#: a supervisor/parent pins these, spawned workers inherit the lineage.
TRACE_ENV = "DIB_TRACE_ID"
TRACE_PARENT_ENV = "DIB_TRACE_PARENT"
TRACE_ORIGIN_ENV = "DIB_TRACE_ORIGIN"


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One causal lineage: ``trace_id`` names the root cause (shared by
    every record the cause transitively produced), ``parent`` names the
    immediate causing record (``plane:record_ref``; None at the root),
    and ``origin`` is the ordered chain of entry points the context has
    passed through (e.g. ``("study", "sched", "unit")``)."""

    trace_id: str
    parent: str | None = None
    origin: tuple[str, ...] = ()

    def to_dict(self) -> dict:
        out: dict = {"trace_id": self.trace_id}
        if self.parent:
            out["parent"] = self.parent
        if self.origin:
            out["origin"] = list(self.origin)
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "TraceContext | None":
        if not isinstance(d, dict) or not d.get("trace_id"):
            return None
        origin = d.get("origin") or ()
        if not isinstance(origin, (list, tuple)):
            origin = ()
        return cls(trace_id=str(d["trace_id"]),
                   parent=str(d["parent"]) if d.get("parent") else None,
                   origin=tuple(str(o) for o in origin))

    def child(self, parent_ref: str, origin: str | None = None
              ) -> "TraceContext":
        """The context a record CAUSED BY ``parent_ref`` carries: same
        trace_id (one causal tree, one id), new parent edge, origin chain
        extended when this is a new entry point."""
        chain = self.origin
        if origin and (not chain or chain[-1] != origin):
            chain = chain + (origin,)
        return TraceContext(self.trace_id, parent=parent_ref, origin=chain)

    def activate(self, environ=None) -> None:
        """Export to the environment so spawned processes inherit this
        lineage (the ``DIB_TELEMETRY_RUN_ID`` pinning idiom — run-pool
        workers, prefork serve workers, watchdog relaunches)."""
        env = os.environ if environ is None else environ
        env[TRACE_ENV] = self.trace_id
        if self.parent:
            env[TRACE_PARENT_ENV] = self.parent
        else:
            env.pop(TRACE_PARENT_ENV, None)
        if self.origin:
            env[TRACE_ORIGIN_ENV] = ",".join(self.origin)
        else:
            env.pop(TRACE_ORIGIN_ENV, None)


def mint(origin: str, trace_id: str | None = None,
         parent: str | None = None) -> TraceContext:
    """A fresh context at a causal root (an entry point with no inherited
    lineage). ``trace_id`` overrides the generated id — the CLI
    ``--trace-id`` flag lands here so an external orchestrator can name
    the trace it is about to follow."""
    return TraceContext(trace_id or ("trace-" + uuid.uuid4().hex[:12]),
                        parent=parent, origin=(origin,))


def from_env(environ=None) -> TraceContext | None:
    """The lineage a parent process pinned (None when unpinned)."""
    env = os.environ if environ is None else environ
    trace_id = env.get(TRACE_ENV)
    if not trace_id:
        return None
    origin = tuple(o for o in (env.get(TRACE_ORIGIN_ENV) or "").split(",")
                   if o)
    return TraceContext(trace_id, parent=env.get(TRACE_PARENT_ENV) or None,
                        origin=origin)


def ensure_context(origin: str, trace_id: str | None = None
                   ) -> TraceContext:
    """The entry-point idiom: an explicit ``--trace-id`` wins, then an
    env-inherited lineage (extended with this entry point's origin), else
    a freshly minted root."""
    inherited = from_env()
    if trace_id and (inherited is None or inherited.trace_id != trace_id):
        return mint(origin, trace_id=trace_id)
    if inherited is None:
        return mint(origin)
    if inherited.origin and inherited.origin[-1] == origin:
        return inherited
    return dataclasses.replace(inherited,
                               origin=inherited.origin + (origin,))


def child_context(ctx: "TraceContext | None", parent_ref: str,
                  origin: str | None = None) -> TraceContext | None:
    """``ctx.child(...)`` that tolerates an absent context (tracing is
    always optional — an untraced caller stays untraced)."""
    if ctx is None:
        return None
    return ctx.child(parent_ref, origin=origin)


def parse_parent_ref(ref: str) -> tuple[str, str]:
    """Split ``plane:record_ref`` into its plane and record ref (the
    record ref may itself contain colons — ``sched:unit:<job>/u0s0``)."""
    plane, _, rest = ref.partition(":")
    return plane, rest
