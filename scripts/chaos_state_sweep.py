"""The PRL paper's outer protocol as a committed artifact: entropy rate vs L.

Runs ``run_chaos_state_sweep`` — "loop over number_states from 2 to 15,
with 20 repeats per" (chaos notebook cell 10 header) — at a documented
reduced budget (the full paper budget is 14 L-values x 20 repeats x the
2x10^7-state CTW characterization; one such configuration alone takes ~2 h
of host CTW time on this box). Within each L the repeats train as ONE
vmapped program and the best repeat is characterized. Writes
``CHAOS_STATE_SWEEP.json`` + the summary figure (entropy rate vs L against
the known rate, the paper's Fig 3 shape).

Run on the TPU (ambient env, ALONE):

    python scripts/chaos_state_sweep.py [--system ikeda] [--repeats 3]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from dib_tpu.workloads.chaos import KNOWN_ENTROPY_RATES

    parser = argparse.ArgumentParser()
    parser.add_argument("--system", default="ikeda",
                        choices=sorted(KNOWN_ENTROPY_RATES))
    parser.add_argument("--repeats", type=int, default=3)
    parser.add_argument("--train-iterations", type=int, default=200_000)
    parser.add_argument("--char-iterations", type=int, default=2_000_000)
    parser.add_argument("--states", type=int, nargs="+",
                        default=list(range(2, 16)))
    parser.add_argument("--outdir", default="chaos_sweep_out")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--report", default="CHAOS_STATE_SWEEP.json")
    args = parser.parse_args()

    import numpy as np

    from dib_tpu.workloads.chaos import run_chaos_state_sweep

    t0 = time.time()
    result = run_chaos_state_sweep(
        system=args.system,
        state_counts=tuple(args.states),
        num_repeats=args.repeats,
        outdir=args.outdir,
        seed=args.seed,
        train_iterations=args.train_iterations,
        characterization_iterations=args.char_iterations,
        include_random_baseline=False,
    )
    wall_s = time.time() - t0

    curve = result["curve"]
    known = float(curve["h_known"])
    h = np.asarray(curve["h_inf"], np.float64)
    report = {
        "metric": f"{args.system}_entropy_rate_vs_num_measurements",
        "value": round(float(h.max()), 4),
        "unit": "bits (max over L)",
        "system": args.system,
        "known_rate_bits": known,
        "state_counts": [int(x) for x in curve["state_counts"]],
        "h_inf_bits": [round(float(x), 4) for x in h],
        "mi_lower_bits": [round(float(x), 4) for x in curve["mi_lower_bits"]],
        "repeats_per_state": args.repeats,
        "train_iterations": args.train_iterations,
        "characterization_iterations": args.char_iterations,
        "budget_note": (
            # the note must describe the budget actually run (VERDICT round
            # 3 item 2: the anchor L values should carry no reduced-budget
            # disclaimer once run at paper scale)
            # exact wording of the committed CHAOS_STATE_SWEEP.json so a
            # re-run of the documented command reproduces the artifact
            # (ADVICE round 4)
            "paper-scale per-config budget (1e6 train / 2e7 characterization "
            f"states) at the anchor L values; {args.repeats} repeats per L "
            "(paper: 20). The full 14-L shape at reduced budget is "
            "CHAOS_STATE_SWEEP_SHAPE.json."
            if args.train_iterations >= 1_000_000
            and args.char_iterations >= 20_000_000
            else
            "reduced budget (paper: 20 repeats, 1e6 train / 2e7 char states "
            "per config); the saturation SHAPE vs L is the product here — "
            "the absolute-rate anchors at full budget are "
            "CHAOS_FULL_BUDGET*.json"
        ),
        "plot_path": result.get("plot_path"),
        "wall_clock_s": round(wall_s, 1),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    with open(args.report, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
