"""Mesh-engine evidence run: reshard save/restore round-trips + parity.

Exercises the PR 13 contract end to end on whatever backend is available
(CPU in CI — the committed ``BENCH_MESH_CPU.json`` is CORRECTNESS
evidence, not speed; the on-hardware MFU re-measure rides the next TPU
tunnel round, see ROADMAP):

  - **serial parity**: a shard_map sweep replica vs the serial
    ``DIBTrainer`` on the same key — must be bit-identical;
  - **reshard round-trips**: save a width-R sweep checkpoint mid-run,
    restore at R' in {R/2, 1, 2R}, continue training — matched members'
    full histories must be bit-identical to the uninterrupted width-R
    run (``parallel/elastic.py:restore_sweep_resharded``), with the
    save/restore wall-clocks reported per row.

Emits ONE bench-shaped JSON line (metric/value/unit; value =
``parity_failures``, gated at 0 by SLO.json's
``mesh_reshard_parity_failures_max`` — `telemetry check
BENCH_MESH_CPU.json` evaluates the rule directly) and registers a fleet
registry entry only under an explicit --runs-root/DIB_RUNS_ROOT.

    python scripts/bench_mesh.py --out BENCH_MESH_CPU.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

METRIC = "mesh_reshard_bench"

#: The width-R β grid every scenario shares, and the widths restored.
ENDS = (0.03, 0.1, 0.3, 1.0)
SHRINK = (0.1, 1.0)      # lanes 1, 3
CARVE = (0.3,)           # lane 2
GROW_EXTRA = (3.0, 10.0, 0.01, 0.05)
CHUNK = 4


def _setup():
    import jax

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)

    from dib_tpu.data import get_dataset
    from dib_tpu.models import DistributedIBModel
    from dib_tpu.train import TrainConfig

    bundle = get_dataset("boolean_circuit")
    model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(8,), integration_hidden=(16,),
        output_dim=1, embedding_dim=2,
    )
    config = TrainConfig(
        batch_size=64, beta_start=1e-3, beta_end=1.0,
        num_pretraining_epochs=2, num_annealing_epochs=6,
        steps_per_epoch=2, max_val_points=128,
    )
    return model, bundle, config


def _identical(rec_a, rec_b) -> bool:
    import numpy as np

    return (np.array_equal(rec_a.loss, rec_b.loss)
            and np.array_equal(rec_a.kl_per_feature, rec_b.kl_per_feature)
            and np.array_equal(rec_a.beta, rec_b.beta))


def main() -> int:
    parser = argparse.ArgumentParser(
        description="Mesh-engine reshard/parity evidence run "
                    "(docs/parallelism.md).")
    parser.add_argument("--out", default=None)
    parser.add_argument("--runs-root", default=None,
                        help="Fleet registry root; registration happens "
                             "ONLY when this (or DIB_RUNS_ROOT) is set.")
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    model, bundle, config = _setup()

    from dib_tpu.parallel import (
        BetaSweepTrainer,
        factor_devices,
        make_sweep_engine_mesh,
        restore_sweep_resharded,
    )
    from dib_tpu.train import CheckpointHook, DIBCheckpointer, DIBTrainer

    n_dev = len(jax.devices())
    width = len(ENDS)
    # the num_replicas-aware factoring: never a sweep axis wider than R
    n_sweep, _ = factor_devices(n_dev, num_replicas=width)

    def engine_mesh(r):
        sweep_axis, _ = factor_devices(n_dev, num_replicas=r)
        return make_sweep_engine_mesh(sweep_axis, 1)

    keys = jax.random.split(jax.random.key(0), width)
    rows: list[dict] = []

    # ---- serial parity: shard_map replica == DIBTrainer, bit for bit
    key = jax.random.key(7)
    t0 = time.time()
    serial = DIBTrainer(model, bundle, config)
    _, hist = serial.fit(key)
    sweep1 = BetaSweepTrainer(model, bundle, config, config.beta_start,
                              jnp.asarray([config.beta_end]),
                              mesh=make_sweep_engine_mesh(1, 1))
    _, recs1 = sweep1.fit(jnp.stack([key]))
    ok = (np.array_equal(np.asarray(recs1[0].loss), np.asarray(hist.loss))
          and np.array_equal(np.asarray(recs1[0].kl_per_feature),
                             np.asarray(hist.kl_per_feature)))
    rows.append({
        "scenario": "serial_parity", "engine": "shard_map",
        "saved_width": 1, "restored_width": 1, "bit_identical": bool(ok),
        "seconds": round(time.time() - t0, 3),
    })

    # ---- uninterrupted width-R baseline + mid-run checkpoint
    full = BetaSweepTrainer(model, bundle, config, 1e-3, jnp.asarray(ENDS),
                            mesh=engine_mesh(width))
    _, rec_full = full.fit(keys, hook_every=CHUNK)

    workdir = tempfile.mkdtemp(prefix="dib_bench_mesh_")
    ckpt_dir = os.path.join(workdir, "ckpt")
    saver = BetaSweepTrainer(model, bundle, config, 1e-3, jnp.asarray(ENDS),
                             mesh=engine_mesh(width))
    ckpt = DIBCheckpointer(ckpt_dir)
    t0 = time.time()
    # lint-ok(prng-reuse): the interrupted run MUST replay the baseline's
    # exact keys — bit-identical continuation is the thing being measured
    saver.fit(keys, num_epochs=CHUNK, hooks=[CheckpointHook(ckpt)],
              hook_every=CHUNK)
    ckpt.close()
    save_s = round(time.time() - t0, 3)

    lane_of = {float(np.float32(b)): i for i, b in enumerate(ENDS)}

    def round_trip(name, ends, new_keys=None, meshless=False):
        mesh = None if meshless else engine_mesh(len(ends))
        sweep = BetaSweepTrainer(model, bundle, config, 1e-3,
                                 jnp.asarray(ends), mesh=mesh)
        ck = DIBCheckpointer(ckpt_dir)
        t0 = time.time()
        try:
            states, histories, ks, info = restore_sweep_resharded(
                ck, sweep, chunk_size=CHUNK, new_member_keys=new_keys)
        finally:
            ck.close()
        restore_s = round(time.time() - t0, 3)
        done = int(np.max(np.asarray(jax.device_get(states.epoch))))
        _, recs = sweep.fit(ks, num_epochs=config.num_epochs - done,
                            states=states, histories=histories,
                            hook_every=CHUNK)
        matched = [i for i, b in enumerate(ends)
                   if float(np.float32(b)) in lane_of]
        ok = all(_identical(rec_full[lane_of[float(np.float32(ends[i]))]],
                            recs[i]) for i in matched)
        rows.append({
            "scenario": name, "engine": sweep.engine,
            "saved_width": info["saved_width"],
            "restored_width": info["restored_width"],
            "matched_members": len(matched),
            "new_members": len(info["new"]),
            "bit_identical": bool(ok),
            "save_s": save_s, "restore_s": restore_s,
            "seconds": restore_s,
        })

    round_trip("reshard_shrink", SHRINK)
    round_trip("reshard_carveout", CARVE, meshless=True)
    round_trip("reshard_grow", ENDS + GROW_EXTRA,
               new_keys=jax.random.split(jax.random.key(99),
                                         len(GROW_EXTRA)))

    failures = sum(1 for r in rows if not r["bit_identical"])
    record = {
        "metric": METRIC,
        "value": failures,
        "unit": "parity_failures",
        "parity_failures": failures,
        "all_parity_ok": failures == 0,
        "detail": "shard_map sweep engine vs serial trainer + "
                  "reshard-on-restore round-trips (width "
                  f"{width} -> {{{len(SHRINK)}, {len(CARVE)}, "
                  f"{width + len(GROW_EXTRA)}}}); bit-identity evidence, "
                  "not speed — CPU",
        "device_kind": jax.devices()[0].device_kind,
        "device_platform": jax.devices()[0].platform,
        "num_devices": n_dev,
        "mesh_axes": {"sweep": n_sweep, "data": 1},
        "rows": rows,
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    line = json.dumps(record)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")

    from dib_tpu.telemetry.registry import register_drill_record

    entry = register_drill_record(record, root=args.runs_root, extra={
        "parity_failures": failures,
    })
    if entry is not None:
        print("bench_mesh: registered in the fleet registry",
              file=sys.stderr)
    return 0 if failures == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
