"""The REAL north-star run: 8 replicas x 25,000 steps, instrumented, measured.

VERDICT round 1, item 2 / round 2, item 1: ``bench.py`` projects the
north-star wall-clock from a short measured chunk; this script runs the
complete sweep — the full set-transformer configuration (amorphous notebook
cell 8) over a grid of beta endpoints with the north star's instrumentation
enabled:

  - compression-scheme pulls from device at each beta checkpoint for every
    replica (the ``SaveCompressionMatricesCallback`` equivalent the
    BASELINE.json north-star text names; reference ``models.py:152-186``),
  - per-replica MI sandwich bounds at the same cadence,
  - per-replica info-plane PNGs at the end,

with wall-clock measured end to end (init + compile + train + measurement
hooks) and a committed run report (``NORTHSTAR_RUN.json``).

Instrumentation design (round 3): the sweep-native hooks
(``dib_tpu/parallel/sweep_hooks.py``) measure ALL replicas in one dispatch
per checkpoint, and compression schemes are SAVED during the run but
RASTERIZED after it — matplotlib is presentation, not measurement, and on a
1-core host it would otherwise dominate the benchmark. The headline
``value`` is the instrumented sweep wall-clock (everything up to and
including the final history fetch); PNG rendering time is reported
separately as ``render_s`` and included in ``total_wall_clock_s``.

Run on the TPU (ambient env, ALONE — no concurrent device users):

    python scripts/northstar_run.py [--outdir northstar_out] [--steps 25000]

Environment: DIB_ATTN_SCORE_DTYPE=bfloat16 selects the measured-faster
attention-score variant (see dib_tpu/parallel/context.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BASELINE_MINUTES = 10.0


def watchdog_main(args) -> int:
    """Supervised north star: the same command re-launched as a worker under
    ``dib_tpu.train.watchdog.supervise``. A chunk that stalls past
    3x the trailing-median chunk wall-clock gets its worker SIGKILLed and
    relaunched; the worker resumes bit-identically from its chunk-boundary
    Orbax checkpoint. The final report is the worker's, augmented with a
    ``watchdog`` section and the headline ``value`` replaced by the
    END-TO-END supervised wall-clock — kills, restarts, re-compiles and
    re-done chunks all count against the 10-minute target."""
    from dib_tpu.train.watchdog import WatchdogConfig, supervise_self

    cfg = WatchdogConfig(first_beat_timeout_s=args.watchdog_first_timeout_s,
                         floor_s=args.watchdog_floor_s)
    # Supervisor-side mitigation events append to the same events.jsonl the
    # worker writes (O_APPEND: no interleaving); the supervisor itself never
    # initializes a backend, hence the explicit process index.
    # Pinned run id: supervisor mitigations + every worker relaunch are
    # ONE run for --run-id scoping (see cli._watchdog_main).
    from dib_tpu.telemetry import open_writer, shared_run_id

    run_id = shared_run_id()
    os.environ["DIB_TELEMETRY_RUN_ID"] = run_id
    telemetry = open_writer(args.telemetry_dir or None, args.outdir,
                            run_id=run_id, process_index=0,
                            tags={"src": "supervisor"})
    t0 = time.time()
    result = supervise_self(
        [sys.executable, os.path.abspath(__file__)], sys.argv[1:],
        outdir=args.outdir,
        watchdog_flag="--watchdog",
        heartbeat_flag="--heartbeat",
        checkpoint_flag="--checkpoint-dir",
        heartbeat=args.heartbeat,
        checkpoint_dir=args.checkpoint_dir,
        config=cfg,
        telemetry=telemetry,
        # stream-based liveness (docs/observability.md): "stalled" means
        # the same thing here, in `telemetry tail`, and in the drills
        events_path=telemetry.path if telemetry is not None else None,
    )
    telemetry.close()
    total_s = time.time() - t0
    try:
        # a report predating this supervised run is some EARLIER run's
        # artifact, not the worker's — never splice metrics into it
        if os.path.getmtime(args.report) < t0:
            raise OSError("stale report")
        with open(args.report) as f:
            report = json.load(f)
    except (OSError, json.JSONDecodeError):
        report = {"metric": "amorphous_set_transformer_beta_sweep_measured",
                  "unit": "minutes", "error": "worker never wrote a report"}
    report["single_process_minutes"] = report.get("value")
    report["value"] = round(total_s / 60.0, 3)
    report["vs_baseline"] = round(total_s / 60.0 / BASELINE_MINUTES, 4)
    report["watchdog"] = {
        "enabled": True,
        "launches": result["launches"],
        "mitigations": result["mitigations"],
        "supervised_wall_s": round(total_s, 1),
        "worker_returncode": result["returncode"],
        "policy": {"k": cfg.k, "floor_s": cfg.floor_s,
                   "first_beat_timeout_s": cfg.first_beat_timeout_s},
    }
    with open(args.report, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    print(json.dumps({"value": report["value"],
                      "launches": result["launches"],
                      "mitigations": len(result["mitigations"]),
                      "returncode": result["returncode"]}))
    return 0 if result["returncode"] == 0 else 1


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--outdir", default="northstar_out")
    parser.add_argument("--steps", type=int, default=25_000)
    parser.add_argument("--replicas", type=int, default=8)
    parser.add_argument("--steps-per-epoch", type=int, default=50)
    parser.add_argument("--chunk-epochs", type=int, default=25,
                        help="beta-checkpoint cadence in epochs "
                             "(25 x 50 = every 1250 steps -> 20 checkpoints)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--report", default="NORTHSTAR_RUN.json")
    parser.add_argument("--no-render", action="store_true",
                        help="skip post-run PNG rasterization")
    parser.add_argument("--no-overlap", action="store_true",
                        help="serialize the MI-bound measurement at each "
                             "beta checkpoint instead of overlapping it "
                             "with the next training chunk (A/B knob; "
                             "overlap is the default — "
                             "docs/performance.md)")
    parser.add_argument("--compile-cache", default="",
                        help="persistent XLA compilation cache dir ('' = off; "
                             "compile_s in the report says which applied)")
    parser.add_argument("--checkpoint-dir", default="",
                        help="arm chunk-boundary Orbax checkpointing; an "
                             "existing checkpoint there RESUMES the run "
                             "(bit-identical continuation)")
    parser.add_argument("--heartbeat", default="",
                        help="write a chunk-boundary heartbeat JSON here "
                             "(read by the --watchdog supervisor)")
    parser.add_argument("--watchdog", action="store_true",
                        help="supervise the run: relaunch this command as a "
                             "worker (checkpoint+heartbeat under --outdir), "
                             "SIGKILL it when a chunk stalls past 3x the "
                             "trailing-median chunk time, and resume it "
                             "from its checkpoint — every run finishes even "
                             "on a stalling device (VERDICT r4 item 1)")
    parser.add_argument("--watchdog-floor-s", type=float, default=45.0)
    parser.add_argument("--watchdog-first-timeout-s", type=float, default=600.0)
    parser.add_argument("--telemetry-dir", default="",
                        help="events.jsonl directory (default: --outdir; "
                             "see docs/observability.md)")
    args = parser.parse_args()

    if args.watchdog:
        return watchdog_main(args)

    import jax

    from dib_tpu.utils.compile_cache import enable_persistent_cache

    # '' keeps the historical explicit-opt-in semantics of this flag (maps
    # to "off" in the shared helper; the report still says "cold")
    status = enable_persistent_cache(args.compile_cache or "")
    compile_cache = "cold" if status == "off" else status

    import numpy as np

    from dib_tpu.parallel import SweepCompressionHook, SweepInfoPerFeatureHook
    from dib_tpu.parallel.context import _dense_score_dtype
    from dib_tpu.workloads.amorphous import (
        AmorphousWorkloadConfig,
        run_amorphous_sweep,
    )

    devices = jax.devices()
    print(f"devices: {devices}", file=sys.stderr)
    config = AmorphousWorkloadConfig(num_steps=args.steps)

    # Per-checkpoint instrumentation, one dispatch for the whole sweep:
    # compression-scheme pulls (feature 0 only: the per-particle model
    # shares ONE encoder across particle slots, so other slots' schemes are
    # identical) + MI sandwich bounds for every replica.
    # Worker-side event stream (docs/observability.md): run_start manifest,
    # one ``chunk`` event per beta checkpoint, ``mi_bounds`` per checkpoint
    # measurement, ``run_end``. Under --watchdog the supervisor appends its
    # ``mitigation`` events to the SAME file (O_APPEND, no interleaving).
    from dib_tpu.telemetry import (
        ChunkPhaseHooks,
        SpannedHook,
        Tracer,
        open_writer,
        runtime_manifest,
        shared_run_id,
        use_tracer,
    )

    # always on: '' (the flag default) falls through to the run's outdir;
    # under --watchdog, shared_run_id() adopts the supervisor's pinned id
    telemetry = open_writer(args.telemetry_dir or None, args.outdir,
                            run_id=shared_run_id())
    # the ONE definition of the sweep grid: the fit call and every
    # telemetry step count derive from these
    num_repeats = max(args.replicas // 8, 1)
    beta_ends = np.logspace(-2, 0, min(args.replicas, 8))
    num_replicas = num_repeats * len(beta_ends)
    telemetry.run_start(runtime_manifest(
        config=config,
        extra={"workload": "northstar_amorphous_sweep", "seed": args.seed,
               "replicas": num_replicas, "compile_cache": compile_cache,
               "score_dtype": _dense_score_dtype().__name__},
    ))

    resuming = bool(args.checkpoint_dir)
    comp = SweepCompressionHook(args.outdir, features=(0,), resume=resuming)
    # overlap (default): each checkpoint's measurement is dispatched on a
    # params snapshot and collected at the NEXT checkpoint, riding the
    # async queue under the following 1250-step chunk — the mi_bounds
    # span stops serializing checkpoint boundaries (docs/performance.md)
    info = SweepInfoPerFeatureHook(
        config.mi_eval_batch_size, config.mi_eval_batches,
        persist=os.path.join(args.outdir, "mi_bounds") if resuming else None,
        telemetry=telemetry,
        overlap=not args.no_overlap,
    )

    # Per-checkpoint chunk-vs-instrumentation wall clocks (round 4: the
    # ensemble showed a 1.65x run-to-run spread on an idle host — this
    # records WHERE a slow run loses the time). ``phases.pre`` runs FIRST
    # and blocks on the chunk's outputs, so its interval is the 1250-step
    # train chunk; ``phases.post`` runs LAST, so its interval is the
    # measurement/pull work of the checkpoint. The sweep's chunk events
    # count every replica's steps (the bench.py steps/s convention).
    # a resumed run's restore epoch is unknown until the sweep returns, so
    # its first chunk's step count is unattributable — timed but not emitted.
    # The tracer mirrors each chunk/instrumentation interval as a `span`
    # event and parents the per-hook spans below, so the checkpoint cycle
    # shows up whole in `telemetry report`'s flame breakdown.
    tracer = Tracer(telemetry)
    phases = ChunkPhaseHooks(
        telemetry=telemetry, tracer=tracer,
        steps_per_epoch=args.steps_per_epoch * num_replicas,
        baseline_known=not resuming,
    )

    hooks = [phases.pre,
             SpannedHook("compression_pull", comp),
             # overlapped measurement emits its OWN `mi_bounds` spans
             # (overlapped=true, exposed-wait seconds) at collection time;
             # wrapping the dispatch in a second same-named span would
             # double-count the boundary
             (SpannedHook("mi_bounds", info) if args.no_overlap else info),
             phases.post]
    if args.heartbeat:
        from dib_tpu.train.watchdog import HeartbeatHook

        # first: it blocks on the chunk itself, so the supervisor's
        # inter-beat intervals are true chunk wall-clocks
        hooks.insert(0, HeartbeatHook(args.heartbeat))

    t0 = time.time()
    phases.start()
    with use_tracer(tracer):
        result = run_amorphous_sweep(
            key=args.seed,
            config=config,
            num_repeats=num_repeats,
            beta_ends=beta_ends,
            outdir=args.outdir,
            steps_per_epoch=args.steps_per_epoch,
            chunk_epochs=args.chunk_epochs,
            hooks=hooks,
            model_overrides={"compute_dtype": "bfloat16"},
            checkpoint_dir=args.checkpoint_dir or None,
        )
    # Everything that constitutes the MEASURED run is done: init, compile,
    # 25k steps x R, per-checkpoint device measurements + host pulls, final
    # history fetch, info-plane PNGs (run_amorphous_sweep renders those
    # inline; they are 8 small figures).
    measured_s = time.time() - t0

    render_s = 0.0
    num_scheme_pngs = 0
    if not args.no_render:
        t1 = time.time()
        from dib_tpu.data import get_dataset

        bundle = get_dataset(
            "amorphous_particles",
            number_particles_to_use=config.number_particles,
        )
        num_scheme_pngs = len(comp.render(bundle))
        render_s = time.time() - t1
    total_s = time.time() - t0

    records = result["records"]
    finite = all(
        np.isfinite(rec.kl_per_feature).all() and np.isfinite(rec.loss).all()
        for rec in records
    )
    bounds_finite = all(
        np.isfinite(rec["bounds"]).all() for rec in info.records
    )
    report = {
        "metric": "amorphous_set_transformer_beta_sweep_measured",
        "value": round(measured_s / 60.0, 3),
        "unit": "minutes",
        "vs_baseline": round(measured_s / 60.0 / BASELINE_MINUTES, 4),
        "sweep_wall_clock_s": round(result["wall_clock_s"], 1),
        "measured_wall_clock_s": round(measured_s, 1),
        "render_s": round(render_s, 1),
        "total_wall_clock_s": round(total_s, 1),
        "compile_cache": compile_cache,
        # a resumed worker only re-measures its own (post-restore) chunks
        "resumed_from_epoch": result.get("resumed_from_epoch"),
        # first chunk_s entry includes init+compile; the rest are steady-state
        "checkpoint_chunk_s": [
            round(s, 2) for s in phases.timer.intervals.get("chunk", [])
        ],
        "checkpoint_instrumentation_s": [
            round(s, 2)
            for s in phases.timer.intervals.get("instrumentation", [])
        ],
        "events_path": telemetry.path,
        "replicas": len(records),
        "steps_per_replica": args.steps,
        "steps_per_epoch": args.steps_per_epoch,
        "beta_checkpoints": len(info.epochs),
        "mi_bounds_per_checkpoint": int(np.prod(info.records[0]["bounds"].shape[:-1]))
        if info.records else 0,
        "compression_scheme_pulls": len(comp.saved),
        "scheme_pngs_rendered": num_scheme_pngs,
        "all_finite": bool(finite and bounds_finite),
        # the EFFECTIVE score dtype (context.py's default applies when the
        # env is unset), not the raw env string
        "score_dtype": _dense_score_dtype().__name__,
        "device_kind": devices[0].device_kind,
        "entropy_y_bits": round(float(result["entropy_y_bits"]), 4),
        "final_total_kl_bits_per_replica": [
            round(float(rec.to_bits().total_kl[-1]), 4) for rec in records
        ],
        "final_val_loss_bits_per_replica": [
            round(float(rec.to_bits().val_loss[-1]), 4) for rec in records
        ],
        "final_mi_lower_bits_mean_per_replica": [
            round(float(info.bounds_bits(r)[-1, :, 0].mean()), 4)
            for r in range(len(records))
        ] if info.records else [],
        "info_plane_paths": result["info_plane_paths"],
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    with open(args.report, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    # MI-bound trajectories are part of the scientific product: save them.
    np.savez(
        os.path.join(args.outdir, "mi_bounds.npz"),
        epochs=info.epochs,
        bounds_nats=np.stack([rec["bounds"] for rec in info.records])
        if info.records else np.zeros((0,)),
    )
    telemetry.run_end(
        status="ok" if (finite and bounds_finite) else "non_finite",
        minutes=report["value"],
    )
    telemetry.close()
    print(json.dumps(report))
    if not (finite and bounds_finite):
        print("NON-FINITE VALUES IN RUN", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except SystemExit:
        raise
    except BaseException as exc:
        # crash-path terminal record for the run's event stream
        # (docs/observability.md): never end on a dangling chunk
        from dib_tpu.telemetry import finalize_crashed

        finalize_crashed(exc, log=lambda msg: print(msg, file=sys.stderr))
        raise
