"""The REAL north-star run: 8 replicas x 25,000 steps, instrumented, measured.

VERDICT round 1, item 2: ``bench.py`` projects the north-star wall-clock from
a short measured chunk; this script runs the complete sweep — the full
set-transformer configuration (amorphous notebook cell 8) over a grid of
beta endpoints with the north star's instrumentation enabled:

  - compression-scheme pulls from device at each beta checkpoint for every
    replica (the ``SaveCompressionMatricesCallback`` equivalent the
    BASELINE.json north-star text names; reference ``models.py:152-186``),
  - per-replica MI sandwich bounds at the same cadence,
  - per-replica info-plane PNGs at the end,

with wall-clock measured end to end (init + compile + train + hooks) and a
committed run report (``NORTHSTAR_RUN.json``).

Run on the TPU (ambient env, ALONE — no concurrent device users):

    python scripts/northstar_run.py [--outdir northstar_out] [--steps 25000]

Environment: DIB_ATTN_SCORE_DTYPE=bfloat16 selects the measured-faster
attention-score variant (see dib_tpu/parallel/context.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BASELINE_MINUTES = 10.0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--outdir", default="northstar_out")
    parser.add_argument("--steps", type=int, default=25_000)
    parser.add_argument("--replicas", type=int, default=8)
    parser.add_argument("--steps-per-epoch", type=int, default=50)
    parser.add_argument("--chunk-epochs", type=int, default=25,
                        help="beta-checkpoint cadence in epochs "
                             "(25 x 50 = every 1250 steps -> 20 checkpoints)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--report", default="NORTHSTAR_RUN.json")
    args = parser.parse_args()

    import jax
    import numpy as np

    from dib_tpu.parallel.sweep import PerReplicaHook
    from dib_tpu.train.hooks import CompressionMatrixHook, InfoPerFeatureHook
    from dib_tpu.workloads.amorphous import (
        AmorphousWorkloadConfig,
        run_amorphous_sweep,
    )

    devices = jax.devices()
    print(f"devices: {devices}", file=sys.stderr)
    config = AmorphousWorkloadConfig(num_steps=args.steps)

    # Per-replica instrumentation at every chunk boundary (= beta checkpoint).
    # CompressionMatrixHook pulls (mu, logvar) compression schemes from
    # device; InfoPerFeatureHook runs the sandwich bounds on validation data.
    info_hooks: dict[int, InfoPerFeatureHook] = {}

    def make_hooks(r: int):
        # feature 0 only: the per-particle model shares ONE encoder across
        # all particle slots, so the other slots' schemes are identical
        comp = CompressionMatrixHook(
            os.path.join(args.outdir, f"replica{r}", "compression"),
            features=(0,),
        )
        info_hooks[r] = InfoPerFeatureHook(
            config.mi_eval_batch_size, config.mi_eval_batches
        )
        info = info_hooks[r]

        def both(trainer, state, epoch):
            comp(trainer, state, epoch)
            info(trainer, state, epoch)

        return both

    t0 = time.time()
    result = run_amorphous_sweep(
        key=args.seed,
        config=config,
        num_repeats=max(args.replicas // 8, 1),
        beta_ends=np.logspace(-2, 0, min(args.replicas, 8)),
        outdir=args.outdir,
        steps_per_epoch=args.steps_per_epoch,
        chunk_epochs=args.chunk_epochs,
        hooks=[PerReplicaHook(make_hooks)],
        model_overrides={"compute_dtype": "bfloat16"},
    )
    total_s = time.time() - t0

    records = result["records"]
    finite = all(
        np.isfinite(rec.kl_per_feature).all() and np.isfinite(rec.loss).all()
        for rec in records
    )
    report = {
        "metric": "amorphous_set_transformer_beta_sweep_measured",
        "value": round(total_s / 60.0, 3),
        "unit": "minutes",
        "vs_baseline": round(total_s / 60.0 / BASELINE_MINUTES, 4),
        "sweep_wall_clock_s": round(result["wall_clock_s"], 1),
        "total_wall_clock_s": round(total_s, 1),
        "replicas": len(records),
        "steps_per_replica": args.steps,
        "steps_per_epoch": args.steps_per_epoch,
        "beta_checkpoints": len(next(iter(info_hooks.values())).epochs)
        if info_hooks else 0,
        "all_finite": bool(finite),
        "score_dtype": os.environ.get("DIB_ATTN_SCORE_DTYPE", "float32"),
        "device_kind": devices[0].device_kind,
        "entropy_y_bits": round(float(result["entropy_y_bits"]), 4),
        "final_total_kl_bits_per_replica": [
            round(float(rec.to_bits().total_kl[-1]), 4) for rec in records
        ],
        "final_val_loss_bits_per_replica": [
            round(float(rec.to_bits().val_loss[-1]), 4) for rec in records
        ],
        "info_plane_paths": result["info_plane_paths"],
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    with open(args.report, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    print(json.dumps(report))
    if not finite:
        print("NON-FINITE VALUES IN RUN", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
