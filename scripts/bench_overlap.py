"""Measure the overlapped-MI-measurement pipeline on a real telemetry run.

Runs the boolean workload's chunked fit (the inline overlap site:
``BooleanTrainer._fit_loop`` dispatches each boundary's channel-MI
measurement on a params snapshot and collects it at the next boundary)
with the event stream on, then reports the ``overlap`` rollup the stream
carries: how much of the measurement's dispatch→ready window the
boundaries actually waited for (``exposed_frac``), and the span-hotspots
table showing ``mi_bounds`` charged only its exposed wait.

Emits ONE bench-shaped JSON line (metric/value/unit; value =
``exposed_frac``, lower is better — 1.0 would mean the measurement
serializes its boundary again, which `telemetry compare` gates via
``overlap_exposed_frac``). Honest-scope note: on CPU this evidences the
MECHANISM (spans, rollup, bit-identical numerics are pinned by
tests/test_overlap.py); the north-star TPU MFU delta needs a hardware
round (`python bench.py` + scripts/northstar_run.py, overlap on by
default).

    python scripts/bench_overlap.py --out BENCH_OVERLAP_CPU.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

METRIC = "boolean_mi_overlap"


def main() -> int:
    parser = argparse.ArgumentParser(
        description="Overlapped-measurement evidence run "
                    "(docs/performance.md).")
    parser.add_argument("--steps", type=int, default=2000)
    parser.add_argument("--mi-every", type=int, default=250)
    parser.add_argument("--out", default=None)
    args = parser.parse_args()

    import jax

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)

    from dib_tpu.data import get_dataset
    from dib_tpu.telemetry import EventWriter, runtime_manifest, summarize
    from dib_tpu.workloads.boolean import BooleanTrainer, BooleanWorkloadConfig

    bundle = get_dataset("boolean_circuit", number_inputs=10, seed=0)
    config = BooleanWorkloadConfig(num_steps=args.steps,
                                   mi_every=args.mi_every)
    trainer = BooleanTrainer(bundle, config)
    telemetry_dir = tempfile.mkdtemp(prefix="bench_overlap_")
    writer = EventWriter(telemetry_dir)
    writer.run_start(runtime_manifest(
        config=config, extra={"bench": METRIC}))
    t0 = time.time()
    trainer.fit(jax.random.key(0), telemetry=writer)
    wall_s = time.time() - t0
    writer.run_end(status="ok")
    writer.close()
    summary = summarize(telemetry_dir, run_id=writer.run_id)
    overlap = summary.get("overlap") or {}
    record = {
        "metric": METRIC,
        "value": overlap.get("exposed_frac"),
        "unit": "exposed_frac",
        "detail": "fraction of the MI measurements' dispatch→ready window "
                  "the chunk boundaries actually blocked on (1.0 = the "
                  "measurement serializes boundaries; gated by `telemetry "
                  "compare` overlap_exposed_frac)",
        "num_steps": args.steps,
        "mi_checkpoints": summary.get("mi_checkpoints"),
        "wall_clock_s": round(wall_s, 2),
        "steps_per_s": summary.get("steps_per_s"),
        "overlap": overlap,
        "span_hotspots": summary.get("span_hotspots"),
        "device_kind": summary.get("device_kind"),
        "device_platform": summary.get("device_platform"),
        "telemetry": summary,
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    line = json.dumps(record)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    root = os.environ.get("DIB_RUNS_ROOT")
    if root:
        from dib_tpu.telemetry.registry import RunRegistry, bench_entry

        RunRegistry(root).append(bench_entry(record))
    import shutil

    shutil.rmtree(telemetry_dir, ignore_errors=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
