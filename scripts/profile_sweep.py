"""Profile the steady-state north-star sweep step (VERDICT round 2, item 2).

Captures a ``jax.profiler`` device trace of a few measured chunks of the
benchmark configuration (the exact program ``bench.py`` times) and prints a
wall-clock + throughput + roofline summary so the MFU gap to peak can be
ATTRIBUTED, not assumed. The trace directory can be inspected with
TensorBoard / xprof offline; the printed summary is self-contained for
``docs/performance.md``.

Run on the TPU (ambient env, ALONE):

    python scripts/profile_sweep.py [--outdir /tmp/sweep_trace]

Environment: DIB_ATTN_SCORE_DTYPE=bfloat16 profiles the bf16-scores variant.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--outdir", default="/tmp/sweep_trace")
    parser.add_argument("--replicas", type=int, default=8)
    parser.add_argument("--epochs", type=int, default=6)
    parser.add_argument("--steps-per-epoch", type=int, default=50)
    parser.add_argument("--trace", action="store_true", default=True)
    parser.add_argument("--no-trace", dest="trace", action="store_false",
                        help="timing-only (profiler unsupported on backend)")
    args = parser.parse_args()

    import jax
    import numpy as np

    import bench
    from dib_tpu.data import get_dataset
    from dib_tpu.models import PerParticleDIBModel
    from dib_tpu.parallel import BetaSweepTrainer
    from dib_tpu.train import TrainConfig

    devices = jax.devices()
    print(f"devices: {devices}", file=sys.stderr)

    bundle = get_dataset("amorphous_particles", num_synthetic_neighborhoods=2048)
    model = PerParticleDIBModel(num_particles=50, compute_dtype="bfloat16")
    config = TrainConfig(
        learning_rate=1e-4,
        batch_size=32,
        num_pretraining_epochs=0,
        num_annealing_epochs=args.epochs,
        steps_per_epoch=args.steps_per_epoch,
        max_val_points=256,
        warmup_steps=500,
    )
    beta_ends = np.logspace(-2, 0, args.replicas)
    sweep = BetaSweepTrainer(model, bundle, config, 2e-6, beta_ends)

    init_keys = jax.random.split(jax.random.key(0), args.replicas)
    states, histories = sweep.init(init_keys)
    # compile + warm
    t0 = time.time()
    states, histories = sweep.run_chunk(
        states, histories, jax.random.split(jax.random.key(1), args.replicas),
        args.epochs,
    )
    jax.block_until_ready(states.params)
    compile_s = time.time() - t0

    def timed_chunk(seed):
        keys = jax.random.split(jax.random.key(seed), args.replicas)
        nonlocal states, histories
        t = time.time()
        states, histories = sweep.run_chunk(states, histories, keys, args.epochs)
        jax.block_until_ready(states.params)
        return time.time() - t

    # steady-state timing, then one traced repetition of the same chunk
    plain_s = [timed_chunk(2), timed_chunk(3)]
    traced_s = None
    trace_error = None
    if args.trace:
        try:
            with jax.profiler.trace(args.outdir):
                traced_s = timed_chunk(4)
        except Exception as e:   # axon/tunnel backends may lack profiler RPCs
            trace_error = f"{type(e).__name__}: {e}"

    sweep_steps = args.epochs * args.steps_per_epoch * args.replicas
    best_s = min(plain_s)
    steps_per_s = sweep_steps / best_s
    model_flops = bench.analytic_model_flops_per_step(model, config.batch_size)
    peak = bench.peak_tflops_for(devices[0].device_kind)  # None if unknown
    achieved = model_flops * steps_per_s / 1e12

    # Roofline attribution inputs: bytes moved per step (params + opt state
    # + activations are the candidates; params dominate at batch 32).
    n_params = sum(
        int(np.prod(p.shape)) for p in jax.tree.leaves(states.params)
    ) // args.replicas
    # Steady state per replica step reads params, writes grads+opt updates:
    # >= 3 accesses x 4 bytes (f32 master params).
    param_bytes_per_step = 3 * 4 * n_params
    # Public per-chip HBM bandwidth (GB/s); ORDER matters (v5p before v5).
    hbm_peaks = (("v6", 1640.0), ("v5p", 2765.0), ("v5", 819.0),
                 ("v4", 1228.0), ("v3", 900.0), ("v2", 700.0))
    kind = devices[0].device_kind.lower()
    hbm_gbps = next((gbps for key, gbps in hbm_peaks if key in kind), None)

    summary = {
        "device_kind": devices[0].device_kind,
        "score_dtype": __import__(
            "dib_tpu.parallel.context", fromlist=["_dense_score_dtype"]
        )._dense_score_dtype().__name__,
        "compile_s": round(compile_s, 1),
        "chunk_s": [round(s, 3) for s in plain_s],
        "traced_chunk_s": round(traced_s, 3) if traced_s else None,
        "trace_outdir": args.outdir if traced_s else None,
        "trace_error": trace_error,
        "sweep_steps_per_chunk": sweep_steps,
        "steps_per_s": round(steps_per_s, 1),
        "model_flops_per_step": model_flops,
        "achieved_tflops": round(achieved, 2),
        "peak_tflops": peak,                # None on unlisted device kinds —
        "mfu": (round(achieved / peak, 4)   # NaN would break strict JSON
                if peak else None),
        "params_per_replica": n_params,
        "param_traffic_gb_per_s": round(
            param_bytes_per_step * steps_per_s / 1e9, 2
        ),
        "hbm_peak_gb_per_s": hbm_gbps,
        "matmul_shapes_note": (
            "per replica step the largest matmuls are [1600, 32] x [32, 1536]"
            " (QKV) and [12*32, 50, 50] x [50, 128] (attention) — M/N/K far"
            " below the 128x128 MXU tile in the contracted dims, so the"
            " systolic array is mostly idle by construction at batch 32"
        ),
    }
    print(json.dumps(summary, indent=1))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
