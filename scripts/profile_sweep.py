"""Profile the steady-state north-star sweep step — now a thin wrapper.

The timing/roofline arithmetic this script used to carry lives in the
telemetry layer (``dib_tpu/telemetry/trace.py`` + ``xla_stats.py``); what
remains here is orchestration:

  - build the exact benchmark configuration (``bench.py``'s program);
  - run warm + measured chunks inside named spans — the SAME names appear
    in the captured ``jax.profiler`` device trace via ``TraceAnnotation``,
    so the host spans and the device timeline join by name;
  - cost-analyze the compiled chunk program
    (``lower().compile().cost_analysis()``) onto a ``compile`` event;
  - append everything to ``<outdir>/events.jsonl`` so
    ``python -m dib_tpu telemetry report <outdir>`` renders the profile
    run (span breakdown + roofline utilization), and print the rolled-up
    summary JSON.

Run on the TPU (ambient env, ALONE):

    python scripts/profile_sweep.py [--outdir /tmp/sweep_trace]

Environment: DIB_ATTN_SCORE_DTYPE=bfloat16 profiles the bf16-scores variant.
Per-shape matmul ceilings live in ``scripts/roofline.py``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--outdir", default="/tmp/sweep_trace")
    parser.add_argument("--replicas", type=int, default=8)
    parser.add_argument("--epochs", type=int, default=6)
    parser.add_argument("--steps-per-epoch", type=int, default=50)
    parser.add_argument("--trace", action="store_true", default=True)
    parser.add_argument("--no-trace", dest="trace", action="store_false",
                        help="timing-only (profiler unsupported on backend)")
    args = parser.parse_args()

    import jax
    import numpy as np

    import bench
    from dib_tpu.data import get_dataset
    from dib_tpu.models import PerParticleDIBModel
    from dib_tpu.parallel import BetaSweepTrainer
    from dib_tpu.telemetry import EventWriter, Tracer, runtime_manifest
    from dib_tpu.telemetry import xla_stats
    from dib_tpu.train import TrainConfig
    from dib_tpu.utils.profiling import device_trace

    devices = jax.devices()
    print(f"devices: {devices}", file=sys.stderr)

    bundle = get_dataset("amorphous_particles", num_synthetic_neighborhoods=2048)
    model = PerParticleDIBModel(num_particles=50, compute_dtype="bfloat16")
    config = TrainConfig(
        learning_rate=1e-4,
        batch_size=32,
        num_pretraining_epochs=0,
        num_annealing_epochs=args.epochs,
        steps_per_epoch=args.steps_per_epoch,
        max_val_points=256,
        warmup_steps=500,
    )
    beta_ends = np.logspace(-2, 0, args.replicas)
    sweep = BetaSweepTrainer(model, bundle, config, 2e-6, beta_ends)

    telemetry = EventWriter(args.outdir)
    tracer = Tracer(telemetry)
    telemetry.run_start(runtime_manifest(
        config=config,
        extra={"profile": "northstar_sweep_chunk",
               "replicas": args.replicas},
    ))

    init_keys = jax.random.split(jax.random.key(0), args.replicas)
    with tracer.span("init") as ph:
        states, histories = sweep.init(init_keys)
        ph.block_on(states.params)

    # FLOPs/bytes of the chunk program, recorded before it first executes
    warm_keys = jax.random.split(jax.random.key(1), args.replicas)
    cost = xla_stats.record_compile_event(
        telemetry, "sweep_chunk", type(sweep).run_chunk,
        (sweep, states, histories, warm_keys, args.epochs),
    )
    with tracer.span("compile_and_warm") as ph:
        states, histories = sweep.run_chunk(
            states, histories, warm_keys, args.epochs)
        ph.block_on(states.params)

    def timed_chunk(seed, name):
        keys = jax.random.split(jax.random.key(seed), args.replicas)
        nonlocal states, histories
        with tracer.span(name) as ph:
            states, histories = sweep.run_chunk(
                states, histories, keys, args.epochs)
            ph.block_on(states.params)
        return tracer.timer.intervals[name][-1]

    # steady-state timing, then one traced repetition of the same chunk
    plain_s = [timed_chunk(2, "sweep_chunk"), timed_chunk(3, "sweep_chunk")]
    traced_s = None
    trace_error = None
    if args.trace:
        try:
            with device_trace(args.outdir):
                traced_s = timed_chunk(4, "sweep_chunk_traced")
        except Exception as e:   # axon/tunnel backends may lack profiler RPCs
            trace_error = f"{type(e).__name__}: {e}"

    sweep_steps = args.epochs * args.steps_per_epoch * args.replicas
    best_s = min(plain_s)
    steps_per_s = sweep_steps / best_s
    model_flops = bench.analytic_model_flops_per_step(model, config.batch_size)
    peaks = xla_stats.backend_peaks(devices[0].device_kind)
    analytic = xla_stats.achieved(
        best_s, flops=model_flops * sweep_steps, peaks=peaks)
    whole_program = xla_stats.achieved(
        best_s,
        flops=(cost or {}).get("flops"),
        bytes_accessed=(cost or {}).get("bytes_accessed"),
        peaks=peaks,
    )

    summary = {
        "device_kind": devices[0].device_kind,
        "score_dtype": __import__(
            "dib_tpu.parallel.context", fromlist=["_dense_score_dtype"]
        )._dense_score_dtype().__name__,
        "compile_and_warm_s": round(
            tracer.timer.totals["compile_and_warm"], 1),
        "chunk_s": [round(s, 3) for s in plain_s],
        "traced_chunk_s": round(traced_s, 3) if traced_s else None,
        "trace_outdir": args.outdir if traced_s else None,
        "trace_error": trace_error,
        "sweep_steps_per_chunk": sweep_steps,
        "steps_per_s": round(steps_per_s, 1),
        "model_flops_per_step": model_flops,
        # conventional MFU inputs (analytic model matmul FLOPs)
        "achieved_tflops": round(
            analytic.get("achieved_gflops", 0.0) / 1e3, 2),
        "peak_tflops": (peaks or {}).get("bf16_tflops"),
        "mfu": (round(analytic["flops_frac_of_peak"], 4)
                if "flops_frac_of_peak" in analytic else None),
        # whole-program XLA cost_analysis view (see docs/performance.md for
        # why this is reported separately, never as the headline MFU)
        "xla_cost_analysis": cost,
        "xla_achieved": {k: round(v, 4) for k, v in whole_program.items()},
        "hbm_peak_gb_per_s": (peaks or {}).get("hbm_gbps"),
        "events_path": telemetry.path,
        "note": ("roofline per-shape ceilings: scripts/roofline.py; render "
                 "this run: python -m dib_tpu telemetry report "
                 + args.outdir),
    }
    telemetry.run_end(status="ok", steps_per_s=round(steps_per_s, 1))
    telemetry.close()
    print(json.dumps(summary, indent=1))
    return 0


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except SystemExit:
        raise
    except BaseException as exc:
        from dib_tpu.telemetry import finalize_crashed

        finalize_crashed(exc, log=lambda msg: print(msg, file=sys.stderr))
        raise
