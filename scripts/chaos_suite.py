"""Continuous chaos suite: the fault matrix against the scheduler UNDER LOAD.

``scripts/fault_drill.py`` proves each worker-level recovery path in
isolation; this suite proves the *scheduling layer* (``dib_tpu/sched``,
docs/robustness.md "Sweep as a service") keeps its three invariants
while faults land on a live β-grid job:

  - **zero lost work units** — every submitted unit ends ``done``;
  - **no double-executed unit** — the journal records exactly one
    ``done`` per unit (superseded leases were rejected);
  - **bit-identical per-β histories** — every unit's committed history
    equals an uninterrupted baseline's, byte for byte (the stolen /
    retried / preempted continuations resumed the exact trajectory).

Drills (each runs a fresh 2-unit β-grid job through a worker pool):

  - ``worker_kill``  — one worker dies mid-unit (``WorkerKilled``): the
    pool degrades to N−1, the reaper steals the silent lease, a live
    worker resumes from the unit's newest intact checkpoint;
  - ``lease_expire`` — a held lease is force-expired while its holder
    stalls: a live worker steals the unit; the stale holder's next
    renewal is REJECTED and it abandons without writing anything;
  - ``preempt``      — a unit unwinds with ``TrainingPreempted`` at a
    chunk boundary (checkpoint already durable): re-queued lease-free,
    no retry burned, finished by the next acquire;
  - ``journal_torn`` — the journal is torn mid-append (SIGKILL shape)
    and the scheduler restarted: replay skips the torn line
    (``journal_recovered``), the orphaned lease is stolen, the queue
    drains;
  - ``pool_kill`` (full mode only) — the whole ``sched run-pool``
    WORKER PROCESS is SIGKILLed mid-run and relaunched: the durable
    journal resumes the exact queue across processes.

Every injection lands as a ``fault`` event and every recovery as a
``mitigation`` / ``job`` event on the drill's stream, so ``telemetry
summarize`` reproduces injected/detected/recovered independently of this
script's bookkeeping. The committed record is ``CHAOS_SCHED.json``
(validated per-row by ``scripts/check_run_artifacts.py``).

Usage::

    python scripts/chaos_suite.py --out CHAOS_SCHED.json   # full
    python scripts/chaos_suite.py --quick                  # in-process only
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

METRIC = "chaos_sched_matrix"

#: Tiny per-unit training spec: 4 epochs in 2-epoch chunks (2 boundaries,
#: checkpoint each) — enough structure to kill, steal, and resume against.
TRAIN_SPEC = {
    "num_pretraining_epochs": 2,
    "num_annealing_epochs": 2,
    "steps_per_epoch": 1,
    "batch_size": 32,
    "max_val_points": 64,
    "chunk_epochs": 2,
}
BETAS = (0.1, 1.0)
SEEDS = (0,)


def _job_spec():
    from dib_tpu.sched import JobSpec

    return JobSpec(betas=BETAS, seeds=SEEDS, train=dict(TRAIN_SPEC),
                   retry_budget=3)


def _stream_evidence(run_dir: str) -> dict:
    from dib_tpu.telemetry import summarize

    summary = summarize(run_dir)
    return {
        "faults": summary.get("faults"),
        "scheduler": summary.get("scheduler"),
        "mitigations": summary.get("mitigations"),
        "status": summary.get("status"),
    }


def _journal_invariants(sched_dir: str) -> dict:
    """The journal's own verdict: every unit done exactly once."""
    from dib_tpu.sched import read_journal

    records, torn = read_journal(sched_dir)
    units = [r["unit_id"] for r in records if r.get("kind") == "unit"]
    done: dict[str, int] = {}
    for r in records:
        if r.get("kind") == "done":
            done[r["unit_id"]] = done.get(r["unit_id"], 0) + 1
    return {
        "units": len(units),
        "zero_lost_units": bool(units) and all(u in done for u in units),
        "no_double_execution": all(n == 1 for n in done.values()),
        "done_counts": done,
        "journal_torn_lines": torn,
    }


def _histories_identical(runner, baseline: dict, scheduler) -> bool:
    import numpy as np

    for row in scheduler.status()["units"]:
        unit = scheduler.unit(row["unit_id"])["unit"]
        ref = baseline[(unit.beta, unit.seed)]
        try:
            got = dict(np.load(runner.history_path(unit)))
        except OSError:
            return False
        if sorted(ref) != sorted(got):
            return False
        if not all(np.array_equal(ref[k], got[k]) for k in ref):
            return False
    return True


def run_baseline(workdir: str, log) -> dict:
    """Uninterrupted single-worker run of the drill job: the per-(β,seed)
    history arrays every drill's continuations must match bitwise."""
    import numpy as np

    from dib_tpu.sched import Scheduler, TrainingUnitRunner, WorkerPool

    log("chaos baseline: uninterrupted 2-unit job, one worker")
    d = os.path.join(workdir, "baseline")
    scheduler = Scheduler(d)
    scheduler.submit(_job_spec())
    runner = TrainingUnitRunner(d)
    stats = WorkerPool(scheduler, runner, num_workers=1, poll_s=0.01).run()
    if not (stats["drained"] and stats["completed"] == len(BETAS) * len(SEEDS)):
        raise RuntimeError(f"chaos baseline did not drain cleanly: {stats}")
    histories = {}
    for row in scheduler.status()["units"]:
        unit = scheduler.unit(row["unit_id"])["unit"]
        histories[(unit.beta, unit.seed)] = dict(
            np.load(runner.history_path(unit)))
    scheduler.close()
    return histories


def _drill_stack(workdir: str, name: str, boundary_hook=None,
                 lease_s: float = 60.0):
    """Scheduler + pool + runner + event stream for one drill."""
    from dib_tpu.sched import Scheduler, TrainingUnitRunner, WorkerPool
    from dib_tpu.telemetry import EventWriter, runtime_manifest

    d = os.path.join(workdir, name)
    writer = EventWriter(d, run_id=f"chaos-{name}")
    writer.run_start(runtime_manifest(extra={"mode": "chaos_sched",
                                             "drill": name}))
    scheduler = Scheduler(d, telemetry=writer, lease_s=lease_s)
    scheduler.submit(_job_spec())
    runner = TrainingUnitRunner(d, telemetry=writer,
                                boundary_hook=boundary_hook)
    pool = WorkerPool(scheduler, runner, num_workers=2, telemetry=writer,
                      poll_s=0.01, reap_every_s=0.05)
    return d, writer, scheduler, runner, pool


def _drill_record(name: str, kind: str, ok: bool, **details) -> dict:
    return {"drill": name, "kind": kind, "ok": bool(ok), **details}


def _finish(name, kind, ok_extra, d, writer, scheduler, runner, baseline,
            stats, t0, **details) -> dict:
    writer.run_end(status="ok")
    writer.close()
    invariants = _journal_invariants(d)
    identical = _histories_identical(runner, baseline, scheduler)
    scheduler.close()
    evidence = _stream_evidence(d)
    faults = evidence.get("faults") or {}
    ok = (ok_extra and stats["drained"]
          and invariants["zero_lost_units"]
          and invariants["no_double_execution"]
          and identical
          and faults.get("injected") == faults.get("detected") == 1
          and faults.get("recovered") == 1)
    return _drill_record(
        name, kind, ok,
        zero_lost_units=invariants["zero_lost_units"],
        no_double_execution=invariants["no_double_execution"],
        bit_identical_histories=identical,
        pool_stats={k: stats[k] for k in
                    ("completed", "failed", "released", "stale_abandoned",
                     "stale_completions", "workers_died", "stolen")},
        wall_s=round(time.time() - t0, 1),
        evidence=evidence, **details,
    )


# ------------------------------------------------------------------ drills
def run_worker_kill_drill(workdir: str, baseline: dict, log) -> dict:
    """One worker dies dead mid-unit; the reaper steals its silent lease
    and a live worker resumes the unit from its newest intact checkpoint."""
    log("chaos worker_kill: worker dies at a chunk boundary under load")
    fired = threading.Event()
    state = {}

    def boundary_hook(unit, epoch):
        from dib_tpu.sched import WorkerKilled

        if unit.beta == BETAS[0] and not fired.is_set():
            fired.set()
            state["writer"].fault(kind="sched_worker_kill",
                                  detail=unit.unit_id, epoch=epoch)
            raise WorkerKilled(f"chaos: worker killed at epoch {epoch}")

    t0 = time.time()
    d, writer, scheduler, runner, pool = _drill_stack(
        workdir, "worker_kill", boundary_hook)
    state["writer"] = writer
    stats = pool.run()
    return _finish("worker_kill", "sched_worker_kill",
                   stats["workers_died"] == 1 and stats["stolen"] >= 1,
                   d, writer, scheduler, runner, baseline, stats, t0)


def run_lease_expire_drill(workdir: str, baseline: dict, log) -> dict:
    """A held lease is force-expired while its holder stalls: the unit is
    stolen and completed by a live worker; the stale holder's renewal is
    rejected and it abandons without writing a thing."""
    log("chaos lease_expire: stalled holder loses its lease to a thief")
    stalled = threading.Event()
    fired = threading.Event()
    state = {}

    def boundary_hook(unit, epoch):
        if unit.beta == BETAS[0] and not fired.is_set():
            fired.set()
            stalled.set()
            # stall past the injected expiry: the thief takes the unit
            # while this worker sleeps; its next heartbeat is rejected
            time.sleep(2.0)

    t0 = time.time()
    d, writer, scheduler, runner, pool = _drill_stack(
        workdir, "lease_expire", boundary_hook)
    state["unit_id"] = None

    def injector():
        from dib_tpu.faults import expire_lease

        stalled.wait(timeout=120)
        for row in scheduler.status()["units"]:
            if row["status"] == "leased" and row["beta"] == BETAS[0]:
                expire_lease(scheduler, row["unit_id"], telemetry=writer)
                state["unit_id"] = row["unit_id"]
                return

    injector_thread = threading.Thread(target=injector, daemon=True)
    injector_thread.start()
    stats = pool.run()
    injector_thread.join(timeout=5)
    return _finish("lease_expire", "lease_expire",
                   state["unit_id"] is not None
                   and stats["stale_abandoned"] == 1,
                   d, writer, scheduler, runner, baseline, stats, t0,
                   expired_unit=state["unit_id"])


def run_preempt_drill(workdir: str, baseline: dict, log) -> dict:
    """A unit unwinds with TrainingPreempted at a chunk boundary (the
    checkpoint hook already saved): re-queued lease-free — no retry
    burned — and finished bit-identically by the next acquire."""
    log("chaos preempt: cooperative preemption re-queues lease-free")
    fired = threading.Event()
    state = {}

    def boundary_hook(unit, epoch):
        from dib_tpu.train.preempt import TrainingPreempted

        if unit.beta == BETAS[0] and not fired.is_set():
            fired.set()
            state["writer"].fault(kind="preempt", detail=unit.unit_id,
                                  epoch=epoch)
            raise TrainingPreempted(epoch, checkpoint_saved=True)

    t0 = time.time()
    d, writer, scheduler, runner, pool = _drill_stack(
        workdir, "preempt", boundary_hook)
    state["writer"] = writer
    stats = pool.run()
    # lease-free: the preempted attempt must not have burned the budget
    retries = (_stream_evidence_retries(d))
    return _finish("preempt", "preempt",
                   stats["released"] == 1 and retries == 0,
                   d, writer, scheduler, runner, baseline, stats, t0,
                   retries_burned=retries)


def _stream_evidence_retries(run_dir: str) -> int:
    from dib_tpu.telemetry import summarize

    sched = summarize(run_dir).get("scheduler") or {}
    return int(sched.get("retries_max") or 0)


def run_journal_torn_drill(workdir: str, baseline: dict, log) -> dict:
    """The journal is torn mid-append (the SIGKILL shape) with a lease in
    flight, and the scheduler restarted: replay skips the torn line
    (journal_recovered), the orphaned lease is stolen, the queue drains."""
    from dib_tpu.faults import tear_journal
    from dib_tpu.sched import (
        JOURNAL_FILENAME,
        Scheduler,
        TrainingUnitRunner,
        WorkerPool,
    )
    from dib_tpu.telemetry import EventWriter, runtime_manifest

    log("chaos journal_torn: torn journal + scheduler restart under load")
    t0 = time.time()
    d = os.path.join(workdir, "journal_torn")
    writer = EventWriter(d, run_id="chaos-journal_torn")
    writer.run_start(runtime_manifest(extra={"mode": "chaos_sched",
                                             "drill": "journal_torn"}))
    # phase A: a scheduler submits the job, grants one short lease to a
    # ghost holder, then dies mid-append (the torn final line)
    sched_a = Scheduler(d, telemetry=writer, lease_s=0.2)
    sched_a.submit(_job_spec())
    ghost = sched_a.acquire("ghost-worker")
    sched_a.close()
    tear_journal(os.path.join(d, JOURNAL_FILENAME), telemetry=writer)

    # phase B: a fresh scheduler replays the journal (skipping the torn
    # line, surfacing journal_recovered) and a pool drains the queue —
    # the ghost's expired lease is stolen on the first reap
    scheduler = Scheduler(d, telemetry=writer, lease_s=60.0)
    torn_seen = scheduler.replayed_torn
    runner = TrainingUnitRunner(d, telemetry=writer)
    pool = WorkerPool(scheduler, runner, num_workers=2, telemetry=writer,
                      poll_s=0.01, reap_every_s=0.05)
    stats = pool.run()
    return _finish("journal_torn", "journal_torn",
                   torn_seen == 1 and ghost is not None
                   and stats["stolen"] >= 1,
                   d, writer, scheduler, runner, baseline, stats, t0,
                   replayed_torn=torn_seen)


# ----------------------------------------------------- subprocess drill
def _worker_env(**extra) -> dict:
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "DIB_COMPILE_CACHE": "",
        "JAX_COMPILATION_CACHE_DIR":
            os.path.expanduser("~/.cache/jax_comp_cache_cpu"),
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0.2",
        "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES": "0",
    })
    env.update(extra)
    return env


def run_pool_kill_drill(workdir: str, baseline: dict, log) -> dict:
    """Process-level graceful degradation: the whole `sched run-pool`
    worker process is SIGKILLed mid-run and a fresh one launched — the
    durable journal resumes the exact queue across processes, and every
    unit still completes exactly once, bit-identically."""
    import numpy as np

    from dib_tpu.sched import JOURNAL_FILENAME, Scheduler, TrainingUnitRunner
    from dib_tpu.sched.journal import read_journal
    from dib_tpu.telemetry import EventWriter

    log("chaos pool_kill: SIGKILL the run-pool process, relaunch it")
    t0 = time.time()
    d = os.path.join(workdir, "pool_kill")
    os.makedirs(d, exist_ok=True)
    run_id = "chaos-pool_kill"
    env = _worker_env(DIB_TELEMETRY_RUN_ID=run_id)
    submit = subprocess.run(
        [sys.executable, "-m", "dib_tpu.cli", "sched", "submit",
         "--sched-dir", d, "--betas", *[str(b) for b in BETAS],
         "--seeds", *[str(s) for s in SEEDS],
         *sum((["--set", f"{k}={v}"] for k, v in TRAIN_SPEC.items()), [])],
        env=env, capture_output=True, text=True, timeout=120)
    if submit.returncode != 0:
        return _drill_record("pool_kill", "sched_worker_kill", False,
                             error=submit.stderr[-1000:])
    pool_cmd = [sys.executable, "-m", "dib_tpu.cli", "sched", "run-pool",
                "--sched-dir", d, "--workers", "1", "--lease-s", "1.0"]
    journal = os.path.join(d, JOURNAL_FILENAME)
    # the injection is a SIGKILL, which leaves no room for the worker to
    # emit its own fault event — record it from the drill harness instead
    writer = EventWriter(d, run_id=run_id, process_index=0,
                         tags={"src": "chaos"})
    proc = subprocess.Popen(pool_cmd, env=env, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 300
        while time.time() < deadline:
            records, _ = read_journal(journal)
            if any(r.get("kind") == "done" for r in records):
                break
            if proc.poll() is not None:
                break
            time.sleep(0.2)
        killed = proc.poll() is None
        writer.fault(kind="sched_worker_kill", detail="run-pool process",
                     via="SIGKILL")
        if killed:
            proc.kill()
        proc.wait()
    finally:
        if proc.poll() is None:
            proc.kill()
    relaunch = subprocess.run(pool_cmd, env=env, capture_output=True,
                              text=True, timeout=600)
    writer.close()
    scheduler = Scheduler(d)
    runner = TrainingUnitRunner(d)
    invariants = _journal_invariants(d)
    identical = _histories_identical(runner, baseline, scheduler)
    counts = scheduler.status()["counts"]
    scheduler.close()
    evidence = _stream_evidence(d)
    ok = (killed and relaunch.returncode == 0
          and counts["done"] == len(BETAS) * len(SEEDS)
          and invariants["zero_lost_units"]
          and invariants["no_double_execution"]
          and identical)
    return _drill_record(
        "pool_kill", "sched_worker_kill", ok,
        killed_mid_run=killed,
        relaunch_returncode=relaunch.returncode,
        zero_lost_units=invariants["zero_lost_units"],
        no_double_execution=invariants["no_double_execution"],
        bit_identical_histories=identical,
        wall_s=round(time.time() - t0, 1),
        evidence=evidence,
        **({} if relaunch.returncode == 0
           else {"stderr_tail": relaunch.stderr[-1500:]}),
    )


# ----------------------------------------------------------------- driver
def run_chaos(workdir: str | None = None, quick: bool = False,
              log=lambda m: print(m, file=sys.stderr, flush=True)) -> dict:
    """Run the chaos matrix; returns the bench-shaped record."""
    owned = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="dib_chaos_sched_")
    matrix: list[dict] = []
    try:
        baseline = run_baseline(workdir, log)
        matrix.append(run_worker_kill_drill(workdir, baseline, log))
        matrix.append(run_lease_expire_drill(workdir, baseline, log))
        matrix.append(run_preempt_drill(workdir, baseline, log))
        matrix.append(run_journal_torn_drill(workdir, baseline, log))
        if not quick:
            matrix.append(run_pool_kill_drill(workdir, baseline, log))
    finally:
        if owned:
            shutil.rmtree(workdir, ignore_errors=True)
    passed = sum(1 for d in matrix if d["ok"])
    return {
        "metric": METRIC,
        "value": passed,
        "unit": "drills_passed",
        "total": len(matrix),
        "quick": quick,
        "all_passed": passed == len(matrix),
        "betas": list(BETAS),
        "seeds": list(SEEDS),
        "matrix": matrix,
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }


def _register(record: dict, runs_root: str | None, log) -> None:
    """Fleet-registry registration (docs/observability.md): explicit-
    root-only (--runs-root / DIB_RUNS_ROOT) — ad-hoc local runs must not
    grow the committed runs/index.jsonl; see register_drill_record."""
    from dib_tpu.telemetry.registry import register_drill_record

    if register_drill_record(record, root=runs_root) is not None:
        log("chaos suite: registered in the fleet registry")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--out", default=None,
                        help="Also write the JSON record to this path.")
    parser.add_argument("--quick", action="store_true",
                        help="Skip the subprocess pool_kill drill "
                             "(in-process drills only).")
    parser.add_argument("--workdir", default=None,
                        help="Keep drill artifacts here (default: a temp "
                             "dir, removed afterwards).")
    parser.add_argument("--runs-root", "--runs_root", dest="runs_root",
                        default=None,
                        help="Register this run in the fleet registry "
                             "(<runs-root>/index.jsonl; default: "
                             "DIB_RUNS_ROOT when set, else off).")
    args = parser.parse_args(argv)
    log = lambda m: print(m, file=sys.stderr, flush=True)  # noqa: E731
    record = run_chaos(workdir=args.workdir, quick=args.quick, log=log)
    print(json.dumps(record), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(json.dumps(record, indent=1) + "\n")
    _register(record, args.runs_root, log)
    return 0 if record["all_passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
