"""Generate the static site's figures by RUNNING dib-tpu workloads.

The reference is, literally, a GitHub Pages site (reference
``index.html``, ``website_files/``) whose figures come from its papers.
This builds the equivalent L6 artifact for dib-tpu with figures produced
by this framework's own workloads at documentation scale:

  - boolean info plane + per-feature information allocation (circuit.svg
    analogue; boolean notebook cells 6-7),
  - per-particle probe-grid information heat map (transformer.svg
    analogue; amorphous notebook cell 8),
  - compression matrices across the anneal (ICLR paper's signature viz),
  - double-pendulum trajectory (pendy_anim.gif analogue, static),
  - radial-shell information profile (the reconstructed workload).

Run: PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/build_site.py
(about 5 minutes on the 1-core CPU box; instant-ish on TPU).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt
import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ASSETS = os.path.join(REPO, "site", "assets")


def boolean_figures() -> None:
    from dib_tpu.workloads.boolean import (
        BooleanWorkloadConfig,
        run_boolean_workload,
    )

    config = BooleanWorkloadConfig(
        num_steps=4000, batch_size=512, mi_every=200,
        beta_start=1e-3, beta_end=5.0,
    )
    result = run_boolean_workload(0, config)
    hist = result["history"]
    lower = hist["mi_lower_bits"]                      # [C, F]
    betas = hist["mi_betas"]

    fig, ax = plt.subplots(figsize=(7, 4.2))
    cmap = plt.get_cmap("tab10")
    for f in range(lower.shape[1]):
        ax.plot(betas, lower[:, f], color=cmap(f % 10),
                label=f"input {f + 1}", lw=1.6)
    ax.set_xscale("log")
    ax.set_xlabel(r"bottleneck strength $\beta$")
    ax.set_ylabel("information used per input (bits)")
    ax.set_title("Reverse-engineering a Boolean circuit: information allocation")
    ax.legend(ncol=2, fontsize=7, frameon=False)
    fig.tight_layout()
    fig.savefig(os.path.join(ASSETS, "boolean_allocation.png"), dpi=130)
    plt.close(fig)


def glass_probe_map() -> None:
    from dib_tpu.workloads.amorphous import (
        AmorphousWorkloadConfig,
        run_amorphous_workload,
    )

    config = AmorphousWorkloadConfig(
        num_steps=4000, number_particles=20, batch_size=32,
        warmup_steps=200, eval_every=4000, probe_every=2000,
        grid_side=48, probe_data_batch=256,
        mi_eval_batch_size=256, mi_eval_batches=1,
        beta_start=2e-6, beta_end=2e-1,
    )
    result = run_amorphous_workload(
        key=0, config=config, outdir=os.path.join(ASSETS, "_glass_tmp"),
        steps_per_epoch=20,
        model_overrides={
            "encoder_hidden": (64,), "embedding_dim": 8, "num_blocks": 2,
            "num_heads": 4, "key_dim": 32, "ff_hidden": (64,),
            "head_hidden": (64,),
        },
        num_synthetic_neighborhoods=512,
    )
    # keep the final probe map as the site figure
    import shutil

    steps = sorted(result["probe_grids"])
    src = os.path.join(ASSETS, "_glass_tmp", f"info_map_step{steps[-1]}.png")
    shutil.copy(src, os.path.join(ASSETS, "glass_info_map.png"))
    shutil.copy(result["info_plane_path"],
                os.path.join(ASSETS, "glass_info_plane.png"))
    shutil.rmtree(os.path.join(ASSETS, "_glass_tmp"))


def compression_matrices() -> None:
    import jax

    from dib_tpu.data import get_dataset
    from dib_tpu.models import DistributedIBModel
    from dib_tpu.train import CompressionMatrixHook, DIBTrainer, Every, TrainConfig

    bundle = get_dataset("wine", data_path=os.path.join(REPO, "tests/fixtures/tabular"))
    model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=(32,), integration_hidden=(64,), output_dim=1,
        embedding_dim=2,
    )
    config = TrainConfig(
        batch_size=32, beta_start=1e-4, beta_end=2.0,
        num_pretraining_epochs=100, num_annealing_epochs=400,
        steps_per_epoch=2, max_val_points=16,
    )
    trainer = DIBTrainer(model, bundle, config)
    outdir = os.path.join(ASSETS, "_comp_tmp")
    hook = CompressionMatrixHook(outdir, features=(10,))   # alcohol
    trainer.fit(jax.random.key(0), hooks=[Every(100, hook)], hook_every=100)

    import shutil

    # mid-anneal checkpoint: distinctions partially merged (the signature
    # visual); the final beta=2.0 matrix is uniformly crushed
    pngs = sorted(os.listdir(outdir))
    mid = [p for p in pngs if "log10beta_-0." in p] or pngs
    shutil.copy(os.path.join(outdir, mid[0]),
                os.path.join(ASSETS, "compression_matrix.png"))
    shutil.rmtree(outdir)


def pendulum_figure() -> None:
    from dib_tpu.data.pendulum import simulate_double_pendulum

    traj = simulate_double_pendulum(
        num_trajectories=1, simulation_time=18.0, seed=4
    )[0]
    theta1, theta2 = traj[:, 0], traj[:, 2]
    l1 = l2 = 1.0
    x1, y1 = l1 * np.sin(theta1), -l1 * np.cos(theta1)
    x2, y2 = x1 + l2 * np.sin(theta2), y1 - l2 * np.cos(theta2)

    fig, ax = plt.subplots(figsize=(4.6, 4.6))
    points = np.stack([x2, y2], -1)
    for i in range(len(points) - 1):
        ax.plot(points[i:i + 2, 0], points[i:i + 2, 1],
                color=plt.get_cmap("viridis")(i / len(points)), lw=0.8)
    ax.plot([0, x1[-1], x2[-1]], [0, y1[-1], y2[-1]], "o-", color="k", lw=2)
    ax.set_aspect("equal")
    ax.set_xlim(-2.1, 2.1); ax.set_ylim(-2.1, 2.1)
    ax.set_title("Double pendulum: chaotic tip trajectory")
    ax.axis("off")
    fig.tight_layout()
    fig.savefig(os.path.join(ASSETS, "pendulum_trajectory.png"), dpi=130)
    plt.close(fig)


def radial_shell_figure() -> None:
    from dib_tpu.workloads.radial_shells import RadialShellsConfig, run_radial_shells_workload

    result = run_radial_shells_workload(
        0,
        RadialShellsConfig(
            num_pretraining_epochs=500, num_annealing_epochs=3000,
            num_shells=8, eval_every=250,
        ),
        outdir=os.path.join(ASSETS, "_shell_tmp"),
    )
    import shutil

    shutil.copy(result["profile_path"],
                os.path.join(ASSETS, "radial_shells.png"))
    shutil.rmtree(os.path.join(ASSETS, "_shell_tmp"))


def compression_anneal_gif(
    compression_dir: str | None = None, feature: int = 0
) -> None:
    """Animate one channel's compression schemes across the beta anneal.

    Frames come from a north-star run's per-checkpoint scheme PNGs (the
    sweep instrumentation output, ``SweepCompressionHook.render``); the
    committed ``site/assets/compression_anneal.gif`` was built from the
    measured run behind ``NORTHSTAR_RUN.json`` (replica 7). Skipped with a
    note when no run directory is present — regenerate the run first with
    ``scripts/northstar_run.py``.
    """
    import glob as _glob
    import re as _re

    from PIL import Image

    compression_dir = compression_dir or os.path.join(
        REPO, "northstar_out", "replica7", "compression"
    )
    paths = _glob.glob(
        os.path.join(compression_dir, f"feature_{feature}_log10beta_*.png")
    )
    if not paths:
        print(f"  (no schemes under {compression_dir}; run "
              "scripts/northstar_run.py first — keeping committed gif)")
        return
    paths.sort(key=lambda p: float(
        _re.search(r"log10beta_(-?[\d.]+)\.png", p).group(1)
    ))
    frames = [Image.open(p).convert("P", palette=Image.ADAPTIVE)
              for p in paths]
    frames[0].save(
        os.path.join(ASSETS, "compression_anneal.gif"),
        save_all=True, append_images=frames[1:], duration=350, loop=0,
    )


def info_map_anneal_gif(maps_dir: str | None = None,
                        size: tuple[int, int] = (900, 400)) -> None:
    """Animate the probe-grid info maps of a full protocol run.

    Frames come from ``run_amorphous_protocols`` output
    (``info_map_step{N}.png``); the committed gif was built from the
    25k-step GradualQuench TPU run behind ``AMORPHOUS_PROTOCOLS.json``.
    Skipped with a note when no run directory is present.
    """
    import glob as _glob
    import re as _re

    from PIL import Image

    maps_dir = maps_dir or os.path.join(REPO, "amorphous_out", "GradualQuench")
    paths = _glob.glob(os.path.join(maps_dir, "info_map_step*.png"))
    if not paths:
        print(f"  (no info maps under {maps_dir}; run "
              "scripts/amorphous_protocols_run.py first — keeping committed gif)")
        return
    paths.sort(key=lambda p: int(_re.search(r"step(\d+)\.png", p).group(1)))
    frames = [Image.open(p).resize(size, Image.LANCZOS)
              .convert("P", palette=Image.ADAPTIVE) for p in paths]
    frames[0].save(
        os.path.join(ASSETS, "info_map_anneal.gif"),
        save_all=True, append_images=frames[1:], duration=280, loop=0,
    )


def chaos_scaling_figure() -> None:
    """The PRL paper's headline (Fig. 3): entropy-rate estimate vs number
    of measurement outcomes L, saturating on the known KS entropy.

    Built from the COMMITTED hardware artifacts (no re-run): the
    paper-budget anchors (`CHAOS_STATE_SWEEP.json`, 1e6 train / 2e7 char
    states per config on the TPU) over the reduced-budget 14-L shape
    sweep (`CHAOS_STATE_SWEEP_SHAPE.json`). Reference protocol:
    chaos notebook cell 10 ("loop over number_states from 2 to 15")."""
    import json

    with open(os.path.join(REPO, "CHAOS_STATE_SWEEP.json")) as f:
        anchor = json.load(f)
    shape = None
    shape_path = os.path.join(REPO, "CHAOS_STATE_SWEEP_SHAPE.json")
    if os.path.exists(shape_path):
        with open(shape_path) as f:
            shape = json.load(f)

    fig, ax = plt.subplots(figsize=(6.4, 4.2))
    known = anchor["known_rate_bits"]
    ax.axhline(known, color="0.25", lw=1.2, ls="--",
               label=f"known rate ({known:.3f} bits)")
    if shape is not None:
        ax.plot(shape["state_counts"], shape["h_inf_bits"], "o-",
                color="#9ecae1", ms=4, lw=1.2,
                label="14-L shape sweep (reduced budget)")
    ax.plot(anchor["state_counts"], anchor["h_inf_bits"], "o-",
            color="#1f77b4", ms=7, lw=2.2,
            label="paper-budget anchors (TPU)")
    ax.set_xlabel("number of measurement outcomes  L")
    ax.set_ylabel("entropy rate estimate  (bits / iteration)")
    ax.set_title(f"{anchor['system'].capitalize()} map: IB-optimized "
                 "measurements approach the KS entropy")
    ax.legend(frameon=False, loc="lower right")
    ax.spines[["top", "right"]].set_visible(False)
    fig.tight_layout()
    fig.savefig(os.path.join(ASSETS, "chaos_entropy_scaling.png"), dpi=160)
    plt.close(fig)


def characterization_residual_figure() -> None:
    """MI sandwich-bound residuals against the Monte-Carlo oracle across
    the 105-cell characterization sweep (`CHARACTERIZATION_FULL.json`,
    measured on the TPU): lower/upper bound errors vs ground truth at each
    batch size, showing the float32 log-space kernel brackets the truth.
    Reference: Characterizing_mutual_information_bounds.ipynb's bound
    tightness study."""
    import json

    with open(os.path.join(REPO, "CHARACTERIZATION_FULL.json")) as f:
        art = json.load(f)
    cells = [c for c in art["cells"] if c["batch_size"] == 1024]
    gap_median = float(np.median([c["gap_bits"] for c in cells]))
    truth = np.array([c["mc_truth_bits"] for c in cells])
    lower = np.array([c["lower_bits"] for c in cells]) - truth
    upper = np.array([c["upper_bits"] for c in cells]) - truth
    lstd = np.array([c["lower_std_bits"] for c in cells])
    ustd = np.array([c["upper_std_bits"] for c in cells])

    fig, ax = plt.subplots(figsize=(6.4, 4.2))
    ax.axhline(0.0, color="0.25", lw=1.0)
    ax.errorbar(truth, lower, yerr=lstd, fmt="v", ms=5, lw=0.9,
                color="#1f77b4", capsize=2, label="lower bound − truth")
    ax.errorbar(truth, upper, yerr=ustd, fmt="^", ms=5, lw=0.9,
                color="#9ecae1", capsize=2, label="upper bound − truth")
    ax.set_xlabel("Monte-Carlo ground-truth MI  (bits)")
    ax.set_ylabel("bound residual  (bits)")
    ax.set_title("MI sandwich bounds vs a Monte-Carlo oracle  (B = 1024)")
    ax.text(0.02, 0.97,
            f"{art['bracketing_fraction']:.0%} of {art['cells_total']} "
            "sweep cells bracketed\n"
            f"median sandwich gap {gap_median:.4f} bits at B=1024 "
            "(float32, log-space, on TPU)",
            transform=ax.transAxes, va="top", fontsize=9, color="0.3")
    ax.legend(frameon=False, loc="lower left", fontsize=9)
    ax.spines[["top", "right"]].set_visible(False)
    fig.tight_layout()
    fig.savefig(os.path.join(ASSETS, "characterization_residuals.png"),
                dpi=160)
    plt.close(fig)


def main() -> None:
    os.makedirs(ASSETS, exist_ok=True)
    for name, fn in [
        ("pendulum", pendulum_figure),
        ("boolean", boolean_figures),
        ("compression", compression_matrices),
        ("radial shells", radial_shell_figure),
        ("glass probe map", glass_probe_map),
        ("compression anneal gif", compression_anneal_gif),
        ("info map anneal gif", info_map_anneal_gif),
        ("chaos entropy scaling", chaos_scaling_figure),
        ("characterization residuals", characterization_residual_figure),
    ]:
        print(f"building {name} figure...", flush=True)
        fn()
    print("site assets written to", ASSETS)


if __name__ == "__main__":
    main()
