"""Hardware validation of the Pallas kernels at the current commit.

VERDICT round 2, item 7: the CPU suite runs both kernels in interpreter
mode, so a TPU lowering/VMEM regression would be invisible. This script
runs the kernels NON-interpreted on the real device — the same checks the
CPU tests pin, plus a large-set forward/backward through the flash kernel —
and prints a stamp for PARITY.md.

Run on the TPU (ambient env, ALONE):  python scripts/tpu_validate_pallas.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dib_tpu.ops.gaussian import gaussian_log_density_mat
    from dib_tpu.ops.pallas_attention import flash_self_attention
    from dib_tpu.ops.pallas_density import gaussian_log_density_mat_pallas
    from dib_tpu.parallel.context import dense_self_attention

    devices = jax.devices()
    assert devices[0].platform == "tpu", f"need a TPU, got {devices}"
    rng = np.random.default_rng(0)
    checks = {}

    # Lowering-correctness checks run at matmul precision 'highest' (f32
    # accumulation through the MXU): at the DEFAULT precision the MXU
    # computes f32 matmuls through bf16 passes and the Pallas kernel and the
    # XLA einsum oracle round differently (~2e-3 abs — checked separately,
    # loose tolerance), which would mask real lowering bugs at tight tol.
    # ---- flash attention vs dense oracle, compiled lowering ----
    with jax.default_matmul_precision("highest"):
        for seq, block in [(64, 32), (50, 16), (37, 32), (1024, 128)]:
            q, k, v = (
                jnp.asarray(rng.standard_normal((2, seq, 3, 16)), jnp.float32)
                for _ in range(3)
            )
            got = flash_self_attention(q, k, v, block_q=block, block_k=block,
                                       interpret=False)
            want = dense_self_attention(q, k, v)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=2e-4, atol=2e-4)
            checks[f"flash_fwd_seq{seq}_block{block}"] = "ok"

    # default-precision agreement (what production runs use): bf16-pass MXU
    # rounding differs between the two implementations — loose tolerance
    q, k, v = (
        jnp.asarray(rng.standard_normal((2, 64, 3, 16)), jnp.float32)
        for _ in range(3)
    )
    got = flash_self_attention(q, k, v, block_q=32, block_k=32,
                               interpret=False)
    want = dense_self_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-2, atol=2e-2)
    checks["flash_fwd_default_precision"] = "ok (loose tol: bf16 MXU passes)"

    # large scores stay finite (the flagship failure mode)
    q, k, v = (
        jnp.asarray(rng.standard_normal((2, 64, 3, 16)), jnp.float32)
        for _ in range(3)
    )
    got = flash_self_attention(q * 100.0, k * 100.0, v, block_q=32,
                               block_k=32, interpret=False)
    assert bool(jnp.isfinite(got).all())
    checks["flash_large_scores_finite"] = "ok"

    # ---- large-set forward/BACKWARD (recompute VJP) on device ----
    big_q = jnp.asarray(rng.standard_normal((1, 4096, 4, 32)), jnp.float32)

    def loss(q, k, v):
        return flash_self_attention(q, k, v, block_q=256, block_k=256,
                                    interpret=False).sum()

    t0 = time.time()
    grads = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(big_q, big_q, big_q)
    jax.block_until_ready(grads)
    checks["flash_bwd_seq4096"] = (
        f"ok ({time.time() - t0:.1f}s incl. compile; grads finite="
        f"{bool(all(jnp.isfinite(g).all() for g in grads))}"
    )
    assert all(bool(jnp.isfinite(g).all()) for g in grads)

    # dense-oracle gradient agreement at a checkable size
    with jax.default_matmul_precision("highest"):
        small = jnp.asarray(rng.standard_normal((2, 128, 2, 16)), jnp.float32)

        def loss_flash(q):
            return flash_self_attention(q, small, small, block_q=64,
                                        block_k=64, interpret=False).sum()

        def loss_dense(q):
            return dense_self_attention(q, small, small).sum()

        g_flash = jax.grad(loss_flash)(small)
        g_dense = jax.grad(loss_dense)(small)
        np.testing.assert_allclose(np.asarray(g_flash), np.asarray(g_dense),
                                   rtol=2e-3, atol=2e-3)
        checks["flash_bwd_matches_dense"] = "ok"

        # ---- tiled density kernel vs the XLA reference ----
        for b, d, tile in [(256, 8, 128), (1024, 32, 256)]:
            u = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)
            mus = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)
            lvs = jnp.asarray(rng.standard_normal((b, d)) * 0.3, jnp.float32)
            got = gaussian_log_density_mat_pallas(u, mus, lvs, interpret=False)
            want = gaussian_log_density_mat(u, mus, lvs)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=1e-4, atol=1e-4)
            checks[f"density_b{b}_d{d}"] = "ok"

        # ---- fused one-pass MI-sandwich row stats vs materialize+reduce
        # (incl. a non-tile-divisible shape: padding/masking lowering) ----
        from dib_tpu.ops.pallas_density import mi_row_stats_pallas

        for b, d in [(256, 8), (1000, 32), (4096, 32)]:
            u = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)
            mus = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)
            lvs = jnp.asarray(rng.standard_normal((b, d)) * 0.3, jnp.float32)
            log_p = gaussian_log_density_mat(u, mus, lvs)
            want_diag = jnp.diagonal(log_p)
            want_full = jax.scipy.special.logsumexp(log_p, axis=1)
            want_off = jax.scipy.special.logsumexp(
                jnp.where(jnp.eye(b, dtype=bool), -1e30, log_p), axis=1)
            diag, full, off = mi_row_stats_pallas(u, mus, lvs,
                                                  interpret=False)
            np.testing.assert_allclose(np.asarray(diag),
                                       np.asarray(want_diag),
                                       rtol=2e-4, atol=2e-4)
            np.testing.assert_allclose(np.asarray(full),
                                       np.asarray(want_full),
                                       rtol=2e-4, atol=2e-4)
            np.testing.assert_allclose(np.asarray(off),
                                       np.asarray(want_off),
                                       rtol=2e-4, atol=2e-4)
            checks[f"fused_row_stats_b{b}_d{d}"] = "ok"

        # probe variant (no diagonal), ragged both axes
        u = jnp.asarray(rng.standard_normal((1000, 16)), jnp.float32)
        mus = jnp.asarray(rng.standard_normal((2050, 16)), jnp.float32)
        lvs = jnp.asarray(rng.standard_normal((2050, 16)) * 0.3, jnp.float32)
        want = jax.scipy.special.logsumexp(
            gaussian_log_density_mat(u, mus, lvs), axis=1)
        _, full, _ = mi_row_stats_pallas(u, mus, lvs, interpret=False,
                                         diagonal=False)
        np.testing.assert_allclose(np.asarray(full), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)
        checks["fused_probe_m1000_n2050"] = "ok"

    commit = subprocess.run(
        ["git", "rev-parse", "--short", "HEAD"],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    ).stdout.strip()
    stamp = {
        "validated_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "commit": commit,
        "device_kind": devices[0].device_kind,
        "checks": checks,
    }
    print(json.dumps(stamp, indent=1))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
