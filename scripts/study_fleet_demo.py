"""Submit-only study fleet demo → STUDY_FLEET_CPU.json.

The deployment shape docs/scheduling.md promises: ONE long-lived
``sched run-pool --serve`` fleet process owns all the workers, and every
study is a submit-only client — the three CLI controllers here
(``study run --fleet`` under tenants alice/bob/carol) plus one study the
drift autopilot submits on its own (``stream autopilot --fleet`` against
a real drifted stream, billed to the ``autopilot`` tenant). All four
drain CONCURRENTLY through the shared fleet, coordinated only by the
scheduler journal.

The committed record is the acceptance evidence for the fleet layer:

  - every study reaches a clean verdict (``converged`` /
    ``no_transitions``), at least one row with ``autopilot: true``;
  - ``admission_reject_frac`` from the fleet's telemetry rollup stays
    inside the committed ``sched_admission_reject_ceiling`` budget — a
    polite study mix is never refused admission;
  - ``tenant_wait_p99_ratio`` (worst tenant queue-wait p99 over the
    fleet median) stays inside ``sched_starvation_ceiling`` — fair-share
    keeps concurrent tenants near parity.

``scripts/check_run_artifacts.py`` re-validates all of that per-row
against the committed SLO budgets on every run.

Usage::

    python scripts/study_fleet_demo.py --out STUDY_FLEET_CPU.json
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

METRIC = "study_fleet_demo"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: The proven converging study shape (scripts/chaos_study.py /
#: scripts/chaos_fleet_study.py): 4-β grid, one seed, refinement to a
#: clean verdict in <= 3 rounds.
STUDY_FLAGS = [
    "--grid", "0.03", "30", "4", "--seeds", "0",
    "--threshold-nats", "0.1", "--tolerance-decades", "0.3",
    "--max-bracket-decades", "2.0",
    "--min-refine-rounds", "1", "--max-rounds", "3", "--max-units", "20",
    "--refine-num", "3",
    "--set", "steps_per_epoch=16", "--set", "num_annealing_epochs=20",
    "--set", "batch_size=128", "--set", "chunk_epochs=11",
]

#: The same shape as the autopilot CLI's ``--study-set`` overrides, so
#: the drift study the autopilot mints is the same scale as the CLI
#: studies it shares the fleet with.
STUDY_SETS = [
    "grid_start=0.03", "grid_stop=30.0", "grid_num=4", "seeds=[0]",
    "threshold_nats=0.1", "tolerance_decades=0.3",
    "max_bracket_decades=2.0", "min_refine_rounds=1", "max_rounds=3",
    "max_units=20", "refine_num=3",
    ("train={'steps_per_epoch': 16, 'num_annealing_epochs': 20, "
     "'batch_size': 128, 'chunk_epochs': 11}"),
]

#: Tiny always-on stream (the chaos_autopilot scale) with one scripted
#: drift — the autopilot needs a real drifted stream to mint its study.
STREAM_ROUNDS = 7
STREAM_DRIFT = "80:mean_shift:3.0"
STREAM_FLAGS = [
    "--dataset", "boolean_circuit",
    "--feature_embedding_dimension", "2",
    "--feature_encoder_architecture", "8",
    "--integration_network_architecture", "16",
    "--batch_size", "32",
    "--number_pretraining_epochs", "2",
    "--number_annealing_epochs", "4",
    "--window", "64", "--stride", "16", "--chunk-epochs", "2",
    "--drift-threshold", "0.5",
]

CLI_TENANTS = ("alice", "bob", "carol")


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _env(extra: dict | None = None) -> dict:
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    for fault in ("DIB_STUDY_FAULT", "DIB_POOL_FAULT", "DIB_STREAM_FAULT",
                  "DIB_AUTOPILOT_FAULT"):
        env.pop(fault, None)
    env.pop("DIB_RUNS_ROOT", None)  # only --runs-root grows the registry
    if extra:
        env.update(extra)
    return env


def _build_stream(stream_dir: str) -> None:
    """Run the tiny drifted trainer stream through the real CLI."""
    _log(f"stream fixture: {STREAM_ROUNDS} rounds, drift {STREAM_DRIFT}")
    proc = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "stream", "run",
         "--stream-dir", stream_dir, *STREAM_FLAGS,
         "--publish-every", "1", "--rounds", str(STREAM_ROUNDS),
         "--seed", "0", "--drift", STREAM_DRIFT],
        env=_env(), cwd=REPO, capture_output=True, text=True, timeout=900)
    if proc.returncode != 0:
        raise RuntimeError(
            f"stream run failed (rc={proc.returncode}):\n"
            f"{(proc.stderr or '')[-2000:]}")


def _start_fleet(sched_dir: str, workers: int) -> subprocess.Popen:
    """Launch THE long-lived external fleet: ``sched run-pool --serve``."""
    os.makedirs(sched_dir, exist_ok=True)
    log = open(os.path.join(sched_dir, "pool.log"), "ab")
    return subprocess.Popen(
        [sys.executable, "-m", "dib_tpu", "sched", "run-pool",
         "--sched-dir", sched_dir, "--workers", str(workers),
         "--lease-s", "8.0", "--duration-s", "3600", "--serve",
         "--preempt_grace_s", "0"],
        env=_env(), stdout=log, stderr=log)


def _start_study(study_dir: str, fleet: str, tenant: str) -> subprocess.Popen:
    """Launch one submit-only CLI study controller against the fleet."""
    os.makedirs(study_dir, exist_ok=True)
    log = open(os.path.join(study_dir, "study.log"), "ab")
    return subprocess.Popen(
        [sys.executable, "-m", "dib_tpu", "study", "run",
         "--study-dir", study_dir, *STUDY_FLAGS,
         "--fleet", fleet, "--tenant", tenant, "--poll-s", "0.2"],
        env=_env(), stdout=log, stderr=log)


def _start_autopilot(stream_dir: str, fleet: str) -> subprocess.Popen:
    """Launch the drift autopilot in submit-only mode: it mints the
    drift study itself and bills it to the ``autopilot`` tenant."""
    log = open(os.path.join(stream_dir, "autopilot.log"), "ab")
    cmd = [sys.executable, "-m", "dib_tpu", "stream", "autopilot",
           "--stream-dir", stream_dir, "--cooldown-rounds", "0",
           "--fleet", fleet, "--tenant", "autopilot"]
    for pair in STUDY_SETS:
        cmd += ["--study-set", pair]
    return subprocess.Popen(cmd, env=_env(), cwd=REPO, stdout=log,
                            stderr=log)


def _wait_proc(proc: subprocess.Popen, timeout: float) -> int | None:
    try:
        return proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
        return None


def _kill_hard(proc: subprocess.Popen | None) -> None:
    if proc is not None and proc.poll() is None:
        proc.kill()
        proc.wait()


def _tail(path: str, n: int = 800) -> str:
    try:
        with open(path, "r", errors="replace") as f:
            return f.read()[-n:]
    except OSError:
        return ""


def _study_verdict(study_dir: str) -> str | None:
    from dib_tpu.study.journal import fold_study, read_study_journal

    records, _ = read_study_journal(study_dir)
    verdict = fold_study(records)["verdict"]
    return None if verdict is None else verdict.get("verdict")


def _autopilot_study(stream_dir: str) -> tuple[str, str | None]:
    """(study_id, verdict) of the drift study the autopilot minted."""
    from dib_tpu.autopilot import autopilot_journal_path, fold_autopilot
    from dib_tpu.sched.journal import read_journal

    records, _ = read_journal(
        autopilot_journal_path(os.path.join(stream_dir, "autopilot")))
    state = fold_autopilot(records)
    decided = [(idx, d) for idx, d in sorted(state["drifts"].items())
               if d.get("verdict") is not None]
    if not decided:
        return "drift-none", None
    idx, drift = decided[-1]
    return f"drift-r{idx:04d}", (drift["verdict"] or {}).get("verdict")


def _fleet_stats(fleet_dir: str) -> dict:
    """The SLO-facing queue stats, from the same telemetry rollup the
    ``telemetry check`` gate reads (``scheduler_rollup``)."""
    from dib_tpu.telemetry import summarize

    sched = summarize(fleet_dir).get("scheduler") or {}
    return {
        "admission_reject_frac": sched.get("admission_reject_frac"),
        "tenant_wait_p99_ratio": sched.get("tenant_wait_p99_ratio"),
        "tenants": sched.get("tenants"),
        "admission_rejected": sched.get("admission_rejected"),
    }


def run_demo(workdir: str, workers: int) -> dict:
    stream_dir = os.path.join(workdir, "stream")
    fleet = os.path.join(workdir, "fleet")
    _build_stream(stream_dir)

    _log(f"fleet: sched run-pool --serve, {workers} workers")
    pool = _start_fleet(fleet, workers)
    started = time.time()
    studies: list[tuple[str, subprocess.Popen]] = []
    autopilot = None
    try:
        for tenant in CLI_TENANTS:
            studies.append((tenant, _start_study(
                os.path.join(workdir, f"study-{tenant}"), fleet, tenant)))
        autopilot = _start_autopilot(stream_dir, fleet)
        _log(f"{len(studies)} CLI studies + autopilot submitted "
             "concurrently; draining through the shared fleet")

        rows = []
        for tenant, proc in studies:
            rc = _wait_proc(proc, timeout=2400)
            study_dir = os.path.join(workdir, f"study-{tenant}")
            verdict = _study_verdict(study_dir)
            if rc != 0:
                _log(f"study {tenant}: rc={rc} verdict={verdict}\n"
                     + _tail(os.path.join(study_dir, "study.log")))
            rows.append({"study_id": f"study-{tenant}", "tenant": tenant,
                         "verdict": verdict, "autopilot": False,
                         "rc": rc})
        rc_auto = _wait_proc(autopilot, timeout=2400)
        study_id, verdict = _autopilot_study(stream_dir)
        if rc_auto != 0:
            _log(f"autopilot: rc={rc_auto} verdict={verdict}\n"
                 + _tail(os.path.join(stream_dir, "autopilot.log")))
        rows.append({"study_id": study_id, "tenant": "autopilot",
                     "verdict": verdict, "autopilot": True, "rc": rc_auto})
        elapsed = round(time.time() - started, 1)
    finally:
        _kill_hard(pool)
        for _, proc in studies:
            _kill_hard(proc)
        _kill_hard(autopilot)

    stats = _fleet_stats(fleet)
    converged = sum(1 for r in rows
                    if r["verdict"] in ("converged", "no_transitions"))
    all_passed = (converged == len(rows)
                  and all(r["rc"] == 0 for r in rows)
                  and isinstance(stats["admission_reject_frac"],
                                 (int, float)))
    record = {
        "metric": METRIC,
        "value": converged,
        "unit": "studies_converged",
        "quick": False,
        "total": len(rows),
        "all_passed": bool(all_passed),
        "workers": workers,
        "concurrent": True,
        "elapsed_s": elapsed,
        "studies": rows,
        "admission_reject_frac": stats["admission_reject_frac"],
        "admission_rejected": stats["admission_rejected"],
        "tenants": stats["tenants"],
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if stats["tenant_wait_p99_ratio"] is not None:
        record["tenant_wait_p99_ratio"] = stats["tenant_wait_p99_ratio"]
    return record


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--out", default="STUDY_FLEET_CPU.json")
    parser.add_argument("--workdir", default=None,
                        help="Keep fleet/study dirs here (default: a "
                             "temp dir, removed on success).")
    parser.add_argument("--workers", type=int, default=3)
    parser.add_argument("--runs-root", "--runs_root", dest="runs_root",
                        default=None,
                        help="Also append a bench entry to this runs "
                             "registry (<runs-root>/index.jsonl; "
                             "default: none).")
    args = parser.parse_args(argv)

    workdir = args.workdir or tempfile.mkdtemp(prefix="study_fleet_")
    record = run_demo(workdir, args.workers)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2, sort_keys=True)
        f.write("\n")
    _log(f"wrote {args.out}: {record['value']}/{record['total']} studies "
         f"converged, admission_reject_frac="
         f"{record['admission_reject_frac']}")

    from dib_tpu.telemetry.registry import register_drill_record

    if register_drill_record(record, root=args.runs_root, extra={
            "autopilot_studies": sum(
                1 for r in record["studies"] if r["autopilot"])},
            ) is not None:
        _log(f"registered in {args.runs_root}/index.jsonl")
    return 0 if record["all_passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
