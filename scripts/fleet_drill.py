"""Fleet causal-tracing drills → FLEET_CPU.json / FLEET_CHAOS.json.

Two drills for the fleet aggregator (docs/observability.md "Fleet
causality"), each through the REAL CLI:

- ``trace`` — a real CPU boolean study driven end-to-end through
  ``python -m dib_tpu study run --trace-id ...``, then its full
  cross-plane timeline reconstructed by ``telemetry fleet summarize``:
  every sched unit and every unit-run event must be reachable from the
  study's trace_id and ``orphan_events`` must be 0. The summary record
  (metric ``fleet_trace``) is committed as ``FLEET_CPU.json`` and gated
  by ``check_run_artifacts`` + the ``fleet_orphan_ceiling`` SLO row.
- ``chaos`` — a durable merge (``telemetry fleet tail --out``) over
  skewed-clock multi-writer sources (one with a torn final line) is
  SIGKILLed mid-merge, the writers keep writing, and a re-attached
  aggregator finishes the merge: zero duplicate entries, zero lost
  entries, and a merged-view digest **bit-identical** to an
  uninterrupted baseline merge of the same sources. Committed as
  ``FLEET_CHAOS.json`` (metric ``fleet_chaos_matrix``).

Usage::

    python scripts/fleet_drill.py trace --out FLEET_CPU.json
    python scripts/fleet_drill.py chaos --out FLEET_CHAOS.json
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TRACE_ID = "trace-fleetdrill0"

#: Small-but-real study shape (the scripts/chaos_study.py scale): 4-β
#: grid, one seed, a refinement round — enough to fan out jobs, units,
#: and unit-run events across all three planes.
STUDY_FLAGS = [
    "--grid", "0.03", "30", "4", "--seeds", "0",
    "--threshold-nats", "0.1", "--tolerance-decades", "0.3",
    "--max-bracket-decades", "2.0",
    "--min-refine-rounds", "1", "--max-rounds", "3", "--max-units", "20",
    "--refine-num", "3",
    "--set", "steps_per_epoch=16", "--set", "num_annealing_epochs=20",
    "--set", "batch_size=128", "--set", "chunk_epochs=11",
]


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _cli(*args: str, **kwargs) -> subprocess.CompletedProcess:
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    # a clean trace root: the drill's own env must not leak a parent
    for var in ("DIB_TRACE_ID", "DIB_TRACE_PARENT", "DIB_TRACE_ORIGIN"):
        env.pop(var, None)
    return subprocess.run([sys.executable, "-m", "dib_tpu", *args],
                          env=env, capture_output=True, text=True,
                          **kwargs)


# ----------------------------------------------------------------- trace
def run_trace(work: str) -> dict:
    study_dir = os.path.join(work, "study")
    _log(f"fleet-drill: running traced CPU study under {study_dir}")
    proc = _cli("study", "run", "--study-dir", study_dir,
                "--trace-id", TRACE_ID, *STUDY_FLAGS, timeout=1800)
    if proc.returncode != 0:
        raise SystemExit(f"study run failed rc={proc.returncode}:\n"
                         f"{proc.stdout}\n{proc.stderr}")
    _log("fleet-drill: study done; merging the fleet timeline")
    proc = _cli("telemetry", "fleet", "summarize", study_dir, timeout=300)
    if proc.returncode != 0:
        raise SystemExit(f"fleet summarize failed rc={proc.returncode} "
                         f"(orphans?):\n{proc.stdout}\n{proc.stderr}")
    summary = json.loads(proc.stdout)

    if summary["orphan_events"] != 0:
        raise SystemExit(f"orphan events: {summary['orphans']}")
    rows = {t["trace_id"]: t for t in summary["traces"]}
    if TRACE_ID not in rows:
        raise SystemExit(f"study trace {TRACE_ID!r} not in merged view "
                         f"({sorted(rows)})")
    row = rows[TRACE_ID]
    # end-to-end reachability: EVERY sched unit and EVERY unit-run event
    # in the merge carries the study's trace_id
    if row["sched_units"] != summary["sched_units_total"] \
            or row["sched_units"] < 1:
        raise SystemExit(
            f"sched units reachable from {TRACE_ID}: {row['sched_units']} "
            f"of {summary['sched_units_total']}")
    if row["run_events"] != summary["run_events_total"] \
            or row["run_events"] < 1:
        raise SystemExit(
            f"run events reachable from {TRACE_ID}: {row['run_events']} "
            f"of {summary['run_events_total']}")
    for plane in ("study", "sched", "run"):
        if plane not in row["planes"]:
            raise SystemExit(f"trace spans {row['planes']}, no {plane!r}")
    summary["drill"] = {
        "mode": "trace",
        "trace_id": TRACE_ID,
        "study_flags": STUDY_FLAGS,
        "reachable_sched_units": row["sched_units"],
        "reachable_run_events": row["run_events"],
        "trace_planes": row["planes"],
    }
    # the committed record must not pin the drill's tempdir
    summary["roots"] = [os.path.basename(r) for r in summary["roots"]]
    return summary


# ----------------------------------------------------------------- chaos
def _write_lines(path: str, lines: list[str], torn_tail: str | None = None):
    with open(path, "a") as f:
        for line in lines:
            f.write(line + "\n")
        if torn_tail is not None:
            f.write(torn_tail)  # no newline: a torn in-flight write
        f.flush()
        os.fsync(f.fileno())


def _records(run: str, start: int, count: int, t0: float) -> list[str]:
    # skewed-clock writers: each source stamps t from its own offset
    return [json.dumps({"v": 1, "run": run, "proc": 0, "seq": i,
                        "t": t0 + 0.01 * i, "type": "metrics",
                        "counters": {"steps": i}})
            for i in range(start, start + count)]


def _read_timeline(out_dir: str) -> list[dict]:
    entries = []
    with open(os.path.join(out_dir, "timeline.jsonl")) as f:
        for line in f:
            if line.endswith("\n"):
                try:
                    entries.append(json.loads(line))
                except ValueError:
                    pass
    return entries


def run_chaos(work: str) -> dict:
    from dib_tpu.telemetry.fleet import timeline_digest

    roots = [os.path.join(work, name) for name in ("a", "b", "c")]
    for root in roots:
        os.makedirs(root, exist_ok=True)
    paths = {r: os.path.join(r, "events.jsonl") for r in roots}
    # phase 1: three writers with skewed clocks; source b ends torn
    counts = {roots[0]: 900, roots[1]: 700, roots[2]: 500}
    skew = {roots[0]: 1000.0, roots[1]: 950.0, roots[2]: 1100.0}
    torn = json.dumps({"v": 1, "run": "b", "seq": 10 ** 6, "t": 1.0,
                       "type": "metrics"})[:17]
    for root in roots:
        _write_lines(paths[root],
                     _records(os.path.basename(root), 0, counts[root],
                              skew[root]),
                     torn_tail=torn if root == roots[1] else None)
    out_dir = os.path.join(work, "merged")
    baseline_dir = os.path.join(work, "merged_baseline")

    _log("fleet-drill: starting durable aggregator, then SIGKILL")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    tail = subprocess.Popen(
        [sys.executable, "-m", "dib_tpu", "telemetry", "fleet", "tail",
         *roots, "--out", out_dir, "--refresh-s", "0.02"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    timeline = os.path.join(out_dir, "timeline.jsonl")
    deadline = time.time() + 60.0
    while time.time() < deadline:
        if os.path.exists(timeline) and os.path.getsize(timeline) > 0:
            break
        time.sleep(0.005)
    else:
        tail.kill()
        raise SystemExit("aggregator never started writing the timeline")
    tail.send_signal(signal.SIGKILL)
    tail.wait(timeout=30)
    killed_at = len(_read_timeline(out_dir))
    _log(f"fleet-drill: killed mid-merge at {killed_at} durable entries")

    # phase 2: the writers keep going while the aggregator is dead —
    # b's torn line completes, every source appends fresh records
    rest = json.dumps({"v": 1, "run": "b", "seq": 10 ** 6, "t": 1.0,
                       "type": "metrics"})[17:]
    _write_lines(paths[roots[1]], [], torn_tail=rest + "\n")
    extra = {roots[0]: 300, roots[1]: 200, roots[2]: 400}
    for root in roots:
        _write_lines(paths[root],
                     _records(os.path.basename(root), counts[root],
                              extra[root], skew[root] + 500.0))
    expected = {os.path.basename(r): counts[r] + extra[r] for r in roots}
    expected["b"] += 1  # the healed torn line

    _log("fleet-drill: re-attaching the aggregator (resume)")
    proc = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "telemetry", "fleet", "tail",
         *roots, "--out", out_dir, "--once"],
        env=env, capture_output=True, text=True, timeout=300)
    if proc.returncode != 0:
        raise SystemExit(f"resume failed rc={proc.returncode}:"
                         f"\n{proc.stdout}\n{proc.stderr}")

    # uninterrupted baseline merge of the same (final) sources
    proc = subprocess.run(
        [sys.executable, "-m", "dib_tpu", "telemetry", "fleet", "tail",
         *roots, "--out", baseline_dir, "--once"],
        env=env, capture_output=True, text=True, timeout=300)
    if proc.returncode != 0:
        raise SystemExit(f"baseline merge failed rc={proc.returncode}:"
                         f"\n{proc.stdout}\n{proc.stderr}")

    resumed = _read_timeline(out_dir)
    baseline = _read_timeline(baseline_dir)
    seen_keys = [(e["source"], e["n"]) for e in resumed]
    zero_duplicates = len(seen_keys) == len(set(seen_keys))
    per_source: dict[str, int] = {}
    for e in resumed:
        label = e["source"].split("/")[0].split("#")[0]
        per_source[label] = per_source.get(label, 0) + 1
    zero_lost = per_source == expected
    digest_resumed = timeline_digest(resumed)
    digest_baseline = timeline_digest(baseline)
    digest_identical = digest_resumed == digest_baseline
    ok = zero_duplicates and zero_lost and digest_identical \
        and 0 < killed_at < len(resumed)
    row = {
        "drill": "aggregator_kill_resume",
        "kind": "sigkill",
        "ok": bool(ok),
        "zero_duplicates": bool(zero_duplicates),
        "zero_lost": bool(zero_lost),
        "digest_identical": bool(digest_identical),
        "killed_at_entries": killed_at,
        "entries_total": len(resumed),
        "entries_per_source": per_source,
        "expected_per_source": expected,
        "torn_line_healed": per_source.get("b") == expected["b"],
        "digest": digest_resumed,
    }
    if not ok:
        raise SystemExit(f"chaos drill failed: {json.dumps(row, indent=1)}")
    return {
        "metric": "fleet_chaos_matrix",
        "unit": "drills",
        "value": 1,
        "quick": False,
        "matrix": [row],
    }


# ------------------------------------------------------------------ main
def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("mode", choices=("trace", "chaos"))
    parser.add_argument("--out", default=None,
                        help="Output record path (default FLEET_CPU.json "
                             "/ FLEET_CHAOS.json in the repo root).")
    parser.add_argument("--work-dir", default=None,
                        help="Working directory (default: a tempdir, "
                             "removed on success).")
    args = parser.parse_args(argv)
    default_out = ("FLEET_CPU.json" if args.mode == "trace"
                   else "FLEET_CHAOS.json")
    out = args.out or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        default_out)
    work = args.work_dir or tempfile.mkdtemp(prefix=f"fleet_{args.mode}_")
    try:
        record = (run_trace if args.mode == "trace" else run_chaos)(work)
    finally:
        if args.work_dir is None:
            shutil.rmtree(work, ignore_errors=True)
    record["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                          time.gmtime())
    with open(out, "w") as f:
        f.write(json.dumps(record, indent=1) + "\n")
    _log(f"fleet-drill: wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
