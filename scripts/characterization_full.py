"""Full MI-bound characterization sweep vs Monte Carlo (SURVEY §6 anchor).

The characterization notebook's complete protocol (cells 3-4): synthetic
channels of 1/2/4/6 binary input bits plus a continuous channel, swept over
7 Gaussian separation scales x evaluation batch sizes {64, 256, 1024}, each
cell's sandwich bounds compared against a 20k-sample Monte Carlo oracle.
Summarizes the regime behind the reference's "bounds separated by no more
than ~0.01 bits" claim: at B=1024 on channels whose MI is well below
log2(B), the sandwich must bracket the MC truth with a tight gap.

Writes ``CHARACTERIZATION_FULL.json`` and the residual plots.

Run: PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/characterization_full.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    from dib_tpu.workloads import run_characterization, save_characterization_plots

    t0 = time.time()
    results = run_characterization(seed=0)
    wall_s = time.time() - t0
    save_characterization_plots(results, "characterization_out")

    rows = []
    for r in results:
        rows.append({
            "input_bits": r.channel.input_bits,
            "scale": round(r.channel.scale, 4),
            "batch_size": r.batch_size,
            "mc_truth_bits": round(r.mc_truth, 4),
            "lower_bits": round(r.lower_mean, 4),
            "lower_std_bits": round(r.lower_std, 4),
            "upper_bits": round(r.upper_mean, 4),
            "upper_std_bits": round(r.upper_std, 4),
            "gap_bits": round(r.upper_mean - r.lower_mean, 4),
        })

    # The headline regime: B=1024, channel MI comfortably below log2(B).
    tight = [
        row for row in rows
        if row["batch_size"] == 1024 and 0.05 < row["mc_truth_bits"] < 6.0
    ]
    gaps = np.array([row["gap_bits"] for row in tight])
    # sandwich brackets the MC truth within the measured estimator noise
    # (3 sigma of the across-repeat std per bound — not a flat slack, so a
    # bias regression several times the claimed precision cannot hide)
    brackets = np.array([
        row["lower_bits"] - 3 * row["lower_std_bits"]
        <= row["mc_truth_bits"]
        <= row["upper_bits"] + 3 * row["upper_std_bits"]
        for row in tight
    ])
    report = {
        "metric": "mi_bound_characterization_median_gap_B1024",
        "value": round(float(np.median(gaps)), 4),
        "unit": "bits",
        "cells_total": len(rows),
        "cells_B1024_informative": len(tight),
        "bracketing_fraction": round(float(brackets.mean()), 4),
        "gap_bits_median": round(float(np.median(gaps)), 4),
        "gap_bits_p90": round(float(np.percentile(gaps, 90)), 4),
        "gap_bits_max": round(float(gaps.max()), 4),
        "wall_clock_s": round(wall_s, 1),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "cells": rows,
    }
    with open("CHARACTERIZATION_FULL.json", "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    print(json.dumps({k: v for k, v in report.items() if k != "cells"}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
