"""Measure the utilization CEILING of the north-star's matmul shapes on the
actual device (VERDICT round-4 item 3 / weak #5).

``docs/performance.md`` argues analytically that the paper shapes (batch 32
x 50 particles, model_dim 32 — contraction dims K=32 in the projections and
K=50/128 in the attention matmuls) leave the 128x128 MXU mostly idle BY
CONSTRUCTION. This script replaces the analytic claim with measurements:

  1. every distinct matmul of one sweep step, timed STANDALONE at the exact
     shapes the compiled step uses (8-replica batched, bfloat16), reporting
     achieved TFLOP/s per shape;
  2. reference points showing what the chip CAN do when shapes cooperate:
     a 4096^3 dense matmul (the MXU-friendly ceiling) and the same op mix
     with the contraction dims scaled up;
  3. remedy microbenchmarks: the fused QKV projection (one K=32 -> N=4608
     matmul vs three N=1536) and shared-weight row folding ([R*M, K] x one
     weight vs the R-batched matmul the per-replica sweep needs);
  4. the shape-implied ceiling: serial sum of best-case per-shape times ->
     the steps/s the matmuls alone would allow if everything else were free,
     vs the measured end-to-end steps/s from ``BENCH_CACHE.json``.

Run ALONE on the TPU box (ambient env):  python scripts/roofline.py
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# North-star shape constants (amorphous notebook cell 8 / bench.py)
R, B, P, F = 8, 32, 50, 12
D_MODEL, HEADS, KEY_DIM = 32, 12, 128
QKV = HEADS * KEY_DIM                       # 1536
FF = 128
ENC_H = 128
ENC_OUT = 2 * D_MODEL
HEAD_H = 256


def time_matmul(a_shape, b_shape, *, iters=200, dtype="bfloat16",
                batched=True) -> dict:
    """Achieved TFLOP/s of ``a @ b`` at these shapes, steady-state.

    The loop carries a data dependency (the operand is nudged by the
    previous product's mean) so XLA cannot hoist or elide the matmuls; the
    nudge's elementwise cost is O(M*K), negligible next to 2*M*K*N.
    """
    import jax
    import jax.numpy as jnp

    dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    k_a, k_b = jax.random.split(jax.random.key(0))
    a = jax.random.normal(k_a, a_shape, jnp.float32).astype(dt)
    b = jax.random.normal(k_b, b_shape, jnp.float32).astype(dt)
    contract = "...mk,...kn->...mn" if batched else "mk,kn->mn"

    def step(carry, _):
        x, y = carry
        out = jnp.einsum(contract, x, y)
        x = x * (1.0 + 1e-6 * out.mean().astype(x.dtype))
        return (x, y), None

    @jax.jit
    def run(a, b):
        (a, _), _ = jax.lax.scan(step, (a, b), None, length=iters)
        return a

    run(a, b).block_until_ready()            # compile + warm
    t0 = time.time()
    run(a, b).block_until_ready()
    dt_s = time.time() - t0

    m, k = a_shape[-2], a_shape[-1]
    n = b_shape[-1]
    batch = 1
    for s in a_shape[:-2]:
        batch *= s
    flops = 2.0 * batch * m * k * n * iters
    return {
        "a_shape": list(a_shape),
        "b_shape": list(b_shape),
        "dtype": dtype,
        "iters": iters,
        "wall_s": round(dt_s, 4),
        "achieved_tflops": round(flops / dt_s / 1e12, 3),
        "flops_per_call": flops / iters,
    }


def main() -> int:
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--report", default="ROOFLINE.json")
    parser.add_argument("--iters", type=int, default=200)
    args = parser.parse_args()

    from dib_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()
    import jax

    devices = jax.devices()
    device_kind = devices[0].device_kind
    print(f"devices: {devices}", file=sys.stderr)

    it = args.iters
    M = B * P                                  # rows per replica, 1600

    shapes = {}
    t_all = time.time()
    # --- 1. the sweep step's own matmuls (R-batched: per-replica weights) ---
    shapes["encoder_l1_K12"] = time_matmul((R, M, F), (R, F, ENC_H), iters=it)
    shapes["encoder_l2_K128"] = time_matmul((R, M, ENC_H), (R, ENC_H, ENC_H), iters=it)
    shapes["encoder_out_K128"] = time_matmul((R, M, ENC_H), (R, ENC_H, ENC_OUT), iters=it)
    shapes["qkv_proj_K32_N1536"] = time_matmul((R, M, D_MODEL), (R, D_MODEL, QKV), iters=it)
    shapes["attn_scores_K128"] = time_matmul(
        (R * B * HEADS, P, KEY_DIM), (R * B * HEADS, KEY_DIM, P), iters=it)
    shapes["attn_values_K50"] = time_matmul(
        (R * B * HEADS, P, P), (R * B * HEADS, P, KEY_DIM), iters=it)
    shapes["out_proj_K1536"] = time_matmul((R, M, QKV), (R, QKV, D_MODEL), iters=it)
    shapes["ff1_K32"] = time_matmul((R, M, D_MODEL), (R, D_MODEL, FF), iters=it)
    shapes["ff2_K128"] = time_matmul((R, M, FF), (R, FF, D_MODEL), iters=it)
    shapes["head_K32"] = time_matmul((R, B, D_MODEL), (R, D_MODEL, HEAD_H), iters=it)

    # --- 2. what the chip can do when shapes cooperate ---
    shapes["ceiling_4096cubed"] = time_matmul(
        (4096, 4096), (4096, 4096), iters=20, batched=False)
    shapes["scaled_K512_N1536"] = time_matmul((R, M, 512), (R, 512, QKV), iters=it)

    # --- 3. remedies ---
    shapes["remedy_fused_qkv_K32_N4608"] = time_matmul(
        (R, M, D_MODEL), (R, D_MODEL, 3 * QKV), iters=it)
    shapes["remedy_shared_weight_rows_K32_N1536"] = time_matmul(
        (R * M, D_MODEL), (D_MODEL, QKV), iters=it, batched=False)

    # --- 4. shape-implied ceiling vs the measured end-to-end number ---
    # Serial best case: one step's matmuls (fwd + ~2x bwd), each running at
    # its measured standalone throughput, nothing else on the clock.
    per_step = {
        "encoder_l1_K12": 1, "encoder_l2_K128": 1, "encoder_out_K128": 1,
        "qkv_proj_K32_N1536": 3 * 6, "attn_scores_K128": 6,
        "attn_values_K50": 6, "out_proj_K1536": 6,
        "ff1_K32": 6, "ff2_K128": 6,
        "head_K32": 1,
    }
    serial_s = 0.0
    total_flops = 0.0
    for name, count in per_step.items():
        entry = shapes[name]
        call_s = entry["wall_s"] / entry["iters"]
        serial_s += 3.0 * count * call_s              # fwd + 2x bwd
        total_flops += 3.0 * count * entry["flops_per_call"]
    ceiling_replica_steps_per_s = R / serial_s
    cached = None
    try:
        with open(os.path.join(REPO, "BENCH_CACHE.json")) as f:
            cached = json.load(f)
    except (OSError, json.JSONDecodeError):
        pass
    measured = cached.get("steps_per_s") if cached else None

    # shared per-backend capability table (telemetry/xla_stats.py): per-shape
    # achieved TFLOP/s are readable against the chip's bf16 peak in-place
    from dib_tpu.telemetry.xla_stats import backend_peaks

    report = {
        "metric": "northstar_shape_matmul_ceiling",
        "value": round(ceiling_replica_steps_per_s, 1),
        "unit": "sweep steps/s (matmuls alone, measured per-shape ceilings)",
        "backend_peaks": backend_peaks(device_kind),
        "measured_end_to_end_steps_per_s": measured,
        "fraction_of_shape_ceiling": round(measured / ceiling_replica_steps_per_s, 3)
        if measured else None,
        "device_kind": device_kind,
        "config": {"replicas": R, "batch": B, "particles": P,
                   "model_dim": D_MODEL, "heads": HEADS, "key_dim": KEY_DIM},
        "shapes": shapes,
        "remedy_summary": {
            "fused_qkv_tflops_vs_split": [
                shapes["remedy_fused_qkv_K32_N4608"]["achieved_tflops"],
                shapes["qkv_proj_K32_N1536"]["achieved_tflops"],
            ],
            "shared_weight_rows_tflops_vs_batched": [
                shapes["remedy_shared_weight_rows_K32_N1536"]["achieved_tflops"],
                shapes["qkv_proj_K32_N1536"]["achieved_tflops"],
            ],
        },
        "note": (
            "Per-shape standalone throughput of every matmul in one sweep "
            "step at the exact compiled shapes (8-replica batched, bf16), "
            "plus cooperative-shape references and remedy variants. The "
            "shape-implied ceiling assumes fwd+2x-bwd matmuls run serially "
            "at their standalone rates with everything else free; the "
            "measured end-to-end steps/s (BENCH_CACHE.json) includes "
            "sampling, KL, LayerNorms, validation, optimizer and history "
            "writes."
        ),
        "wall_clock_s": round(time.time() - t_all, 1),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    with open(args.report, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    print(json.dumps({k: report[k] for k in
                      ("value", "measured_end_to_end_steps_per_s",
                       "fraction_of_shape_ceiling")}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
